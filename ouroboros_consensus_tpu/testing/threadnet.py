"""ThreadNet: whole-network simulation in one deterministic process.

Reference: `runThreadNetwork`
(diffusion-testlib/Test/ThreadNet/Network.hs:276) — N full nodes (real
NodeKernel, real ChainDB on disk, real protocol crypto) as graph
vertices, every topology edge a real ChainSync + BlockFetch client/server
pair over channels with per-message delay, all driven by a virtual clock
for a fixed number of slots. Properties checked by the tests mirror
`prop_general` (ThreadNet/General.hs:403): common prefix, chain growth,
all nodes converge.

Hardening knobs mirroring the reference harness:
  * join plans (`NodeJoinPlan`): a node's forging loop and its protocol
    edges only start at its join slot.
  * restarts (`ThreadNet/Util/NodeRestarts.hs`): at the scheduled slot
    the node's tasks are killed mid-run, its ChainDB closed and reopened
    WITH full revalidation (the crashed-marker policy), and fresh
    protocol edges spawned.
  * rekeying (`Util/Rekeying.hs`): a restart can hand the node a fresh
    KES hot key + ocert (counter+1) via NodeKernel.rekey.
  * `expected_chain_length` — the reference-simulator check (Ref/PBFT.hs
    analog): for a deterministic leader layout (single forger, f=1) the
    exact final chain length is predicted from the join/restart plan.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from fractions import Fraction

from ..ledger.extended import ExtLedger
from ..ledger.mock import MockConfig, MockLedger
from ..miniprotocol import blockfetch, chainsync, txsubmission
from ..miniprotocol.rethrow import peer_guard
from ..miniprotocol.chainsync import Candidate
from ..node.kernel import NodeKernel, SlotClock
from ..protocol import praos
from ..protocol.instances import PraosProtocol
from ..storage.open import open_chaindb
from ..testing import fixtures
from ..utils.sim import Channel, Sim, Sleep

# the COMMON genesis UTxO every node starts from: txgen spends these
# outputs one by one, so count and amount are shared constants
N_GENESIS_OUTPUTS = 16
GENESIS_AMOUNT = 100


@dataclass
class ThreadNetConfig:
    n_nodes: int = 3
    n_slots: int = 30
    k: int = 10
    slot_length: float = 1.0
    msg_delay: float = 0.05
    kes_depth: int = 3
    active_slot_coeff: Fraction = Fraction(1, 2)
    epoch_length: int = 50
    topology: list[tuple[int, int]] | None = None  # directed edges; None=full
    async_chaindb: bool = False  # decoupled add-block queue + background GC
    use_device_batch: bool = False  # candidate validation via fused kernel
    forgers: list[int] | None = None  # node indices that forge; None = all
    join_plan: dict[int, int] | None = None  # node -> first slot it's up
    restarts: list[tuple[int, int]] | None = None  # (slot, node) kill+reopen
    rekey_on_restart: bool = False  # fresh KES + ocert counter+1 at restart
    tx_submission: bool = False  # run TxSubmission2 on every edge
    in_future_check: bool = False  # CheckInFuture vs the sim clock
    # ThreadNet/TxGen.hs analog: (slot, node, tx_bytes) injected into
    # that node's mempool at the slot's start
    tx_injections: list[tuple[int, int, bytes]] | None = None
    # io-sim schedule exploration (SURVEY §5.2): a seed permutes
    # same-time task wakeups deterministically; None = FIFO
    seed: int | None = None
    # TxGen (ThreadNet/TxGen.hs analog): every N slots, a rotating node
    # submits a fresh valid tx spending a distinct genesis output
    tx_gen_every: int | None = None
    # 2-era HFC net (the reference's A→B model test, diffusion
    # test/consensus-test HardFork/Combinator.hs): era A (Praos, these
    # params) hard-forks into era B (Praos, doubled epoch length) at
    # this epoch; every node runs the composite protocol/ledger
    hard_fork_at_epoch: int | None = None
    # era B runs the REAL Shelley STS ledger (same epoch length as A so
    # Shelley's slot/epoch arithmetic aligns with the boundary): the
    # translation carries the mock-era UTxO and seals genesis staking
    # that delegates every genesis output's stake round-robin to the
    # forger pools (the DualByron-test shape on the Shelley side)
    hf_shelley_era: bool = False
    # third era: the Shelley state translates again into the MARY-class
    # ledger (multi-asset values, minting, validity intervals) at this
    # epoch — a 3-era net crossing two GENUINE rule changes (requires
    # hf_shelley_era)
    hf_mary_at_epoch: int | None = None
    # fourth era: Mary translates into the ALONZO-class ledger (phase-2
    # script witnesses, ExUnits, collateral, two-phase IsValid) at this
    # epoch — the net crosses into the script era LIVE (requires
    # hf_mary_at_epoch)
    hf_alonzo_at_epoch: int | None = None


@dataclass
class ThreadNetResult:
    nodes: list[NodeKernel]
    sim: Sim
    chains: list[list] = field(default_factory=list)  # per node: Block list
    n_restarts: int = 0

    def chain_hashes(self, i: int) -> list[bytes]:
        return [b.hash_ for b in self.chains[i]]


def _delayed(dt: float, gen):
    """Spawn-later wrapper: sleep dt (virtual), then run `gen`."""
    if dt > 0:
        yield Sleep(dt)
    yield from gen





class _Net:
    """Mutable network state during a run (vertex/edge respawns)."""

    def __init__(self, base_dir: str, cfg: ThreadNetConfig, sim: Sim):
        self.base_dir = base_dir
        self.cfg = cfg
        self.sim = sim
        self.params = praos.PraosParams(
            slots_per_kes_period=100,
            max_kes_evolutions=62,
            security_param=cfg.k,
            active_slot_coeff=cfg.active_slot_coeff,
            epoch_length=cfg.epoch_length,
            kes_depth=cfg.kes_depth,
        )
        self.pools = [
            fixtures.make_pool(i, kes_depth=cfg.kes_depth)
            for i in range(cfg.n_nodes)
        ]
        self.lview = fixtures.make_ledger_view(self.pools)
        self.nodes: list[NodeKernel] = []
        self.node_tasks: dict[int, list] = {}  # node -> sim Tasks to kill
        # node -> [(chain_db, follower)] registered by its edges; closed
        # when either endpoint restarts (a killed server must not leak
        # its follower on the surviving peer's ChainDB)
        self.node_followers: dict[int, list] = {}
        self.n_restarts = 0
        forgers = cfg.forgers if cfg.forgers is not None else list(range(cfg.n_nodes))
        self.forgers = set(forgers)
        self.edges = cfg.topology
        if self.edges is None:
            self.edges = [
                (i, j)
                for i in range(cfg.n_nodes)
                for j in range(cfg.n_nodes)
                if i != j
            ]
        self.join = cfg.join_plan or {}

    # -- vertices -----------------------------------------------------------

    def _shelley_era_b(self, params_b):
        """Era B over the REAL Shelley STS ledger: the boundary
        translation carries the mock UTxO and seals genesis staking
        that delegates each genesis output round-robin to the forger
        pools — so era-B elections run on ledger-derived stake."""
        from fractions import Fraction as F

        from ..hardfork.combinator import Era
        from ..ledger import shelley as sh
        from ..protocol.views import hash_key, hash_vrf_vk

        import zlib

        cfg = self.cfg
        forger_pools = [self.pools[i] for i in sorted(self.forgers)]
        if not forger_pools:
            raise ValueError(
                "hf_shelley_era needs at least one forger: era-B "
                "elections run on stake delegated to the forger pools"
            )
        # EVERY address keeps stake across the boundary: a deterministic
        # address->credential map (not just the pristine genesis-k
        # addresses — the mock-era TxGen re-addresses outputs, and spent
        # stake silently vanishing would stall era B)
        cred_list = [b"tn-cred-%03d" % k for k in range(N_GENESIS_OUTPUTS)]

        def stake_of(addr: bytes) -> bytes:
            return cred_list[zlib.crc32(addr) % len(cred_list)]

        initial_pools = tuple(
            sh.PoolParams(
                pool_id=hash_key(p.vk_cold),
                vrf_hash=hash_vrf_vk(p.vrf_vk),
                pledge=0, cost=0, margin=F(0),
                reward_cred=cred_list[i % len(cred_list)], owners=(),
            )
            for i, p in enumerate(forger_pools)
        )
        initial_delegations = tuple(
            (cred, hash_key(forger_pools[k % len(forger_pools)].vk_cold))
            for k, cred in enumerate(cred_list)
        )
        genesis = sh.ShelleyGenesis(
            pparams=sh.PParams(min_fee_a=0, min_fee_b=0),
            epoch_length=params_b.epoch_length,
            stability_window=params_b.stability_window,
            max_supply=N_GENESIS_OUTPUTS * GENESIS_AMOUNT * 100,
        )
        ledger = sh.ShelleyLedger(genesis)
        boundary_slot = cfg.hard_fork_at_epoch * self.params.epoch_length

        return Era(
            "shelleyB",
            PraosProtocol(params_b, use_device_batch=cfg.use_device_batch),
            ledger=ledger,
            translate_ledger_state=lambda st: ledger.translate_from_utxo_ledger(
                st, at_slot=boundary_slot,
                stake_of=stake_of,
                initial_pools=initial_pools,
                initial_delegations=initial_delegations,
            ),
        )

    def _hf_pieces(self):
        """Protocol+ledger+codec+forge for the 2-era composite."""
        import dataclasses
        import functools

        from ..block.forge import forge_block as praos_forge
        from ..block.praos_block import Block as PraosBlock
        from ..hardfork.combinator import (
            Era,
            HardForkBlock,
            HardForkLedger,
            HardForkProtocol,
            decode_block,
        )
        from ..hardfork.history import EraParams as HEraParams
        from ..hardfork.history import summarize
        from fractions import Fraction as F

        cfg = self.cfg
        params_a = self.params
        if cfg.hf_shelley_era:
            # the era CHANGE is the ledger itself — epoch arithmetic
            # stays aligned (Shelley derives epochs from global slots)
            params_b = params_a
        else:
            # era B: doubled epoch length (a REAL parameter change
            # across the boundary, like the reference's A→B test)
            params_b = dataclasses.replace(
                self.params, epoch_length=2 * self.params.epoch_length
            )
        era_params = [
            HEraParams(params_a.epoch_length, F(1)),
            HEraParams(params_b.epoch_length, F(1)),
        ]
        bounds: list = [cfg.hard_fork_at_epoch, None]
        if cfg.hf_mary_at_epoch is not None:
            if not cfg.hf_shelley_era:
                raise ValueError("hf_mary_at_epoch requires hf_shelley_era")
            era_params.append(HEraParams(params_b.epoch_length, F(1)))
            bounds[-1] = cfg.hf_mary_at_epoch
            bounds.append(None)
        if cfg.hf_alonzo_at_epoch is not None:
            if cfg.hf_mary_at_epoch is None:
                raise ValueError("hf_alonzo_at_epoch requires hf_mary_at_epoch")
            era_params.append(HEraParams(params_b.epoch_length, F(1)))
            bounds[-1] = cfg.hf_alonzo_at_epoch
            bounds.append(None)
        summary = summarize(F(0), era_params, bounds)
        if cfg.hf_shelley_era:
            era_b = self._shelley_era_b(params_b)
        else:
            era_b = Era(
                "eraB",
                PraosProtocol(params_b, use_device_batch=cfg.use_device_batch),
                ledger=MockLedger(
                    MockConfig(self.lview, params_b.stability_window)
                ),
            )
        eras = [
            Era(
                "eraA",
                PraosProtocol(params_a, use_device_batch=cfg.use_device_batch),
                ledger=MockLedger(
                    MockConfig(self.lview, params_a.stability_window)
                ),
            ),
            era_b,
        ]
        if cfg.hf_mary_at_epoch is not None:
            from ..ledger import mary as mary_mod

            mary_ledger = mary_mod.MaryLedger(era_b.ledger.genesis)
            eras.append(Era(
                "maryC",
                PraosProtocol(params_b, use_device_batch=cfg.use_device_batch),
                ledger=mary_ledger,
                # Shelley→Mary: Coin widens to MaryValue, rules change
                # (CanHardFork.hs:273 Shelley-family step)
                translate_ledger_state=mary_ledger.translate_from_shelley,
                translate_tx=mary_mod.translate_tx_from_shelley,
            ))
        if cfg.hf_alonzo_at_epoch is not None:
            from ..ledger import alonzo as alonzo_mod

            alonzo_ledger = alonzo_mod.AlonzoLedger(era_b.ledger.genesis)
            eras.append(Era(
                "alonzoD",
                PraosProtocol(params_b, use_device_batch=cfg.use_device_batch),
                ledger=alonzo_ledger,
                # Mary→Alonzo: pparams widen with script economics; the
                # net crosses into the phase-2 script era LIVE
                translate_ledger_state=alonzo_ledger.translate_from_mary,
                translate_tx=alonzo_mod.translate_tx_from_mary,
            ))
        protocol = HardForkProtocol(eras, summary)
        ledger = HardForkLedger(eras, summary)
        codec = functools.partial(
            decode_block,
            era_decoders=[PraosBlock.from_bytes] * len(eras),
        )

        def forge_fn(node, slot, block_no, prev_hash, ticked, is_leader, txs):
            era = protocol.era_of_slot(slot)
            inner_params = params_a if era == 0 else params_b
            blk = praos_forge(
                inner_params,
                node.pool,
                slot=slot,
                block_no=block_no,
                prev_hash=prev_hash,
                epoch_nonce=ticked.inner.state.epoch_nonce,
                txs=txs,
                is_leader=is_leader,
                hotkey=node.hotkey,
                ocert=node._ocert,
            )
            return HardForkBlock(era, blk)

        def check_integrity(raw: bytes) -> bool:
            try:
                return codec(raw).check_integrity()
            except Exception:
                return False

        return protocol, ledger, codec, forge_fn, check_integrity

    def _open_db(self, i: int, validate_all: bool = False):
        """-> (db, protocol, ledger, forge_fn|None)."""
        if self.cfg.hard_fork_at_epoch is not None:
            return self._open_db_hf(i, validate_all)
        ledger = MockLedger(MockConfig(self.lview, self.params.stability_window))
        protocol = PraosProtocol(
            self.params, use_device_batch=self.cfg.use_device_batch
        )
        ext = ExtLedger(ledger, protocol)
        # a COMMON genesis UTxO: generated txs validate on every node
        # regardless of where they enter the network
        genesis = ext.genesis(
            ledger.genesis_state(
                [(b"genesis-%d" % k, GENESIS_AMOUNT)
                 for k in range(N_GENESIS_OUTPUTS)]
            )
        )
        cif = None
        if self.cfg.in_future_check:
            from ..block.infuture import CheckInFuture

            cif = CheckInFuture(
                now=lambda: self.sim.now, slot_length=self.cfg.slot_length
            )
        db = open_chaindb(
            os.path.join(self.base_dir, f"node{i}"), ext, genesis, self.cfg.k,
            validate_all=validate_all, check_in_future=cif,
        )
        return db, protocol, ledger, None

    def _open_db_hf(self, i: int, validate_all: bool = False):
        import dataclasses

        protocol, ledger, codec, forge_fn, check_integrity = self._hf_pieces()
        ext = ExtLedger(ledger, protocol)
        inner_genesis = ledger.eras[0].ledger.genesis_state(
            [(b"genesis-%d" % k, GENESIS_AMOUNT)
             for k in range(N_GENESIS_OUTPUTS)]
        )
        genesis = ext.genesis(ledger.genesis_state(inner_genesis))
        # seed the era-0 Praos epoch nonce inside the telescope
        from ..hardfork.combinator import HFState

        hs = genesis.header_state
        inner0 = dataclasses.replace(
            hs.chain_dep_state.inner, epoch_nonce=b"\x22" * 32
        )
        genesis = dataclasses.replace(
            genesis,
            header_state=dataclasses.replace(
                hs, chain_dep_state=HFState(0, inner0)
            ),
        )
        db = open_chaindb(
            os.path.join(self.base_dir, f"node{i}"), ext, genesis, self.cfg.k,
            validate_all=validate_all, decode_block=codec,
            check_integrity=check_integrity,
        )
        return db, protocol, ledger, forge_fn

    def make_node(self, i: int) -> NodeKernel:
        db, protocol, ledger, forge_fn = self._open_db(i)
        node = NodeKernel(
            f"node{i}", db, protocol, ledger,
            pool=self.pools[i] if i in self.forgers else None,
            clock=SlotClock(self.cfg.slot_length),
            forge_fn=forge_fn,
        )
        self._wire_chaindb(i, node)
        return node

    def _wire_chaindb(self, i: int, node: NodeKernel) -> None:
        if self.cfg.async_chaindb:
            runners = node.chain_db.start_decoupled(self.sim)
            self.node_tasks.setdefault(i, []).append(
                self.sim.spawn(runners[0], f"addblock{i}")
            )
            self.node_tasks[i].append(self.sim.spawn(runners[1], f"background{i}"))
        else:
            # followers still fire wakeup events through the sim so the
            # ChainSync server blocks instead of polling
            node.chain_db.runtime = self.sim

    def spawn_vertex(self, i: int, start_slot: int) -> None:
        node = self.nodes[i]
        if node.pool is not None:
            dt = max(0.0, node.clock.start_of(start_slot) - self.sim.now)
            self.node_tasks.setdefault(i, []).append(
                self.sim.spawn(
                    _delayed(dt, node.forging_loop(self.cfg.n_slots, start_slot)),
                    f"forge{i}",
                )
            )

    # -- edges --------------------------------------------------------------

    def spawn_edge(self, i: int, j: int, dt: float = 0.0) -> None:
        """Edge (i, j): node j syncs FROM node i (i serves, j consumes)."""
        cfg = self.cfg
        server_node, client_node = self.nodes[i], self.nodes[j]
        cand = Candidate()
        client_node.candidates[f"node{i}"] = cand
        cs_req = Channel(delay=cfg.msg_delay, name=f"cs-req-{i}-{j}")
        cs_rsp = Channel(delay=cfg.msg_delay, name=f"cs-rsp-{i}-{j}")
        bf_req = Channel(delay=cfg.msg_delay, name=f"bf-req-{i}-{j}")
        bf_rsp = Channel(delay=cfg.msg_delay, name=f"bf-rsp-{i}-{j}")
        cs_follower = server_node.chain_db.new_follower(include_tentative=True)
        for end in (i, j):
            self.node_followers.setdefault(end, []).append(
                (server_node.chain_db, cs_follower)
            )
        pairs = [
            (i, chainsync.server(server_node.chain_db, cs_req, cs_rsp,
                                 follower=cs_follower),
             f"cs-server-{i}->{j}"),
            (j, chainsync.client(client_node, f"node{i}", cs_rsp, cs_req, cand),
             f"cs-client-{i}->{j}"),
            (i, blockfetch.server(server_node.chain_db, bf_req, bf_rsp),
             f"bf-server-{i}->{j}"),
            (j, blockfetch.client(client_node, f"node{i}", bf_rsp, bf_req, cand),
             f"bf-client-{i}->{j}"),
        ]
        if cfg.tx_submission:
            ts_req = Channel(delay=cfg.msg_delay, name=f"ts-req-{i}-{j}")
            ts_rsp = Channel(delay=cfg.msg_delay, name=f"ts-rsp-{i}-{j}")
            pairs.append(
                (i, txsubmission.outbound(server_node, ts_req, ts_rsp),
                 f"ts-outbound-{i}->{j}")
            )
            pairs.append(
                (j, txsubmission.inbound(client_node, f"node{i}", ts_rsp, ts_req),
                 f"ts-inbound-{i}->{j}")
            )
        # one peer violation tears down the WHOLE edge (all of its
        # protocol tasks + the candidate + the server-side follower) —
        # the connection-level disconnect of RethrowPolicy
        edge_tasks: list = []

        def disconnect_edge():
            for t in edge_tasks:
                t.alive = False
                try:
                    t.gen.close()
                except Exception:
                    pass
            cs_follower.close()
            client_node.candidates.pop(f"node{i}", None)

        for owner, gen, name in pairs:
            task = self.sim.spawn(
                _delayed(
                    dt,
                    peer_guard(gen, name, client_node.trace, disconnect_edge),
                ),
                name,
            )
            edge_tasks.append(task)
            # edge tasks die with EITHER endpoint's restart
            self.node_tasks.setdefault(i, []).append(task)
            self.node_tasks.setdefault(j, []).append(task)

    # -- restarts (NodeRestarts.hs) -----------------------------------------

    def restart_node(self, i: int, slot: int) -> None:
        """Kill the node's tasks, reopen its ChainDB with FULL
        revalidation (crash-marker policy), optionally rekey, respawn."""
        for t in self.node_tasks.get(i, []):
            t.alive = False
        self.node_tasks[i] = []
        for (db_, f) in self.node_followers.get(i, []):
            f.close()  # idempotent — the pair is registered at both ends
        self.node_followers[i] = []
        old = self.nodes[i]
        old.chain_db.close()
        db, protocol, ledger, forge_fn = self._open_db(i, validate_all=True)
        pool = self.pools[i] if i in self.forgers else None
        carry = pool is not None and not self.cfg.rekey_on_restart
        node = NodeKernel(
            f"node{i}", db, protocol, ledger,
            pool=pool,
            clock=SlotClock(self.cfg.slot_length),
            # carry the EVOLVED hot key + certificate across the restart
            # (forward security: never re-derive from the root seed)
            hotkey=old.hotkey if carry else None,
            ocert=old._ocert if carry else None,
            ocert_counter=old._ocert_counter if carry else 0,
            forge_fn=forge_fn,
        )
        if pool is not None and self.cfg.rekey_on_restart:
            node._ocert_counter = old._ocert_counter
            node.rekey(slot)
        self._wire_chaindb(i, node)
        self.nodes[i] = node
        self.n_restarts += 1
        # resume forging from the NEXT slot boundary; re-establish edges.
        # Edges to peers that have not yet joined were killed with this
        # node's tasks: respawn them with their remaining join delay so
        # the late joiner still gets connected.
        self.spawn_vertex(i, slot + 1)
        for (a, b) in self.edges:
            if i in (a, b):
                other = b if a == i else a
                other_join = self.join.get(other, 0)
                dt = max(
                    0.0,
                    other_join * self.cfg.slot_length - self.sim.now,
                )
                self.spawn_edge(a, b, dt)

    def restart_controller(self, restarts):
        last = 0.0
        for slot, node_ix in sorted(restarts):
            # restart mid-slot so the node misses that slot's forging
            at = slot * self.cfg.slot_length + 0.5 * self.cfg.slot_length
            if at > last:
                yield Sleep(at - last)
                last = at
            self.restart_node(node_ix, slot)


def run_thread_network(base_dir: str, cfg: ThreadNetConfig) -> ThreadNetResult:
    sim = Sim(seed=cfg.seed)
    net = _Net(base_dir, cfg, sim)
    for i in range(cfg.n_nodes):
        net.nodes.append(net.make_node(i))
    for i in range(cfg.n_nodes):
        net.spawn_vertex(i, net.join.get(i, 0))
    for (i, j) in net.edges:
        # an edge exists once BOTH endpoints have joined
        dt = max(net.join.get(i, 0), net.join.get(j, 0)) * cfg.slot_length
        net.spawn_edge(i, j, dt)
    if cfg.restarts:
        sim.spawn(net.restart_controller(cfg.restarts), "restart-controller")
    if cfg.tx_gen_every:
        from ..ledger.mock import encode_tx

        def txgen():
            from ..ledger.abstract import LedgerError
            from ..mempool import MempoolFull

            k = 0
            while True:
                yield Sleep(cfg.tx_gen_every * cfg.slot_length)
                if k >= N_GENESIS_OUTPUTS:
                    return  # genesis outputs exhausted
                node_ix = k % cfg.n_nodes
                tx = encode_tx(
                    [(bytes(32), k)],
                    [(b"paid-%d" % k, GENESIS_AMOUNT)],
                )
                try:
                    net.nodes[node_ix].mempool.add_tx(tx)
                except (LedgerError, MempoolFull) as e:
                    # a duplicate spend after tx diffusion raced ahead is
                    # fine, as is a mock-era tx offered to a node whose
                    # mempool already anchors past a hard fork (another
                    # era's rules reject it — GenTx era mismatch)
                    net.nodes[node_ix].trace(f"txgen: rejected: {e!r}")
                k += 1

        sim.spawn(txgen(), "tx-gen")
    if cfg.tx_injections:
        def injector():
            last = 0.0
            for slot, node_ix, tx_bytes in sorted(cfg.tx_injections):
                at = slot * cfg.slot_length
                if at > last:
                    yield Sleep(at - last)
                    last = at
                net.nodes[node_ix].mempool.add_tx(tx_bytes)
        sim.spawn(injector(), "tx-injector")

    # run: all slots + 2s of virtual drain time for in-flight messages
    sim.run(until=cfg.n_slots * cfg.slot_length + 2.0)

    res = ThreadNetResult(net.nodes, sim, n_restarts=net.n_restarts)
    for node in net.nodes:
        res.chains.append(list(node.chain_db.stream_all()))
    return res


# -- properties (prop_general, ThreadNet/General.hs:403) ---------------------


def check_common_prefix(res: ThreadNetResult, k: int) -> None:
    """All pairs of final chains fork at most k blocks from either tip."""
    for i in range(len(res.chains)):
        for j in range(i + 1, len(res.chains)):
            a, b = res.chain_hashes(i), res.chain_hashes(j)
            common = 0
            for x, y in zip(a, b):
                if x != y:
                    break
                common += 1
            assert len(a) - common <= k and len(b) - common <= k, (
                f"common-prefix violated between node{i} and node{j}: "
                f"common={common}, lens=({len(a)}, {len(b)})"
            )


def check_chain_growth(res: ThreadNetResult, cfg: ThreadNetConfig) -> None:
    """Chain growth against the PURE reference model (Ref/PBFT.hs role,
    General.hs:403): where the model applies (single epoch, full
    within-slot diffusion, no restarts) the adopted chain length must
    EQUAL the model's slot-by-slot prediction — a 2x forging regression
    is caught immediately. Outside the model a conservative fraction of
    active slots still bounds growth from below (the round-4 ÷4
    fallback)."""
    from . import refmodel

    min_len = min(len(c) for c in res.chains)
    if refmodel.mock_net_model_applies(cfg):
        expect = refmodel.expected_mock_net_length(cfg)
        max_len = max(len(c) for c in res.chains)
        assert min_len == max_len == expect, (
            f"model mismatch: chains [{min_len}, {max_len}] blocks, "
            f"model predicts exactly {expect}"
        )
        return
    # fallback: loose lower bound
    expect = int(cfg.n_slots * float(cfg.active_slot_coeff) / 4)
    assert min_len >= expect, f"chain too short: {min_len} < {expect}"


def expected_chain_length(cfg: ThreadNetConfig) -> int:
    """Reference simulator (the Ref/PBFT.hs role) for the DETERMINISTIC
    layout: a single forger with f=1 forges in every slot it is up —
    all slots except those before its join slot and the slot of each of
    its restarts (the restart lands mid-slot, killing that slot's
    block... which was forged at slot START, so only slots whose forging
    happened while the node was down are lost: none after a clean
    mid-slot restart). Requires cfg.forgers == [i] and f == 1."""
    assert cfg.forgers is not None and len(cfg.forgers) == 1
    assert cfg.active_slot_coeff == Fraction(1)
    forger = cfg.forgers[0]
    join = (cfg.join_plan or {}).get(forger, 0)
    # a MID-slot restart loses no slots: the slot's block was forged at
    # the slot START and survives on disk; forging resumes at slot+1
    return cfg.n_slots - join
