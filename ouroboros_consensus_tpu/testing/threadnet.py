"""ThreadNet: whole-network simulation in one deterministic process.

Reference: `runThreadNetwork`
(diffusion-testlib/Test/ThreadNet/Network.hs:276) — N full nodes (real
NodeKernel, real ChainDB on disk, real protocol crypto) as graph
vertices, every topology edge a real ChainSync + BlockFetch client/server
pair over channels with per-message delay, all driven by a virtual clock
for a fixed number of slots. Properties checked by the tests mirror
`prop_general` (ThreadNet/General.hs:403): common prefix, chain growth,
all nodes converge.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from fractions import Fraction

from ..ledger.extended import ExtLedger
from ..ledger.mock import MockConfig, MockLedger
from ..miniprotocol import blockfetch, chainsync
from ..miniprotocol.chainsync import Candidate
from ..node.kernel import NodeKernel, SlotClock
from ..protocol import praos
from ..protocol.instances import PraosProtocol
from ..storage.open import open_chaindb
from ..testing import fixtures
from ..utils.sim import Channel, Sim


@dataclass
class ThreadNetConfig:
    n_nodes: int = 3
    n_slots: int = 30
    k: int = 10
    slot_length: float = 1.0
    msg_delay: float = 0.05
    kes_depth: int = 3
    active_slot_coeff: Fraction = Fraction(1, 2)
    epoch_length: int = 50
    topology: list[tuple[int, int]] | None = None  # directed edges; None=full
    async_chaindb: bool = False  # decoupled add-block queue + background GC
    use_device_batch: bool = False  # candidate validation via fused kernel


@dataclass
class ThreadNetResult:
    nodes: list[NodeKernel]
    sim: Sim
    chains: list[list] = field(default_factory=list)  # per node: Block list

    def chain_hashes(self, i: int) -> list[bytes]:
        return [b.hash_ for b in self.chains[i]]


def run_thread_network(base_dir: str, cfg: ThreadNetConfig) -> ThreadNetResult:
    params = praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=cfg.k,
        active_slot_coeff=cfg.active_slot_coeff,
        epoch_length=cfg.epoch_length,
        kes_depth=cfg.kes_depth,
    )
    pools = [fixtures.make_pool(i, kes_depth=cfg.kes_depth) for i in range(cfg.n_nodes)]
    lview = fixtures.make_ledger_view(pools)

    nodes: list[NodeKernel] = []
    for i in range(cfg.n_nodes):
        ledger = MockLedger(MockConfig(lview, params.stability_window))
        protocol = PraosProtocol(params, use_device_batch=cfg.use_device_batch)
        ext = ExtLedger(ledger, protocol)
        genesis = ext.genesis(ledger.genesis_state([(b"addr-%d" % i, 100)]))
        db = open_chaindb(
            os.path.join(base_dir, f"node{i}"), ext, genesis, cfg.k
        )
        nodes.append(
            NodeKernel(
                f"node{i}",
                db,
                protocol,
                ledger,
                pool=pools[i],
                clock=SlotClock(cfg.slot_length),
            )
        )

    edges = cfg.topology
    if edges is None:
        edges = [
            (i, j)
            for i in range(cfg.n_nodes)
            for j in range(cfg.n_nodes)
            if i != j
        ]

    sim = Sim()
    for i, node in enumerate(nodes):
        if cfg.async_chaindb:
            runners = node.chain_db.start_decoupled(sim)
            sim.spawn(runners[0], f"addblock{i}")
            sim.spawn(runners[1], f"background{i}")
        sim.spawn(node.forging_loop(cfg.n_slots), f"forge{i}")

    # edge (i, j): node j syncs FROM node i (i serves, j consumes)
    for (i, j) in edges:
        server_node, client_node = nodes[i], nodes[j]
        cand = Candidate()
        client_node.candidates[f"node{i}"] = cand
        cs_req = Channel(delay=cfg.msg_delay, name=f"cs-req-{i}-{j}")
        cs_rsp = Channel(delay=cfg.msg_delay, name=f"cs-rsp-{i}-{j}")
        bf_req = Channel(delay=cfg.msg_delay, name=f"bf-req-{i}-{j}")
        bf_rsp = Channel(delay=cfg.msg_delay, name=f"bf-rsp-{i}-{j}")
        sim.spawn(
            chainsync.server(server_node.chain_db, cs_req, cs_rsp),
            f"cs-server-{i}->{j}",
        )
        sim.spawn(
            chainsync.client(client_node, f"node{i}", cs_rsp, cs_req, cand),
            f"cs-client-{i}->{j}",
        )
        sim.spawn(
            blockfetch.server(server_node.chain_db, bf_req, bf_rsp),
            f"bf-server-{i}->{j}",
        )
        sim.spawn(
            blockfetch.client(client_node, f"node{i}", bf_rsp, bf_req, cand),
            f"bf-client-{i}->{j}",
        )

    # run: all slots + 2s of virtual drain time for in-flight messages
    sim.run(until=cfg.n_slots * cfg.slot_length + 2.0)

    res = ThreadNetResult(nodes, sim)
    for node in nodes:
        res.chains.append(list(node.chain_db.stream_all()))
    return res


# -- properties (prop_general, ThreadNet/General.hs:403) ---------------------


def check_common_prefix(res: ThreadNetResult, k: int) -> None:
    """All pairs of final chains fork at most k blocks from either tip."""
    for i in range(len(res.chains)):
        for j in range(i + 1, len(res.chains)):
            a, b = res.chain_hashes(i), res.chain_hashes(j)
            common = 0
            for x, y in zip(a, b):
                if x != y:
                    break
                common += 1
            assert len(a) - common <= k and len(b) - common <= k, (
                f"common-prefix violated between node{i} and node{j}: "
                f"common={common}, lens=({len(a)}, {len(b)})"
            )


def check_chain_growth(res: ThreadNetResult, cfg: ThreadNetConfig) -> None:
    """Chains grow: with n pools at stake 1/n and coeff f, expect ≥ a
    conservative fraction of active slots to produce adopted blocks."""
    min_len = min(len(c) for c in res.chains)
    # P(some leader in a slot) = 1-(1-f)^1 aggregated ≈ f for 1 pool; be
    # loose: expect at least n_slots * f / 4 blocks
    expect = int(cfg.n_slots * float(cfg.active_slot_coeff) / 4)
    assert min_len >= expect, f"chain too short: {min_len} < {expect}"
