"""Seeded multi-peer candidate-suffix traffic for the serving plane.

The reference's production workload is not one long replay: it is
thousands of concurrent ChainSync instances each pushing a SHORT
candidate suffix at the tip (SURVEY.md §3.2/§3.5). This module forges
that shape deterministically — N tenants (simulated peers), each
emitting rounds of within-epoch suffixes from its own fork of the
shared tip — so the serving-plane scheduler (node/serve.py), its
differential tests and `scripts/profile_serve.py` all drive the SAME
byte-reproducible traffic from one integer seed.

Convention: STUBBED-CRYPTO, like the profile_replay/profile_forge
device twins (testing/stubs.install_stub_crypto). Every signature,
VRF proof and VRF output is a counter-mode Blake2b expansion — zero
curve operations at forge time, so a 64-tenant x 256-header run
synthesizes in milliseconds — while everything validation actually
folds stays REAL: slots, OCert counters, KES window arithmetic, pool
lookups against the shared ledger view, and the eta/nonce chain
derived from the (deterministic) declared VRF outputs. Injected
failures therefore ride the REAL host-side error paths:

  * a counter jump   -> CounterOverIncrementedOCERT at the exact lane
  * an unknown pool  -> NoCounterForKeyHashOCERT (the stateful counter
                        check precedes the VRF pool lookup in the
                        reference order, Praos.hs:585-590)

Traffic shapes (all seeded):

  * follow        — one peer extending the tip, one suffix per round
  * fork storm    — a group of peers offering COMPETING suffixes from
                    the same parent: same pool, same slots, distinct
                    bodies (so distinct etas / distinct chains)
  * equivocators  — fork-storm pairs sharing the leader pool slot for
                    slot: the same pool forging two different headers
                    per slot across two peers
  * mixed formats — a seeded fraction of tenants carries 128-byte
                    batch-compatible proofs (the rest draft-03 80-byte),
                    so shared windows must segregate by proof class

Real networking (mux, delta-Q, peer churn) is NOT simulated — see
COVERAGE.md §3 for the honesty row."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from fractions import Fraction

from ..protocol import praos
from ..protocol.views import HeaderView, LedgerView, OCert
from . import fixtures

__all__ = [
    "TrafficConfig", "TenantSpec", "Suffix", "Traffic", "make_traffic",
]

# draft-03 / batch-compatible ECVRF proof lengths (protocol/views.py)
PROOF_LEN_DRAFT03 = 80
PROOF_LEN_BC = 128


def _expand(tag: bytes, data: bytes, n: int) -> bytes:
    """Counter-mode Blake2b expansion — the deterministic byte source
    for every stubbed signature/proof/output (same family as
    testing/stubs._expand_host, different tags)."""
    out = b""
    i = 0
    while len(out) < n:
        out += hashlib.blake2b(
            tag + i.to_bytes(2, "big") + data, digest_size=32
        ).digest()
        i += 1
    return out[:n]


@dataclass(frozen=True)
class TrafficConfig:
    """One seeded traffic mix. Defaults are tier-1 sized; the profile
    script scales n_tenants/suffix_len/rounds up."""

    n_tenants: int = 8
    seed: int = 0
    suffix_len: int = 12  # headers per suffix
    rounds: int = 2  # suffixes per tenant
    n_pools: int = 4
    body_len: int = 64  # KES-signed body bytes per header
    kes_depth: int = 3  # small tree: derive_vk is 2^depth leaf derives
    bc_every: int = 0  # every k-th tenant uses 128-byte bc proofs (0=off)
    fork_storm: int = 0  # first `fork_storm` tenants share one parent
    equivocators: int = 0  # pairs inside the storm sharing pool+slots
    bad_lane_every: int = 0  # every k-th tenant: one counter jump/round
    unknown_pool_every: int = 0  # every k-th tenant: one foreign-pool lane
    base_slot: int = 10
    slot_stride: int = 3  # slots between a tenant's headers


@dataclass(frozen=True)
class TenantSpec:
    """One simulated peer: identity, forging pool, proof format and
    which failure (if any) its suffixes carry."""

    tenant_id: str
    pool_idx: int
    proof_len: int = PROOF_LEN_DRAFT03
    storm_group: int | None = None  # shared-parent fork-storm group
    equivocal_with: str | None = None  # peer sharing pool+slots
    bad_lane: int | None = None  # in-suffix index of the counter jump
    unknown_pool_lane: int | None = None  # in-suffix index of foreign pool


@dataclass(frozen=True)
class Suffix:
    """One candidate suffix as a peer offers it: tenant, arrival
    sequence number, and the forged headers in chain order."""

    tenant_id: str
    seq: int
    hvs: tuple


@dataclass
class _TenantForgeState:
    """Forge-side chain cursor per tenant (NOT validation state)."""

    next_slot: int
    counter: int = 0
    prev_hash: bytes | None = None
    suffixes: int = 0


class Traffic:
    """Deterministic traffic source: `suffixes()` yields the full
    seeded arrival order (round-robin across tenants, the interleaving
    the scheduler must be fair under); `genesis_state()` is the shared
    tip state every tenant's candidate chain extends."""

    def __init__(self, cfg: TrafficConfig):
        if cfg.n_tenants < 1 or cfg.n_pools < 1:
            raise ValueError("traffic needs >= 1 tenant and >= 1 pool")
        self.cfg = cfg
        self.params = praos.PraosParams(
            slots_per_kes_period=3600,
            max_kes_evolutions=62,
            security_param=108,
            active_slot_coeff=Fraction(1, 2),
            epoch_length=4320,
            kes_depth=cfg.kes_depth,
        )
        self.pools = [
            fixtures.make_pool(1000 + i, kes_depth=cfg.kes_depth)
            for i in range(cfg.n_pools)
        ]
        # one pool deliberately OUTSIDE the ledger view: the
        # unknown-pool failure lane forges from it
        self.foreign_pool = fixtures.make_pool(9999, kes_depth=cfg.kes_depth)
        self.lview: LedgerView = fixtures.make_ledger_view(self.pools)
        self.eta0 = _expand(b"eta0", cfg.seed.to_bytes(8, "big"), 32)
        self.tenants = self._make_tenants()
        self._forge: dict[str, _TenantForgeState] = {}

    # -- tenant mix ---------------------------------------------------------

    def _make_tenants(self) -> list[TenantSpec]:
        cfg = self.cfg
        out: list[TenantSpec] = []
        for i in range(cfg.n_tenants):
            tid = f"peer-{i:03d}"
            storm = i if i < cfg.fork_storm else None
            # equivocator pairs live inside the storm: peers 2j/2j+1
            # forge from the SAME pool over the SAME slots
            eq_with = None
            if storm is not None and i < 2 * cfg.equivocators:
                eq_with = f"peer-{(i ^ 1):03d}"
            pool_idx = (i // 2 if eq_with is not None else i) % cfg.n_pools
            plen = (
                PROOF_LEN_BC
                if cfg.bc_every and (i % cfg.bc_every == cfg.bc_every - 1)
                else PROOF_LEN_DRAFT03
            )
            bad = (
                cfg.suffix_len // 2
                if cfg.bad_lane_every
                and (i % cfg.bad_lane_every == cfg.bad_lane_every - 1)
                else None
            )
            unk = (
                cfg.suffix_len // 3
                if cfg.unknown_pool_every
                and (i % cfg.unknown_pool_every
                     == cfg.unknown_pool_every - 1)
                else None
            )
            out.append(TenantSpec(
                tenant_id=tid, pool_idx=pool_idx, proof_len=plen,
                storm_group=storm, equivocal_with=eq_with,
                bad_lane=bad, unknown_pool_lane=unk,
            ))
        return out

    # -- forging ------------------------------------------------------------

    def genesis_state(self) -> praos.PraosState:
        return praos.PraosState(epoch_nonce=self.eta0)

    def _cursor(self, spec: TenantSpec) -> _TenantForgeState:
        st = self._forge.get(spec.tenant_id)
        if st is None:
            # equivocator pairs (and storm members) start on the same
            # slot grid so their headers COLLIDE slot-for-slot; plain
            # followers are offset per tenant so shared windows carry
            # genuinely interleaved slot ranges
            base = self.cfg.base_slot
            if spec.storm_group is None:
                base += (int(spec.tenant_id[-3:]) % 7)
            st = _TenantForgeState(next_slot=base)
            self._forge[spec.tenant_id] = st
        return st

    def _forge_header(self, spec: TenantSpec, slot: int, counter: int,
                      prev_hash: bytes | None, *, pool=None) -> HeaderView:
        """One stub-crypto header: real identity/slot/counter columns,
        expansion-derived signature/proof/output bytes."""
        pool = pool if pool is not None else self.pools[spec.pool_idx]
        uid = (spec.tenant_id.encode()
               + slot.to_bytes(8, "big") + counter.to_bytes(4, "big"))
        body = _expand(b"body", uid, self.cfg.body_len)
        beta = _expand(b"beta", pool.pool_id + body, 64)
        proof = _expand(b"pi", pool.pool_id + body, spec.proof_len)
        kes_sig = _expand(
            b"kes", uid, 64 + 32 + 32 * self.cfg.kes_depth
        )
        kp = self.params.kes_period_of(slot)
        ocert = OCert(
            pool.kes_vk, counter, kp, _expand(b"oc", uid, 64)
        )
        return HeaderView(
            prev_hash=prev_hash,
            vk_cold=pool.vk_cold,
            vrf_vk=pool.vrf_vk,
            vrf_output=beta,
            vrf_proof=proof,
            ocert=ocert,
            slot=slot,
            signed_bytes=body,
            kes_sig=kes_sig,
        )

    def next_suffix(self, spec: TenantSpec) -> Suffix:
        """The tenant's next candidate suffix, extending its own fork.
        Failure lanes are injected at the spec's pinned in-suffix index
        on EVERY round — the valid prefix before them still advances
        the tenant's chain, exactly like a peer whose candidate is
        truncated at the first invalid header."""
        cfg = self.cfg
        st = self._cursor(spec)
        hvs: list[HeaderView] = []
        for j in range(cfg.suffix_len):
            slot = st.next_slot
            st.next_slot += cfg.slot_stride
            counter = st.counter
            pool = None
            if j == spec.bad_lane and st.suffixes % 2 == 0:
                # m <= n <= m+1 violated: the sequential fold raises
                # CounterOverIncrementedOCERT at exactly this lane
                counter = st.counter + 5
            elif j == spec.unknown_pool_lane and st.suffixes % 2 == 1:
                pool = self.foreign_pool  # NoCounterForKeyHashOCERT lane
            hv = self._forge_header(
                spec, slot, counter, st.prev_hash, pool=pool
            )
            hvs.append(hv)
            st.prev_hash = hashlib.blake2b(
                hv.signed_bytes + slot.to_bytes(8, "big"),
                digest_size=32,
            ).digest()
        st.suffixes += 1
        return Suffix(spec.tenant_id, st.suffixes - 1, tuple(hvs))

    def suffixes(self):
        """The full seeded arrival order: `rounds` passes, round-robin
        across tenants (the adversarial interleaving for fairness and
        cross-tenant-bleed tests)."""
        for _ in range(self.cfg.rounds):
            for spec in self.tenants:
                yield self.next_suffix(spec)

    def reset(self) -> None:
        """Forget all forge cursors: the next `suffixes()` pass
        regenerates the byte-identical stream (sigkill-resume tests
        re-derive the undelivered tail from the same seed)."""
        self._forge.clear()


def make_traffic(**kw) -> Traffic:
    """Convenience: Traffic(TrafficConfig(**kw))."""
    return Traffic(TrafficConfig(**kw))
