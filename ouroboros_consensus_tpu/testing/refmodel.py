"""Pure reference models for ThreadNet multi-node properties.

The reference cross-checks its ThreadNet runs against a PURE simulator
of the protocol's deterministic structure
(`ouroboros-consensus-diffusion/src/diffusion-testlib/Test/ThreadNet/
Ref/PBFT.hs`, consumed by `General.hs:403,479`): expected chain length
and fork structure are predicted WITHOUT running nodes, then the real
net's outcome must match. These are the tpu-repo analogs:

* `pbft_ref_simulate` — Byron/PBFT round-robin with the signing-window
  threshold rule (PBFT.hs:393-396) simulated purely.
* `praos_leader_slots` / `expected_mock_net_length` — the Praos lottery
  IS a deterministic leader schedule given the fixture keys and the
  epoch nonce; the model recomputes it via the protocol's own
  `check_is_leader` (no reimplementation) and predicts the adopted
  chain length exactly: one block per slot with >= 1 up leader.

Model applicability (documented per function): single epoch (the nonce
does not rotate), full diffusion within a slot (msg_delay * network
diameter < slot_length), no mid-run restarts. The ThreadNet checker
falls back to the loose bound outside these conditions.
"""

from __future__ import annotations

from fractions import Fraction

from ..protocol import praos
from . import fixtures


def pbft_ref_simulate(
    n_slots: int,
    n_keys: int,
    window: int,
    threshold: Fraction,
    join_plan: dict[int, int] | None = None,
) -> tuple[int, list[int | None]]:
    """Simulate PBFT round-robin forging purely (Ref/PBFT.hs role).

    Slot s's designated signer is s % n_keys (PBftProtocol.
    check_is_leader). It forges unless appending its signature to the
    sliding window of the last `window` signers would push its count
    above floor(threshold * window) — the exact rule of
    PBftProtocol.apply_checked_sig. Returns (expected chain length,
    signer-per-slot list with None for skipped slots)."""
    tcount = int(threshold * window)
    signers: list[int] = []
    outcome: list[int | None] = []
    for s in range(n_slots):
        gk = s % n_keys
        if join_plan and join_plan.get(gk, 0) > s:
            outcome.append(None)
            continue
        new = (signers + [gk])[-window:]
        if new.count(gk) > tcount:
            # the designated signer would violate its threshold: the
            # slot stays empty (the node declines to forge an
            # unadoptable block)
            outcome.append(None)
            continue
        signers = new
        outcome.append(gk)
    return sum(1 for o in outcome if o is not None), outcome


def praos_leader_slots(
    params: praos.PraosParams,
    pools,
    lview,
    epoch_nonce,
    n_slots: int,
    forgers,
    join_plan: dict[int, int] | None = None,
) -> list[list[int]]:
    """Per-slot winner sets of the Praos lottery among the UP forgers —
    computed through the protocol's own check_is_leader. Valid within
    one epoch (constant nonce and stake distribution)."""
    join = join_plan or {}
    out = []
    for s in range(n_slots):
        winners = [
            i for i in forgers
            if join.get(i, 0) <= s
            and fixtures.find_leader(params, [pools[i]], lview, s,
                                     epoch_nonce) is not None
        ]
        out.append(winners)
    return out


def expected_praos_length(leader_slots: list[list[int]]) -> int:
    """Under full within-slot diffusion every slot with >= 1 leader
    contributes EXACTLY one adopted block (same parent everywhere at
    slot start; the SelectView tie-break picks one global winner)."""
    return sum(1 for w in leader_slots if w)


def mock_net_model_applies(cfg) -> bool:
    """The exact model holds for the single-era mock net when: no HFC
    (nonce evolution at era/epoch boundaries is out of model), the run
    stays in epoch 0, no restarts (a restart's downtime window depends
    on sim scheduling), and diffusion completes within a slot."""
    diameter = 1 if cfg.topology is None else cfg.n_nodes  # loose bound
    return (
        cfg.hard_fork_at_epoch is None
        and cfg.n_slots <= cfg.epoch_length
        and not cfg.restarts
        # a late-JOINING forger spends its first slots syncing — its
        # wins orphan until ChainSync catches up, which the pure model
        # cannot time
        and not cfg.join_plan
        and cfg.msg_delay * diameter < cfg.slot_length
    )


def expected_mock_net_length(cfg) -> int:
    """Reconstruct the net's pools/params exactly as testing.threadnet
    does and predict the final chain length. Requires
    mock_net_model_applies(cfg)."""
    params = praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=cfg.k,
        active_slot_coeff=cfg.active_slot_coeff,
        epoch_length=cfg.epoch_length,
        kes_depth=cfg.kes_depth,
    )
    pools = [
        fixtures.make_pool(i, kes_depth=cfg.kes_depth)
        for i in range(cfg.n_nodes)
    ]
    lview = fixtures.make_ledger_view(pools)
    forgers = (
        cfg.forgers if cfg.forgers is not None else list(range(cfg.n_nodes))
    )
    # the mock net's genesis chain-dep state carries the neutral nonce
    slots = praos_leader_slots(
        params, pools, lview, None, cfg.n_slots, forgers, cfg.join_plan
    )
    return expected_praos_length(slots)
