"""Hash-only crypto stubs for pipeline tests and the profiling twin.

The full curve graphs take minutes to compile on XLA:CPU; these stubs
keep every NON-crypto part of the batched pipeline byte-exact — packed
staging, device unpack, verdict bitmasks, the chained nonce scan,
carries, epilogue — while replacing the three verifier subgraphs with
an all-valid verdict plus the REAL eta / leader-value range extensions
(the Blake2b tail the nonce fold and leader compare consume). The
differential suites (tests/test_packed_batch.py, test_columnar.py,
test_warm_ladder.py) and the `scripts/profile_replay.py --overlap-ab`
stubbed-crypto device twin share this one implementation.

`stub_agg_program` additionally stands in for the aggregated
(RLC/MSM) window program with the SAME output contract as
protocol/batch._jitted_packed_agg — limb-first eta/leader-value
handles, verdict_reduce outputs — wrapped in `_warm_timed` so the
warm-ladder machinery (first-execute labels, background compile,
swap) exercises its real code path. An optional per-lane-count delay
simulates a compile wall (the slow-compile stub of the ladder tests
and the cold-cache harness)."""

from __future__ import annotations

import time

from jax import numpy as jnp

from ..ops import blake2b


def stub_verify(*cols):
    """All-valid crypto stub with the real eta / leader-value range
    extensions. Arity-generic (21 draft-03 / 22 batch-compatible
    columns): beta_decl is always the third-from-last column."""
    from ..protocol import batch as pbatch

    beta_decl = cols[-3]
    bd = jnp.asarray(beta_decl).astype(jnp.int32)
    b = bd.shape[0]
    tag_l = jnp.broadcast_to(jnp.asarray([ord("L")], jnp.int32), (b, 1))
    lv = blake2b.blake2b_fixed(jnp.concatenate([tag_l, bd], axis=-1), 65, 32)
    tag_n = jnp.broadcast_to(jnp.asarray([ord("N")], jnp.int32), (b, 1))
    eta1 = blake2b.blake2b_fixed(jnp.concatenate([tag_n, bd], axis=-1), 65, 32)
    eta = blake2b.blake2b_fixed(eta1, 32, 32)
    ones = jnp.ones((b,), bool)
    return pbatch.Verdicts(ones, ones, ones, ones, jnp.zeros((b,), bool),
                           eta, lv)


def _first_exec_delay(delay_s, seen: set):
    """Host-side sleep on the FIRST call per argument lane count — the
    simulated compile wall (sleep releases the GIL, so a background
    'compile' overlaps the foreground replay exactly like XLA does)."""

    def maybe_sleep(lanes: int) -> None:
        if not delay_s:
            return
        if lanes in seen:
            return
        seen.add(lanes)
        d = delay_s(lanes) if callable(delay_s) else float(delay_s)
        if d > 0:
            time.sleep(d)

    return maybe_sleep


def stub_agg_program_builder(delay_s=None):
    """A drop-in for protocol/batch._jitted_packed_agg: same output
    contract (verdict_reduce outputs + limb-first flags/eta/lv
    handles), crypto stubbed, `_warm_timed`-wrapped so first-execute
    labels, the compile gate and the warm ladder see the real
    machinery. `delay_s` (float or callable(lanes)->float) injects a
    simulated compile wall on the first execute per lane count."""
    import jax

    from ..protocol import batch as pbatch

    seen: set = set()
    sleep = _first_exec_delay(delay_s, seen)

    def builder(layout, scan, mode="all"):
        key = ("stub-agg", layout, scan, bool(delay_s))
        if key not in pbatch._JIT:

            def fn(body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
                   thr_idx, thr_tab, nonce, within, n_real,
                   ev0, ev0_set, cand0, cand0_set):
                cols = pbatch.unpack_packed(
                    layout, body, kes_rs, kt_idx, kt_tab, slot, counter,
                    c0, thr_idx, thr_tab, nonce,
                )
                v = stub_verify(*cols)
                flags = jnp.stack(
                    [v.ok_ocert_sig, v.ok_kes_sig, v.ok_vrf, v.ok_leader,
                     v.leader_ambiguous]
                ).astype(jnp.int32)
                red = pbatch.verdict_reduce(
                    flags, v.eta, within, n_real, ev0, ev0_set, cand0,
                    cand0_set, scan=scan,
                )
                return (red, flags, jnp.transpose(v.eta),
                        jnp.transpose(v.leader_value))

            jitted = jax.jit(fn)

            class _SlowJit:
                """Delegates to the jit but sleeps on the first touch
                per lane count — through EITHER the call path or the
                write-back's explicit trace/lower/compile path, so the
                simulated wall lands wherever the real compile would."""

                def __call__(self, *a):
                    sleep(int(a[0].shape[0]))
                    return jitted(*a)

                def trace(self, *a):
                    sleep(int(a[0].shape[0]))
                    return jitted.trace(*a)

            pbatch._JIT[key] = pbatch._warm_timed(
                f"agg-packed:{layout.body_len}b:"
                f"{'scan' if scan else 'noscan'}",
                _SlowJit(),
            )
        return pbatch._JIT[key]

    return builder


def install_stub_crypto(monkeypatch=None, agg_delay_s=None):
    """Patch the crypto entry points of protocol/batch with the stubs.
    With a pytest `monkeypatch` the patches auto-revert; without one
    (profile_replay — a one-shot script process) they are applied
    directly. Covers the generic fused path, the packed xla path and
    the aggregated path; the pk split path routes through
    verify_praos_any inside the packed xla program."""
    import jax

    from ..protocol import batch as pbatch

    def setattr_(name, value):
        if monkeypatch is not None:
            monkeypatch.setattr(pbatch, name, value)
        else:
            setattr(pbatch, name, value)

    setattr_("verify_praos", stub_verify)
    setattr_("verify_praos_bc", stub_verify)
    setattr_("verify_praos_any", stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(stub_verify)
        return pbatch._JIT[key]

    setattr_("_jitted_verify", patched_jv)
    setattr_("_jitted_packed_agg", stub_agg_program_builder(agg_delay_s))
