"""Hash-only crypto stubs for pipeline tests and the profiling twin.

The full curve graphs take minutes to compile on XLA:CPU; these stubs
keep every NON-crypto part of the batched pipeline byte-exact — packed
staging, device unpack, verdict bitmasks, the chained nonce scan,
carries, epilogue — while replacing the three verifier subgraphs with
an all-valid verdict plus the REAL eta / leader-value range extensions
(the Blake2b tail the nonce fold and leader compare consume). The
differential suites (tests/test_packed_batch.py, test_columnar.py,
test_warm_ladder.py) and the `scripts/profile_replay.py --overlap-ab`
stubbed-crypto device twin share this one implementation.

`stub_agg_program` additionally stands in for the aggregated
(RLC/MSM) window program with the SAME output contract as
protocol/batch._jitted_packed_agg — limb-first eta/leader-value
handles, verdict_reduce outputs — wrapped in `_warm_timed` so the
warm-ladder machinery (first-execute labels, background compile,
swap) exercises its real code path. An optional per-lane-count delay
simulates a compile wall (the slow-compile stub of the ladder tests
and the cold-cache harness)."""

from __future__ import annotations

import time

from jax import numpy as jnp

from ..ops import blake2b


def stub_verify(*cols):
    """All-valid crypto stub with the real eta / leader-value range
    extensions. Arity-generic (21 draft-03 / 22 batch-compatible
    columns): beta_decl is always the third-from-last column."""
    from ..protocol import batch as pbatch

    beta_decl = cols[-3]
    bd = jnp.asarray(beta_decl).astype(jnp.int32)
    b = bd.shape[0]
    tag_l = jnp.broadcast_to(jnp.asarray([ord("L")], jnp.int32), (b, 1))
    lv = blake2b.blake2b_fixed(jnp.concatenate([tag_l, bd], axis=-1), 65, 32)
    tag_n = jnp.broadcast_to(jnp.asarray([ord("N")], jnp.int32), (b, 1))
    eta1 = blake2b.blake2b_fixed(jnp.concatenate([tag_n, bd], axis=-1), 65, 32)
    eta = blake2b.blake2b_fixed(eta1, 32, 32)
    ones = jnp.ones((b,), bool)
    return pbatch.Verdicts(ones, ones, ones, ones, jnp.zeros((b,), bool),
                           eta, lv)


def _first_exec_delay(delay_s, seen: set):
    """Host-side sleep on the FIRST call per argument lane count — the
    simulated compile wall (sleep releases the GIL, so a background
    'compile' overlaps the foreground replay exactly like XLA does)."""

    def maybe_sleep(lanes: int) -> None:
        if not delay_s:
            return
        if lanes in seen:
            return
        seen.add(lanes)
        d = delay_s(lanes) if callable(delay_s) else float(delay_s)
        if d > 0:
            time.sleep(d)

    return maybe_sleep


def stub_agg_program_builder(delay_s=None):
    """A drop-in for protocol/batch._jitted_packed_agg: same output
    contract (verdict_reduce outputs + limb-first flags/eta/lv
    handles), crypto stubbed, `_warm_timed`-wrapped so first-execute
    labels, the compile gate and the warm ladder see the real
    machinery. `delay_s` (float or callable(lanes)->float) injects a
    simulated compile wall on the first execute per lane count."""
    import jax

    from ..protocol import batch as pbatch

    seen: set = set()
    sleep = _first_exec_delay(delay_s, seen)

    def builder(layout, scan, mode="all"):
        key = ("stub-agg", layout, scan, bool(delay_s))
        if key not in pbatch._JIT:

            def fn(body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
                   thr_idx, thr_tab, nonce, within, n_real,
                   ev0, ev0_set, cand0, cand0_set):
                cols = pbatch.unpack_packed(
                    layout, body, kes_rs, kt_idx, kt_tab, slot, counter,
                    c0, thr_idx, thr_tab, nonce,
                )
                v = stub_verify(*cols)
                flags = jnp.stack(
                    [v.ok_ocert_sig, v.ok_kes_sig, v.ok_vrf, v.ok_leader,
                     v.leader_ambiguous]
                ).astype(jnp.int32)
                red = pbatch.verdict_reduce(
                    flags, v.eta, within, n_real, ev0, ev0_set, cand0,
                    cand0_set, scan=scan,
                )
                return (red, flags, jnp.transpose(v.eta),
                        jnp.transpose(v.leader_value))

            jitted = jax.jit(fn)

            class _SlowJit:
                """Delegates to the jit but sleeps on the first touch
                per lane count — through EITHER the call path or the
                write-back's explicit trace/lower/compile path, so the
                simulated wall lands wherever the real compile would."""

                def __call__(self, *a):
                    sleep(int(a[0].shape[0]))
                    return jitted(*a)

                def trace(self, *a):
                    sleep(int(a[0].shape[0]))
                    return jitted.trace(*a)

            pbatch._JIT[key] = pbatch._warm_timed(
                f"agg-packed:{layout.body_len}b:"
                f"{'scan' if scan else 'noscan'}",
                _SlowJit(),
            )
        return pbatch._JIT[key]

    return builder


def _expand_host(tag: int, data: bytes, n: int) -> bytes:
    """Counter-mode Blake2b expansion — the host half of the stub
    forge-crypto family. MUST stay byte-identical to `_expand_dev`."""
    import hashlib

    out = b""
    i = 0
    while len(out) < n:
        out += hashlib.blake2b(
            bytes([tag, i]) + data, digest_size=32
        ).digest()
        i += 1
    return out[:n]


def _expand_dev(tag: int, data, data_len: int, n: int):
    """The device twin of `_expand_host` on [..., L] int32 byte rows."""
    parts = []
    for i in range((n + 31) // 32):
        pre = jnp.broadcast_to(
            jnp.asarray([tag, i], jnp.int32), (*data.shape[:-1], 2)
        )
        parts.append(
            blake2b.blake2b_fixed(
                jnp.concatenate([pre, data], axis=-1), data_len + 2, 32
            )
        )
    return jnp.concatenate(parts, axis=-1)[..., :n]


def make_stub_forge_sweep(plen: int):
    """Build a hash-twin of protocol/forge.forge_sweep: the VRF prove
    is replaced by the counter-mode expansion (compiles in seconds on
    XLA:CPU) while the alpha derivation, leader-value tail and
    threshold bracket stay REAL — so the election scatter, ambiguity
    split and proof-column splice are exercised end to end. Must agree
    byte-for-byte with the host stubs install_stub_forge patches into
    ops/host/fast.

    The proof length is captured HERE, at build time, and each call
    returns a fresh function object: jax's tracing cache keys on the
    function identity plus argument avals, and both formats present
    identical avals — a shared module-level sweep traced under one
    format would silently serve the other format's calls with the
    first trace's proof layout baked in."""

    def stub_forge_sweep(x, prefix, pk, slots, nonce, thr_lo, thr_hi):
        from ..ops import ecvrf_batch
        from ..protocol.batch import _lt_be

        x = jnp.asarray(x).astype(jnp.int32)
        alpha = ecvrf_batch.alpha_from_slots(
            jnp.asarray(slots).astype(jnp.int32), nonce
        )
        xa = jnp.concatenate([x, alpha], axis=-1)
        proof = _expand_dev(ord("p"), xa, 64, plen)
        p32 = blake2b.blake2b_fixed(proof, plen, 32)
        beta = _expand_dev(ord("b"), p32, 32, 64)
        tag_l = jnp.broadcast_to(
            jnp.asarray([ord("L")], jnp.int32), (*beta.shape[:-1], 1)
        )
        lv = blake2b.blake2b_fixed(
            jnp.concatenate([tag_l, beta], axis=-1), 65, 32
        )
        thr_lo = jnp.asarray(thr_lo).astype(jnp.int32)
        thr_hi = jnp.asarray(thr_hi).astype(jnp.int32)
        win = _lt_be(lv, thr_lo)
        ambiguous = ~win & _lt_be(lv, thr_hi)
        if plen == 128:
            g_enc, u_enc, v_enc, s32 = (
                proof[..., :32], proof[..., 32:64],
                proof[..., 64:96], proof[..., 96:128],
            )
            c16 = proof[..., :16]
        else:
            g_enc, c16, s32 = (
                proof[..., :32], proof[..., 32:48], proof[..., 48:80],
            )
            u_enc, v_enc = g_enc, g_enc
        return g_enc, c16, u_enc, v_enc, s32, beta, win, ambiguous

    return stub_forge_sweep


def install_stub_forge(monkeypatch, bucket: int = 256):
    """Stub the forge-side crypto for the tier-1 device differential:
    `fast.ecvrf_prove` / `ecvrf_proof_to_hash` / `ed25519_sign` become
    the counter-mode expansion family and the device sweep becomes
    `stub_forge_sweep` — every engine (loop / host / device) then
    forges the SAME bytes, at stub speed. `fast.ed25519_public` is
    deliberately NOT patched: ops/host/kes.derive_vk lru-caches vks
    derived through it, and a poisoned cache would outlive the patch.
    The device OCert batch-sign is rerouted through the (patched) host
    signer so no real ed25519 device graph compiles under the stub —
    the real forge_sign kernel is octrange-certified byte-identical to
    the host signer and exercised by the slow-tier differential."""
    from ..ops.host import ed25519 as he
    from ..ops.host import fast
    from ..protocol import forge as forge_mod
    from ..protocol.views import OCert

    # the proof length is pinned ONCE, at install time, and threaded
    # into a freshly built device sweep: see make_stub_forge_sweep on
    # why the sweep must be a new function object per install
    plen = 128 if fast.vrf_batch_compat() else 80

    def stub_prove(seed: bytes, alpha: bytes) -> bytes:
        x_bytes, _pref, _pk = he.expand_for_staging(seed)
        return _expand_host(ord("p"), x_bytes + alpha, plen)

    def stub_proof_to_hash(pi: bytes) -> bytes:
        # the proof is hashed to 32 bytes first: the device twin's
        # single-block blake2b_fixed cannot absorb tag+proof (130B bc)
        import hashlib

        p32 = hashlib.blake2b(pi, digest_size=32).digest()
        return _expand_host(ord("b"), p32, 64)

    def stub_sign(seed: bytes, msg: bytes) -> bytes:
        x_bytes, _pref, _pk = he.expand_for_staging(seed)
        return _expand_host(ord("s"), x_bytes + msg, 64)

    def stub_sign_ocerts(pools, triples) -> dict:
        out = {}
        for pool_i, counter, kp0 in sorted(triples):
            pool = pools[pool_i]
            oc = OCert(pool.kes_vk, counter, kp0, b"")
            sig = stub_sign(pool.cold_seed, oc.signable())
            out[(pool_i, counter, kp0)] = OCert(
                oc.vk_hot, oc.counter, oc.kes_period, sig
            )
        return out

    monkeypatch.setattr(fast, "ecvrf_prove", stub_prove)
    monkeypatch.setattr(fast, "ecvrf_proof_to_hash", stub_proof_to_hash)
    monkeypatch.setattr(fast, "ed25519_sign", stub_sign)
    monkeypatch.setattr(forge_mod, "_SWEEP_FN", make_stub_forge_sweep(plen))
    monkeypatch.setattr(forge_mod, "sign_ocerts_batch", stub_sign_ocerts)
    monkeypatch.setattr(forge_mod, "_JITS", {})
    monkeypatch.setattr(forge_mod, "FORGE_BUCKET", bucket)


def install_stub_crypto(monkeypatch=None, agg_delay_s=None):
    """Patch the crypto entry points of protocol/batch with the stubs.
    With a pytest `monkeypatch` the patches auto-revert; without one
    (profile_replay — a one-shot script process) they are applied
    directly. Covers the generic fused path, the packed xla path and
    the aggregated path; the pk split path routes through
    verify_praos_any inside the packed xla program."""
    import jax

    from ..protocol import batch as pbatch

    def setattr_(name, value):
        if monkeypatch is not None:
            monkeypatch.setattr(pbatch, name, value)
        else:
            setattr(pbatch, name, value)

    setattr_("verify_praos", stub_verify)
    setattr_("verify_praos_bc", stub_verify)
    setattr_("verify_praos_any", stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(stub_verify)
        return pbatch._JIT[key]

    setattr_("_jitted_verify", patched_jv)
    setattr_("_jitted_packed_agg", stub_agg_program_builder(agg_delay_s))
