"""Deterministic fault injection: every death mode, reproducible on CPU.

Rounds r02-r05 each died a DIFFERENT death — probe timeout, ~410 s
compile wall, AOT format rejection, driver kill — and every one was
only ever observed on a live tunnel, where it cost a session. This
module makes each of those modes an injectable, seeded, deterministic
event so the recovery plane (obs/recovery.py) is proven against them
in tier-1, on CPU, in milliseconds.

Armed by ``OCT_CHAOS=<spec>``; the spec is a comma-separated list of
injections, each ``<fault>@<trigger>:<arg>`` (the trigger clause is
optional for fault kinds that need none):

    compile-stall@window:3        sleep OCT_CHAOS_STALL_S at the 3rd
                                  dispatched window (a simulated wall)
    compile-stall@stage:ed        ...at stage 'ed's dispatch (pk path)
    device-error@dispatch:2       raise DeviceChaosError at the 2nd
                                  window dispatch (fake XlaRuntimeError)
    device-error@stage:finish     ...inside _stage_call for 'finish'
    device-error@shard:0          ...at the 0th sharded dispatch
    staging-thread-death@window:5 raise inside prepare_window for the
                                  5th staged window (producer thread)
    sigkill@window:7              SIGKILL self when the 7th window
                                  retires (AFTER its checkpoint lands)
    chunk-corrupt@epoch:1         raise ChunkChaosError on the 2nd
                                  chunk read (index 1; chunk index
                                  stands in for the epoch on the
                                  synthesized chains, one chunk/epoch)
    aot-reject@stage:aggregate    ops/pk/aot.load reports the entry
                                  rejected ("incompatible" class) for
                                  any stage whose name contains the arg
    probe-timeout                 bench's device probe hangs past its
                                  timeout (one attempt per injection;
                                  list it twice to kill two attempts)

Write-path faults (the durable-store matrix, PR 13) land at the chunk
writer's seam (`ImmutableDB.append_block` consumes them via
`write_fault()` and owns the disk mutation) and the marker writer's
(`storage/guard.write_clean_marker`):

    torn-write@append:4           the 5th block append crashes mid-
                                  write: a PREFIX of the block lands
                                  in the chunk, no index entry, and
                                  the writer dies (TornWriteChaos)
    bitflip@chunk:2               silent bit rot: one byte of a block
                                  appended into chunk 2 flips on disk;
                                  the write "succeeds" and the writer
                                  carries on (the index CRC records
                                  the truth, so a deep walk catches it)
    index-truncate@epoch:1        the chunk-1 index file is torn mid-
                                  entry right after an append lands,
                                  and the writer dies (IndexTornChaos)
    sigkill@append:3              SIGKILL self between the 4th block's
                                  chunk append and its index append —
                                  a REAL kill leaving the index lagging
    partial-rename@marker         the clean-shutdown marker write dies
                                  between the tmp write and the atomic
                                  rename (PartialRenameChaos): durable
                                  tmp, no marker — the next open is
                                  dirty (optionally @marker:clean to
                                  name a specific marker)

Columnar-sidecar faults (PR 17) land at the sidecar writer's and
freshness probe's seams (`storage/sidecar.write_sidecar` /
`load_sidecar` consume them via `sidecar_fault()` and own the
semantics — a fault here may NEVER change a replay verdict, only
force the parse fallback):

    sidecar-torn@build:2          the 3rd sidecar build bypasses the
                                  tmp+rename protocol and lands a torn
                                  prefix at the final name; the probe
                                  must reject it by seal
    sidecar-stale@open:0          the 1st freshness probe reports
                                  stale regardless of the seal — the
                                  replay falls back to parse and (a
                                  writer open) rebuilds
    sigkill@build:1               SIGKILL self between the 2nd sidecar
                                  build's tmp write and its rename —
                                  only the durable tmp survives (the
                                  next open sweeps it)

Forge-pipeline faults (PR 18) land at the batched synthesizer's seams
(`protocol/forge.py`): the per-window election dispatch and the
per-forged-block retire (after the append + state fold land, before
the next block is forged):

    device-error@forge-dispatch:0 raise DeviceChaosError at the 1st
                                  window's leader-election dispatch;
                                  the forge recovery ladder retries,
                                  then drops to the exact host loop
    sigkill@forge:10              SIGKILL self right after the 11th
                                  forged block's append lands — the
                                  store reopens dirty and resume=True
                                  must converge byte-identically

Serving-plane faults (PR 20) land at the continuous-batching
scheduler's seams (`node/serve.py`): the shared-window dispatch and
the per-retired-window checkpoint:

    device-error@serve-dispatch:2 raise DeviceChaosError at the 3rd
                                  shared serving window's dispatch;
                                  every affected tenant segment sheds
                                  down the recovery ladder (degraded-
                                  mode serving, byte-identical verdicts,
                                  no tenant dropped)
    sigkill@serve:10              SIGKILL self right after the 11th
                                  serving window's checkpoint lands —
                                  the relaunched service resumes every
                                  tenant's fold state and banked
                                  verdicts from the progress record

Triggers are matched against per-seam sequence counters (each seam
counts its own firings from 0 in dispatch order) or, for ``stage:``,
by substring against the stage label. Each injection fires EXACTLY
once (append ``xN`` to the arg for N firings: ``device-error@dispatch:
2x3``), so a retried operation succeeds — chaos faults are transient
by construction, which is precisely the contract the recovery ladder
is allowed to assume (COVERAGE.md §5.16 for what that excludes).

Determinism: the spec and the per-seam counters fully determine WHERE
every fault lands; ``OCT_CHAOS_SEED`` seeds the one RNG exposed here
(`rng()`), used for backoff jitter by consumers that want reproducible
recovery timing, never for fault placement.

Zero overhead disarmed: every seam is ``chaos.fire(site, ...)`` whose
first instruction checks a module bool refreshed from the env once per
process (and by `reset()` in tests); with OCT_CHAOS unset the call is
one attribute load + a falsy test, entirely host-side — the
instrumentation-purity ratchet proves the seams add no equations to
any traced program (tests/test_chaos.py)."""

from __future__ import annotations

import os
import random
import threading
import time

_ENV = "OCT_CHAOS"
_SEED_ENV = "OCT_CHAOS_SEED"
_STALL_ENV = "OCT_CHAOS_STALL_S"

FAULT_KINDS = (
    "compile-stall",
    "device-error",
    "staging-thread-death",
    "sigkill",
    "chunk-corrupt",
    "aot-reject",
    "probe-timeout",
    # write-path faults (the durable-store torn-write/bit-rot matrix)
    "torn-write",
    "bitflip",
    "index-truncate",
    "partial-rename",
    # columnar-sidecar faults (storage/sidecar.py; verdict-neutral by
    # contract — they may only force the parse fallback)
    "sidecar-torn",
    "sidecar-stale",
)

# which seam(s) each fault kind is checked at — fire(site) only
# consults injections mapped to that site, so a spec can never detonate
# at a seam its fault kind does not model
_KIND_SITES = {
    "compile-stall": ("dispatch", "stage-call"),
    "device-error": ("dispatch", "stage-call", "shard", "forge-dispatch",
                     "serve-dispatch"),
    "staging-thread-death": ("stage",),
    "sigkill": ("retire", "append", "sidecar-build", "forge", "serve"),
    "chunk-corrupt": ("chunk",),
    "aot-reject": ("aot",),
    "probe-timeout": ("probe",),
    # the chunk writer's seam (write_fault in append_block) and the
    # marker writer's (guard.write_clean_marker)
    "torn-write": ("append",),
    "bitflip": ("append",),
    "index-truncate": ("append",),
    "partial-rename": ("marker",),
    # the sidecar writer's seam (sidecar_fault in write_sidecar) and
    # the freshness probe's (load_sidecar)
    "sidecar-torn": ("sidecar-build",),
    "sidecar-stale": ("sidecar-open",),
}

# the trigger keys each seam actually provides (its explicit ctx= kwargs
# plus its _SITE_SEQ_KEYS) — parse_spec refuses a trigger no seam of the
# fault's kind can ever satisfy: such a spec would arm and then silently
# never fire, exactly the fake-green matrix the fail-loud rule forbids
_SITE_TRIGGER_KEYS = {
    "dispatch": ("window", "dispatch"),
    "stage-call": ("stage",),
    "stage": ("window",),
    "retire": ("window",),
    "shard": ("shard",),
    "chunk": ("chunk",),
    "append": ("append", "chunk"),
    "aot": ("stage",),
    "marker": ("marker",),
    "probe": ("attempt",),
    "sidecar-build": ("build", "chunk"),
    "sidecar-open": ("open", "chunk"),
    "forge": ("forge",),
    "forge-dispatch": ("forge-dispatch",),
    "serve": ("serve",),
    "serve-dispatch": ("serve-dispatch",),
}


class ChaosError(RuntimeError):
    """Base of the injected-fault taxonomy. Transient by contract:
    the injection that raised it is spent, so a retry succeeds."""


class DeviceChaosError(ChaosError):
    """Stands in for a runtime device error (XlaRuntimeError class)."""


class StagingChaosError(ChaosError):
    """The staging producer thread died mid-prepare."""


class ChunkChaosError(ChaosError):
    """A chunk read/extract came back corrupted (transient I/O)."""


class TornWriteChaos(ChaosError):
    """A block append crashed mid-write: a torn prefix is on disk."""


class IndexTornChaos(ChaosError):
    """The secondary index was torn mid-entry after an append."""


class PartialRenameChaos(ChaosError):
    """A marker write died between the tmp write and the rename."""


class AotRejectChaos(ChaosError):
    """An AOT store entry is rejected as format-incompatible. The
    message deliberately matches ops/pk/aot.INCOMPATIBLE_PATTERNS so
    the real classification machinery sees the real failure shape."""

    def __init__(self, stage: str):
        super().__init__(
            f"serialized executable is incompatible (chaos-injected "
            f"rejection for stage {stage})"
        )


# wildcard arg: "any value at this trigger key" — only the grammar
# forms that document it (partial-rename@marker) may parse to this
ANY = object()


class _Injection:
    __slots__ = ("kind", "trigger", "arg", "count", "fired")

    def __init__(self, kind: str, trigger: str | None, arg, count: int):
        self.kind = kind
        self.trigger = trigger  # "window"|"dispatch"|"stage"|"epoch"|
        # "shard"|"append"|"marker"|None — the ctx key the seam
        # matches against
        self.arg = arg  # int seq / str stage-substring / ANY / None
        self.count = count  # firings remaining
        self.fired = 0

    def matches(self, ctx: dict) -> bool:
        if self.count <= 0:
            return False
        if self.trigger is None:
            return True
        if self.trigger not in ctx:
            return False
        if self.arg is ANY:
            return True
        v = ctx[self.trigger]
        if isinstance(self.arg, str):
            return self.arg in str(v)
        return v == self.arg

    def spend(self) -> None:
        self.count -= 1
        self.fired += 1

    def describe(self) -> str:
        if self.trigger is None:
            return self.kind
        if self.arg is ANY:
            return f"{self.kind}@{self.trigger}"
        return f"{self.kind}@{self.trigger}:{self.arg}"


class ChaosPlan:
    """Parsed OCT_CHAOS spec + the per-seam sequence counters."""

    def __init__(self, injections: list[_Injection], seed: int):
        self.injections = injections
        self.seed = seed
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._by_site: dict[str, list[_Injection]] = {}
        for inj in injections:
            for site in _KIND_SITES[inj.kind]:
                self._by_site.setdefault(site, []).append(inj)

    def next_seq(self, site: str) -> int:
        with self._lock:
            n = self._counters.get(site, 0)
            self._counters[site] = n + 1
            return n

    def for_site(self, site: str) -> list[_Injection]:
        return self._by_site.get(site, ())

    def fired(self) -> list[str]:
        return [i.describe() for i in self.injections if i.fired]


def parse_spec(spec: str) -> list[_Injection]:
    """Parse the OCT_CHAOS grammar; raises ValueError on a malformed
    spec — an unparseable chaos plan must fail LOUDLY, a typo'd fault
    that silently never fires would fake a green chaos matrix."""
    out: list[_Injection] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, tail = part.partition("@")
        kind = kind.strip()
        if kind not in _KIND_SITES:
            raise ValueError(
                f"OCT_CHAOS: unknown fault kind {kind!r} "
                f"(know {', '.join(FAULT_KINDS)})"
            )
        trigger: str | None = None
        arg = None
        count = 1
        if tail and kind == "probe-timeout":
            # a trigger clause here would be SILENTLY unhonored
            # (probe_timeout_pending spends injections in list order) —
            # reject it loudly instead of misplacing the fault
            raise ValueError(
                "OCT_CHAOS: probe-timeout takes no @trigger clause "
                "(list it N times to kill N attempts)"
            )
        if tail:
            trigger, _, argtxt = tail.partition(":")
            trigger = trigger.strip()
            argtxt = argtxt.strip()
            if "x" in argtxt and argtxt.rsplit("x", 1)[1].isdigit():
                argtxt, _, n = argtxt.rpartition("x")
                count = int(n)
            if not argtxt and kind == "partial-rename" and trigger == "marker":
                # the documented no-arg form: ANY marker write (there
                # is normally exactly one — the clean-shutdown marker)
                arg = ANY
            elif not trigger or not argtxt:
                # an empty arg would parse as the match-ANYTHING ''
                # substring — a silently mis-placed fault, exactly what
                # the fail-loud rule exists to prevent
                raise ValueError(
                    f"OCT_CHAOS: {part!r} has an empty trigger or arg "
                    "(want <fault>@<trigger>:<arg>)"
                )
            else:
                arg = int(argtxt) if argtxt.lstrip("-").isdigit() else argtxt
            if trigger == "epoch":  # chunk index stands in for epoch
                trigger = "chunk"
        elif kind == "probe-timeout":
            trigger, arg, count = "attempt", None, 1
        else:
            raise ValueError(
                f"OCT_CHAOS: fault {kind!r} needs a @trigger:arg clause"
            )
        if arg is not None and trigger is not None:
            satisfiable = {
                k for site in _KIND_SITES[kind]
                for k in _SITE_TRIGGER_KEYS.get(site, ())
            }
            if trigger not in satisfiable:
                raise ValueError(
                    f"OCT_CHAOS: {part!r} can never fire — trigger "
                    f"{trigger!r} is not provided at any {kind!r} seam "
                    f"(know: {', '.join(sorted(satisfiable))})"
                )
        out.append(_Injection(kind, trigger if arg is not None else None,
                              arg, count))
    return out


_ARMED = False
_PLAN: ChaosPlan | None = None
_RNG: random.Random | None = None


def _load() -> None:
    global _ARMED, _PLAN, _RNG
    spec = os.environ.get(_ENV, "")
    seed = int(os.environ.get(_SEED_ENV, "0") or 0)
    _RNG = random.Random(seed)
    if not spec:
        _ARMED, _PLAN = False, None
        return
    _PLAN = ChaosPlan(parse_spec(spec), seed)
    _ARMED = True


_load()


def reset() -> None:
    """Re-read OCT_CHAOS / OCT_CHAOS_SEED and zero every counter
    (tests arm/disarm per case; production reads the env once)."""
    _load()


def armed() -> bool:
    return _ARMED


def plan() -> ChaosPlan | None:
    return _PLAN


def rng() -> random.Random:
    """The seeded RNG — backoff jitter determinism for consumers
    (obs/recovery.py, bench probe), never fault placement."""
    assert _RNG is not None
    return _RNG


def jitter() -> float:
    """The one backoff-jitter policy every recovery consumer shares
    (obs/recovery.RecoverySupervisor, bench's probe retries): a
    multiplicative factor in [1.0, 1.5), drawn from the seeded chaos
    RNG when armed — reproducible recovery timing under a seeded fault
    plan — and the process RNG otherwise."""
    r = rng() if _ARMED else random
    return 1.0 + 0.5 * r.random()


def stall_s() -> float:
    try:
        return float(os.environ.get(_STALL_ENV, "0.2"))
    except ValueError:
        return 0.2


def _execute(inj: _Injection, site: str, ctx: dict) -> None:
    inj.spend()
    where = f"{site} {ctx}" if ctx else site
    if inj.kind == "compile-stall":
        time.sleep(stall_s())
        return
    if inj.kind == "device-error":
        raise DeviceChaosError(f"chaos: injected device error at {where}")
    if inj.kind == "staging-thread-death":
        raise StagingChaosError(f"chaos: staging producer died at {where}")
    if inj.kind == "chunk-corrupt":
        raise ChunkChaosError(f"chaos: chunk read corrupted at {where}")
    if inj.kind == "aot-reject":
        raise AotRejectChaos(str(ctx.get("stage", "?")))
    if inj.kind == "partial-rename":
        # the marker writer already wrote (and fsynced) the tmp file;
        # raising HERE models the crash between tmp and rename — the
        # durable tmp survives, the final marker never appears
        raise PartialRenameChaos(
            f"chaos: marker rename died at {where}"
        )
    if inj.kind == "sigkill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    # probe-timeout is consumed by bench.probe_device via
    # probe_timeout_pending(), never raised at a seam


# which trigger keys each seam's OWN sequence counter may answer for:
# a seam only ever defaults its canonical aliases, so an injection
# whose trigger names ANOTHER seam (device-error@dispatch:N vs the
# stage-call seam both sites of the same fault kind) can never match
# off this seam's counter — the spec and the per-seam counters fully
# determine WHERE every fault lands, which is the module's contract
_SITE_SEQ_KEYS = {
    "dispatch": ("window", "dispatch"),  # one dispatch per window
    "stage": ("window",),  # prepare_window: one staging per window
    "retire": ("window",),  # one retire per window
    "shard": ("shard",),
    "chunk": ("chunk",),
    "append": ("append",),  # one block append per seq (write_fault);
    # the CHUNK NUMBER rides the explicit chunk= ctx, so bitflip@chunk:N
    # and index-truncate@epoch:N place by chunk, torn-write@append:N and
    # sigkill@append:N by append order
    # "stage-call" / "aot" match only on the explicit stage= ctx;
    # "marker" matches only on the explicit marker= ctx;
    # "probe" is consumed via probe_timeout_pending()
    "sidecar-build": ("build",),  # one sidecar build per seq; the
    # CHUNK NUMBER rides the explicit chunk= ctx (sidecar-torn@chunk:N)
    "sidecar-open": ("open",),  # one freshness probe per seq
    "forge": ("forge",),  # one forged-block retire per seq
    "forge-dispatch": ("forge-dispatch",),  # one election dispatch/seq
    "serve": ("serve",),  # one serving-window checkpoint per seq
    "serve-dispatch": ("serve-dispatch",),  # one shared window per seq
}


def _match(site: str, ctx: dict):
    """THE injection matcher — one implementation of the semantics
    every seam shares (armed check, per-site plan lookup, sequence
    advance, _SITE_SEQ_KEYS defaulting, first un-spent match). Returns
    ``(injection, seq)`` or None; the caller decides what a match DOES
    (fire() executes it, write_fault() hands its kind to the writer).
    The sequence counter only advances when the plan has injections at
    this site, so a disarmed or unrelated run never drifts counters."""
    if not _ARMED:
        return None
    p = _PLAN
    if p is None:
        return None
    injections = p.for_site(site)
    if not injections:
        return None
    seq = p.next_seq(site)
    full = dict(ctx)
    for k in _SITE_SEQ_KEYS.get(site, ()):
        full.setdefault(k, seq)
    for inj in injections:
        if inj.matches(full):
            return inj, seq
    return None


def fire(site: str, **ctx) -> None:
    """The one seam entry point. Cheap no-op disarmed (module bool);
    armed, the first matching un-spent injection (`_match`) is
    executed — raise / sleep / kill per its fault kind."""
    m = _match(site, ctx)
    if m is not None:
        inj, seq = m
        _execute(inj, site, ctx or {"seq": seq})


def write_fault(**ctx) -> str | None:
    """The chunk writer's seam (`ImmutableDB.append_block`): matching
    identical to `fire()` at the ``append`` site (`_match`), but the
    injection's KIND is returned instead of executed — the writer owns
    the disk-mutation semantics (a torn prefix for ``torn-write``, a
    flipped byte for ``bitflip``, a torn index entry for
    ``index-truncate``, a SIGKILL between the chunk and index appends
    for ``sigkill@append``). None = no fault this append."""
    m = _match("append", ctx)
    if m is None:
        return None
    inj, _seq = m
    inj.spend()
    return inj.kind


def sidecar_fault(site: str, **ctx) -> str | None:
    """The columnar-sidecar seams (`storage/sidecar.write_sidecar` at
    ``sidecar-build``, `load_sidecar` at ``sidecar-open``): matching
    identical to `fire()` (`_match`), but the injection's KIND is
    returned instead of executed — the sidecar module owns the
    semantics (a torn prefix at the final name for ``sidecar-torn``, a
    SIGKILL between tmp and rename for ``sigkill@build``, a forced
    stale verdict for ``sidecar-stale``). None = no fault here."""
    m = _match(site, ctx)
    if m is None:
        return None
    inj, _seq = m
    inj.spend()
    return inj.kind


def probe_timeout_pending() -> bool:
    """bench.probe_device's seam: True (and one injection consumed)
    when the next probe attempt should hang past its timeout."""
    if not _ARMED or _PLAN is None:
        return False
    for inj in _PLAN.for_site("probe"):
        if inj.count > 0:
            inj.spend()
            return True
    return False
