"""Praos credential + header-forging fixtures (host, sign-side).

Used by the test suite and by tools/db_synthesizer to forge valid chains.
Mirrors the data the reference's `db-synthesizer` loads from credential
files (Tools/DBSynthesizer/Run.hs) — cold Ed25519 key, VRF key, KES tree —
but generated deterministically from integer seeds.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from functools import cached_property

from ..ops.host import ecvrf as hv
from ..ops.host import ed25519 as he
from ..ops.host import fast
from ..ops.host import kes as hk
from ..protocol import nonces
from ..protocol.praos import PraosCanBeLeader, PraosParams
from ..protocol.views import (
    HeaderView,
    IndividualPoolStake,
    LedgerView,
    OCert,
    hash_key,
    hash_vrf_vk,
)


def _seed(tag: bytes, n: int) -> bytes:
    from ..ops.host.hashes import blake2b_256

    return blake2b_256(tag + n.to_bytes(8, "big"))


@dataclass(frozen=True)
class PoolCredentials:
    """One pool's full signing identity."""

    cold_seed: bytes
    vrf_seed: bytes
    kes_seed: bytes
    kes_depth: int

    # cached: the seeds are frozen, and each derivation is a scalar
    # multiplication — forging consults these every slot
    @cached_property
    def vk_cold(self) -> bytes:
        return fast.ed25519_public(self.cold_seed)

    @cached_property
    def vrf_vk(self) -> bytes:
        return fast.ed25519_public(self.vrf_seed)  # VRF uses Ed25519 keys

    @cached_property
    def kes_vk(self) -> bytes:
        return hk.derive_vk(self.kes_seed, self.kes_depth)

    @cached_property
    def pool_id(self) -> bytes:
        return hash_key(self.vk_cold)

    def make_ocert(self, counter: int, kes_period: int) -> OCert:
        oc = OCert(self.kes_vk, counter, kes_period, b"")
        sig = fast.ed25519_sign(self.cold_seed, oc.signable())
        return OCert(self.kes_vk, counter, kes_period, sig)


def make_pool(n: int, kes_depth: int = hk.DEFAULT_DEPTH) -> PoolCredentials:
    return PoolCredentials(
        _seed(b"cold", n), _seed(b"vrf", n), _seed(b"kes", n), kes_depth
    )


def make_ledger_view(pools: list[PoolCredentials], stakes=None) -> LedgerView:
    if stakes is None:
        stakes = [Fraction(1, len(pools))] * len(pools)
    return LedgerView(
        pool_distr={
            p.pool_id: IndividualPoolStake(s, hash_vrf_vk(p.vrf_vk))
            for p, s in zip(pools, stakes)
        }
    )


def can_be_leader(pool: PoolCredentials, counter: int = 0, kes_period: int = 0) -> PraosCanBeLeader:
    return PraosCanBeLeader(
        ocert=pool.make_ocert(counter, kes_period),
        vk_cold=pool.vk_cold,
        vrf_sign_seed=pool.vrf_seed,
    )


def find_leader(
    params: PraosParams,
    pools: list[PoolCredentials],
    lview: LedgerView,
    slot: int,
    epoch_nonce: nonces.Nonce,
) -> PoolCredentials | None:
    """First pool (by list order) winning the leader check for `slot`,
    decided by the protocol's own check_is_leader (no re-implementation)."""
    from ..protocol import praos as praos_mod

    ticked = praos_mod.TickedPraosState(
        praos_mod.PraosState(epoch_nonce=epoch_nonce), lview
    )
    for pool in pools:
        if (
            praos_mod.check_is_leader(params, can_be_leader(pool), slot, ticked)
            is not None
        ):
            return pool
    return None


def forge_header_view(
    params: PraosParams,
    pool: PoolCredentials,
    slot: int,
    epoch_nonce: nonces.Nonce,
    prev_hash: bytes | None,
    body_bytes: bytes = b"",
    ocert_counter: int = 0,
) -> HeaderView:
    """Forge a protocol-valid HeaderView for `slot` (ignores leader check —
    callers wanting realistic chains should first consult check_is_leader).

    `body_bytes` stands in for the KES-signed header-body serialisation
    until the real codec (block/) is wired; validation only sees bytes.
    """
    alpha = nonces.mk_input_vrf(slot, epoch_nonce)
    proof = fast.ecvrf_prove(pool.vrf_seed, alpha)
    output = fast.ecvrf_proof_to_hash(proof)
    kp = params.kes_period_of(slot)
    ocert = pool.make_ocert(ocert_counter, kp)
    t = 0  # ocert issued for the current period: evolution index 0
    kes_sig = hk.sign(pool.kes_seed, pool.kes_depth, t, body_bytes)
    return HeaderView(
        prev_hash=prev_hash,
        vk_cold=pool.vk_cold,
        vrf_vk=pool.vrf_vk,
        vrf_output=output,
        vrf_proof=proof,
        ocert=ocert,
        slot=slot,
        signed_bytes=body_bytes,
        kes_sig=kes_sig,
    )
