"""Version negotiation: NetworkProtocolVersion + the handshake exchange.

Reference: `Ouroboros.Consensus.Node.NetworkProtocolVersion` — each block
type declares its supported `NodeToNodeVersion`s / `NodeToClientVersion`s
and the codec behavior per version; the network layer's handshake
protocol picks the highest version both ends support and exchanges
version data (network magic, diffusion mode — `stdVersionDataNTN`,
diffusion Node.hs).

Pure negotiation + sim-task client/server; the asyncio transports use
`negotiate` on their first exchange.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.sim import Recv, Send

# NodeToNodeVersion analog: what each wire version enables. Version
# gates mirror the reference's capability progression (tx-submission2,
# peer sharing arriving in later versions).
NODE_TO_NODE_VERSIONS: dict[int, frozenset] = {
    1: frozenset({"chainsync", "blockfetch"}),
    2: frozenset({"chainsync", "blockfetch", "txsubmission2", "keepalive"}),
    3: frozenset(
        {"chainsync", "blockfetch", "txsubmission2", "keepalive", "peersharing"}
    ),
}

NODE_TO_CLIENT_VERSIONS: dict[int, frozenset] = {
    1: frozenset({"localstatequery", "localtxsubmission"}),
    2: frozenset({"localstatequery", "localtxsubmission", "localtxmonitor"}),
    # v3 extends only the QUERY vocabulary (the Shelley ledger queries,
    # localstate.QUERY_MIN_VERSION) — same protocol set as v2
    3: frozenset({"localstatequery", "localtxsubmission", "localtxmonitor"}),
    # v4 adds the local ChainSync over WHOLE BLOCKS — the wallet
    # protocol (Network/NodeToClient.hs:92-121 chainSyncBlocksServer)
    4: frozenset({
        "localstatequery", "localtxsubmission", "localtxmonitor",
        "localchainsync",
    }),
}


@dataclass(frozen=True)
class VersionData:
    """stdVersionDataNTN: networkMagic guards against cross-net connects
    (the DbMarker check's wire-level sibling)."""

    network_magic: int


class HandshakeRefused(Exception):
    pass


def negotiate(
    ours: dict[int, VersionData], theirs_proposal: dict[int, VersionData]
) -> tuple[int, VersionData]:
    """Highest common version with matching magic, or HandshakeRefused."""
    common = sorted(set(ours) & set(theirs_proposal), reverse=True)
    if not common:
        raise HandshakeRefused(
            f"no common version: ours {sorted(ours)}, theirs "
            f"{sorted(theirs_proposal)}"
        )
    v = common[0]
    if ours[v].network_magic != theirs_proposal[v].network_magic:
        raise HandshakeRefused(
            f"network magic mismatch at v{v}: "
            f"{ours[v].network_magic} != {theirs_proposal[v].network_magic}"
        )
    return v, ours[v]


def client(rx, tx, versions: dict[int, VersionData]):
    """Propose all our versions; the server picks (handshake initiator)."""
    yield Send(tx, ("propose_versions", versions))
    msg = yield Recv(rx)
    if msg[0] == "refuse":
        raise HandshakeRefused(msg[1])
    if msg[0] != "accept_version":
        raise HandshakeRefused(f"bad handshake reply {msg[0]!r}")
    version, data = msg[1], msg[2]
    if version not in versions:
        raise HandshakeRefused(f"server accepted unknown version {version}")
    return version, data


def server(rx, tx, versions: dict[int, VersionData]):
    """Accept the highest common version or refuse."""
    msg = yield Recv(rx)
    if msg[0] != "propose_versions":
        yield Send(tx, ("refuse", f"expected propose_versions, got {msg[0]!r}"))
        raise HandshakeRefused(f"bad first message {msg[0]!r}")
    try:
        version, data = negotiate(versions, msg[1])
    except HandshakeRefused as e:
        yield Send(tx, ("refuse", str(e)))
        raise
    yield Send(tx, ("accept_version", version, data))
    return version, data
