"""BlockFetch mini-protocol: download bodies for preferred candidates.

Reference: `MiniProtocol/BlockFetch/{ClientInterface,Server}.hs` plus the
fetch-decision logic the consensus layer feeds (preferAnchoredCandidate:
only fetch candidates strictly better than our chain by the protocol's
SelectView order). The full network-layer fetch governor (multi-peer
de-duplication, in-flight limits) is out of scope for the sim harness —
one fetch client per peer requests the candidate suffix it is missing
and pushes completed blocks into the ChainDB (addBlockAsync sink,
ClientInterface.hs mkBlockFetchConsensusInterface).

Wire messages:
  client → server: ("request_range", Point_from_exclusive|None, Point_to)
                   ("done",)
  server → client: ("start_batch",) ("block", block_bytes) ("batch_done",)
                   ("no_blocks",)
"""

from __future__ import annotations

from ..block.abstract import Point
from ..block.praos_block import Block
from ..utils.sim import Recv, Send, Sleep, Wait


class InvalidBlockFromPeer(Exception):
    """The peer served a block chain selection marked invalid: punished
    by disconnection (InvalidBlockPunishment.hs; RethrowPolicy maps this
    to 'disconnect', not node shutdown)."""

    def __init__(self, peer: str, point):
        super().__init__(f"peer {peer}: invalid block at {point}")
        self.peer = peer
        self.point = point


def _in_immutable(chain_db, point: Point) -> bool:
    imm = getattr(chain_db, "immutable", None)
    if imm is None or point is None:
        return False
    try:
        imm.get_block_bytes(point)
        return True
    except Exception:
        return False


def _range_stream(chain_db, _from: Point | None, to: Point):
    """Lazy iterator of blocks strictly after `_from` up to+incl `to`,
    walking the immutable segment first, then the volatile fragment —
    or None when the range isn't on our chain. A far-behind peer's
    fetch range mostly lives in the ImmutableDB (the ChainSync server
    serves headers from there), so bodies must come from there too."""
    vol = list(chain_db.current_chain)
    vol_idx = {b.point: i for i, b in enumerate(vol)}
    # the endpoint must be ours, else the chain switched away
    if to not in vol_idx and not _in_immutable(chain_db, to):
        return None

    if _from in vol_idx:
        start = vol_idx[_from] + 1
        imm_iter = None
    elif _from is None or _from == chain_db._anchor_point() or _in_immutable(
        chain_db, _from
    ):
        start = 0
        imm = getattr(chain_db, "immutable", None)
        if imm is None or _from == chain_db._anchor_point():
            imm_iter = None
        elif _from is None:
            imm_iter = imm.stream_all()
        else:
            imm_iter = imm.stream_from(_from.slot)
    else:
        return None

    decode = getattr(chain_db, "decode_block", Block.from_bytes)

    def gen():
        if imm_iter is not None:
            for _e, raw in imm_iter:
                b = decode(raw)
                yield b
                if b.point == to:
                    return
        for b in vol[start:]:
            yield b
            if b.point == to:
                return

    return gen()


def server(chain_db, rx, tx):
    """Serve block bodies from the ChainDB (Server.hs) — immutable part
    included (see _range_stream)."""
    while True:
        msg = yield Recv(rx)
        if msg[0] == "done":
            return
        if msg[0] != "request_range":
            raise RuntimeError(f"blockfetch server: bad message {msg[0]!r}")
        stream = _range_stream(chain_db, msg[1], msg[2])
        first = next(stream, None) if stream is not None else None
        if first is None:
            # the chain may have switched away from the candidate
            yield Send(tx, ("no_blocks",))
            continue
        yield Send(tx, ("start_batch",))
        yield Send(tx, ("block", first.bytes_))
        for b in stream:
            yield Send(tx, ("block", b.bytes_))
        yield Send(tx, ("batch_done",))


def client(node, peer_name: str, rx, tx, candidate, *, poll_interval: float = 0.05, rounds: int | None = None):
    """Fetch-decision + download loop for one peer.

    Watches the peer's ChainSync candidate; when the candidate is
    preferred over our current chain (longer per PraosChainSelectView —
    via node.protocol.compare_candidates on select views), requests the
    missing suffix and feeds blocks to the ChainDB.
    """
    done = 0
    while rounds is None or done < rounds:
        headers = list(candidate.headers)
        if not headers:
            yield Sleep(poll_interval)
            done += 1
            continue
        # fetch only headers we don't already have on our chain
        have = {b.hash_ for b in node.chain_db.current_chain}
        missing = [h for h in headers if h.hash_ not in have]
        if not missing:
            yield Sleep(poll_interval)
            done += 1
            continue
        if not node.prefer_candidate(headers):
            yield Sleep(poll_interval)
            done += 1
            continue
        frm = missing[0].prev_hash
        frm_point = None
        if frm is not None:
            # the fetch range anchor: the predecessor's point
            for h in headers:
                if h.hash_ == frm:
                    frm_point = h.point
                    break
            if frm_point is None:
                for b in node.chain_db.current_chain:
                    if b.hash_ == frm:
                        frm_point = b.point
                        break
        yield Send(tx, ("request_range", frm_point, missing[-1].point))
        msg = yield Recv(rx)
        if msg[0] == "no_blocks":
            yield Sleep(poll_interval)
            done += 1
            continue
        assert msg[0] == "start_batch", msg
        while True:
            msg = yield Recv(rx)
            if msg[0] == "batch_done":
                break
            assert msg[0] == "block", msg
            # decode with the node's block codec (era-tagged bytes for
            # HFC nets; the plain Praos block otherwise)
            block = node.chain_db.decode_block(msg[1])
            # enqueue to the add-block runner (decoupled mode: peer
            # tasks never run chain selection themselves) and wait for
            # the verdict; synchronous mode completes inline
            p = node.chain_db.add_block_async(block)
            if p.result is None:
                yield Wait(p.processed)
            if node.chain_db.get_is_invalid_block(block.hash_) is not None:
                # InvalidBlockPunishment (ChainSel.hs:1084-1099 +
                # InvalidBlockPunishment.hs): the peer served a block
                # that failed validation — disconnect it (the task ends;
                # the rethrow policy's 'disconnect peer' class)
                raise InvalidBlockFromPeer(peer_name, block.point)
            if p.result.selected:
                node.on_chain_changed()
                # adoption settles candidate prefixes: the ChainSync
                # history may now trim down to k (HeaderStateHistory)
                candidate.trim()
        done += 1
