"""BlockFetch mini-protocol: download bodies for preferred candidates.

Reference: `MiniProtocol/BlockFetch/{ClientInterface,Server}.hs` plus the
fetch-decision logic the consensus layer feeds:

  * preferAnchoredCandidate — only fetch candidates strictly better than
    our chain by the protocol's SelectView order;
  * FetchMode (readFetchModeDefault, ClientInterface.hs:133-158): when
    the current chain's tip is < 1000 slots behind "now" the governor
    runs in DEADLINE mode (latency first — fetch the whole preferred
    suffix, duplicate fetches across peers are acceptable); further
    behind it runs in BULK-SYNC mode (throughput first — bounded batch
    sizes, and blocks already in flight from one peer are NOT requested
    from another);
  * in-flight limits — each per-peer client keeps at most ONE range
    outstanding (the reference caps in-flight reqs/bytes per peer;
    strict sequencing is the conservative instance of that cap), and
    bulk-sync ranges are capped at `max_fetch_batch` blocks;
  * multi-peer de-duplication — the node-level `FetchRegistry` (the
    FetchClientRegistry analog) tracks which peer has claimed which
    block; bulk-sync clients skip already-claimed blocks and release
    their claims on completion or disconnection.

Wire messages:
  client → server: ("request_range", Point_from_exclusive|None, Point_to)
                   ("done",)
  server → client: ("start_batch",) ("block", block_bytes) ("batch_done",)
                   ("no_blocks",)
"""

from __future__ import annotations

from ..block.abstract import Point
from ..block.praos_block import Block
from ..utils.sim import Recv, Send, Sleep, Wait

# readFetchModeDefault's threshold (ClientInterface.hs:151)
MAX_SLOTS_BEHIND = 1000

BULK_SYNC = "bulk_sync"
DEADLINE = "deadline"


def read_fetch_mode(node, max_slots_behind: int = MAX_SLOTS_BEHIND) -> str:
    """readFetchModeDefault (ClientInterface.hs:133-158): compare the
    current chain's tip slot against the wallclock slot; < 1000 slots
    behind -> deadline mode, else bulk sync. With no runtime clock
    (CurrentSlotUnknown) the reference picks bulk sync."""
    runtime = getattr(node.chain_db, "runtime", None)
    clock = getattr(node, "clock", None)
    if runtime is None or clock is None or not hasattr(runtime, "now"):
        return BULK_SYNC
    cur_slot = clock.slot_of(runtime.now)
    tip = node.chain_db.tip_point()
    slots_behind = cur_slot + 1 if tip is None else cur_slot - tip.slot
    return DEADLINE if slots_behind < max_slots_behind else BULK_SYNC


class FetchRegistry:
    """Node-level in-flight block claims (FetchClientRegistry analog):
    bulk-sync clients claim the blocks of a range before requesting it,
    so the same bodies are never downloaded from two peers at once."""

    def __init__(self):
        self._claims: dict[bytes, str] = {}  # block hash -> peer name

    def claim(self, h: bytes, peer: str) -> bool:
        owner = self._claims.setdefault(h, peer)
        return owner == peer

    def release(self, h: bytes) -> None:
        self._claims.pop(h, None)

    def release_peer(self, peer: str) -> None:
        for h in [h for h, p in self._claims.items() if p == peer]:
            del self._claims[h]

    def owner(self, h: bytes) -> str | None:
        return self._claims.get(h)


class InvalidBlockFromPeer(Exception):
    """The peer served a block chain selection marked invalid: punished
    by disconnection (InvalidBlockPunishment.hs; RethrowPolicy maps this
    to 'disconnect', not node shutdown)."""

    def __init__(self, peer: str, point):
        super().__init__(f"peer {peer}: invalid block at {point}")
        self.peer = peer
        self.point = point


def _in_immutable(chain_db, point: Point) -> bool:
    imm = getattr(chain_db, "immutable", None)
    if imm is None or point is None:
        return False
    try:
        imm.get_block_bytes(point)
        return True
    except Exception:
        return False


def _range_stream(chain_db, _from: Point | None, to: Point):
    """Lazy iterator of blocks strictly after `_from` up to+incl `to`,
    walking the immutable segment first, then the volatile fragment —
    or None when the range isn't on our chain. A far-behind peer's
    fetch range mostly lives in the ImmutableDB (the ChainSync server
    serves headers from there), so bodies must come from there too."""
    vol = list(chain_db.current_chain)
    vol_idx = {b.point: i for i, b in enumerate(vol)}
    # the endpoint must be ours, else the chain switched away
    if to not in vol_idx and not _in_immutable(chain_db, to):
        return None

    if _from in vol_idx:
        start = vol_idx[_from] + 1
        imm_iter = None
    elif _from is None or _from == chain_db._anchor_point() or _in_immutable(
        chain_db, _from
    ):
        start = 0
        imm = getattr(chain_db, "immutable", None)
        if imm is None or _from == chain_db._anchor_point():
            imm_iter = None
        elif _from is None:
            imm_iter = imm.stream_all()
        else:
            imm_iter = imm.stream_from(_from.slot)
    else:
        return None

    decode = getattr(chain_db, "decode_block", Block.from_bytes)

    def gen():
        if imm_iter is not None:
            for _e, raw in imm_iter:
                b = decode(raw)
                yield b
                if b.point == to:
                    return
        for b in vol[start:]:
            yield b
            if b.point == to:
                return

    return gen()


def server(chain_db, rx, tx):
    """Serve block bodies from the ChainDB (Server.hs) — immutable part
    included (see _range_stream)."""
    while True:
        msg = yield Recv(rx)
        if msg[0] == "done":
            return
        if msg[0] != "request_range":
            raise RuntimeError(f"blockfetch server: bad message {msg[0]!r}")
        stream = _range_stream(chain_db, msg[1], msg[2])
        first = next(stream, None) if stream is not None else None
        if first is None:
            # the chain may have switched away from the candidate
            yield Send(tx, ("no_blocks",))
            continue
        yield Send(tx, ("start_batch",))
        yield Send(tx, ("block", first.bytes_))
        for b in stream:
            yield Send(tx, ("block", b.bytes_))
        yield Send(tx, ("batch_done",))


def _anchor_point_of(node, headers, first_missing):
    """The fetch range anchor: the first missing header's predecessor."""
    frm = first_missing.prev_hash
    if frm is None:
        return None
    for h in headers:
        if h.hash_ == frm:
            return h.point
    for b in node.chain_db.current_chain:
        if b.hash_ == frm:
            return b.point
    return None


def client(node, peer_name: str, rx, tx, candidate, *,
           poll_interval: float = 0.05, rounds: int | None = None,
           max_fetch_batch: int = 64,
           max_slots_behind: int = MAX_SLOTS_BEHIND):
    """Fetch-decision + download loop for one peer.

    Watches the peer's ChainSync candidate; when the candidate is
    preferred over our current chain (longer per PraosChainSelectView —
    via node.protocol.compare_candidates on select views), requests the
    missing suffix and feeds blocks to the ChainDB. The decision follows
    the FetchMode (module docstring): deadline mode fetches the whole
    preferred suffix; bulk-sync mode claims bounded batches through the
    node's FetchRegistry so concurrent peers never download the same
    bodies. At most one range is outstanding per peer (in-flight cap).
    """
    registry = getattr(node, "fetch_registry", None)
    claimed: list[bytes] = []
    try:
        yield from _client_loop(
            node, peer_name, rx, tx, candidate, poll_interval, rounds,
            max_fetch_batch, max_slots_behind, registry, claimed,
        )
    finally:
        # a dying client (disconnect/punishment) releases its claims so
        # other peers can pick the blocks up
        if registry is not None:
            registry.release_peer(peer_name)


def _client_loop(node, peer_name, rx, tx, candidate, poll_interval, rounds,
                 max_fetch_batch, max_slots_behind, registry, claimed):
    done = 0
    while rounds is None or done < rounds:
        headers = list(candidate.headers)
        if not headers:
            yield Sleep(poll_interval)
            done += 1
            continue
        # fetch only headers whose bodies we don't already HAVE — stored
        # counts (volatile included), not just selected: a body another
        # peer delivered moments ago must not be fetched again while
        # chain selection catches up
        have = {b.hash_ for b in node.chain_db.current_chain}
        missing = [
            h for h in headers
            if h.hash_ not in have and node.chain_db.get_block(h.point) is None
        ]
        if not missing:
            yield Sleep(poll_interval)
            done += 1
            continue
        if not node.prefer_candidate(headers):
            yield Sleep(poll_interval)
            done += 1
            continue

        mode = read_fetch_mode(node, max_slots_behind)
        if mode == BULK_SYNC and registry is not None:
            # skip blocks another peer already has in flight; claim a
            # bounded contiguous batch starting at our first fetchable
            start = 0
            while start < len(missing) and not registry.claim(
                missing[start].hash_, peer_name
            ):
                start += 1
            if start == len(missing):
                # everything in flight elsewhere: wait for it to land
                yield Sleep(poll_interval)
                done += 1
                continue
            batch = [missing[start]]
            claimed.append(missing[start].hash_)
            for h in missing[start + 1 : start + max_fetch_batch]:
                if not registry.claim(h.hash_, peer_name):
                    break
                claimed.append(h.hash_)
                batch.append(h)
            first, last = batch[0], batch[-1]
        else:
            # deadline mode: latency first — the whole preferred suffix,
            # even if another peer is fetching the same blocks
            first, last = missing[0], missing[-1]

        frm_point = _anchor_point_of(node, headers, first)
        yield Send(tx, ("request_range", frm_point, last.point))
        msg = yield Recv(rx)
        if msg[0] == "no_blocks":
            _release(registry, claimed)
            yield Sleep(poll_interval)
            done += 1
            continue
        assert msg[0] == "start_batch", msg
        while True:
            msg = yield Recv(rx)
            if msg[0] == "batch_done":
                break
            assert msg[0] == "block", msg
            # decode with the node's block codec (era-tagged bytes for
            # HFC nets; the plain Praos block otherwise)
            block = node.chain_db.decode_block(msg[1])
            # enqueue to the add-block runner (decoupled mode: peer
            # tasks never run chain selection themselves) and wait for
            # the verdict; synchronous mode completes inline
            p = node.chain_db.add_block_async(block)
            if p.result is None:
                yield Wait(p.processed)
            if registry is not None:
                registry.release(block.hash_)
                if block.hash_ in claimed:
                    claimed.remove(block.hash_)
            if node.chain_db.get_is_invalid_block(block.hash_) is not None:
                # InvalidBlockPunishment (ChainSel.hs:1084-1099 +
                # InvalidBlockPunishment.hs): the peer served a block
                # that failed validation — disconnect it (the task ends;
                # the rethrow policy's 'disconnect peer' class)
                raise InvalidBlockFromPeer(peer_name, block.point)
            if p.result.selected:
                node.on_chain_changed()
                # adoption settles candidate prefixes: the ChainSync
                # history may now trim down to k (HeaderStateHistory)
                candidate.trim()
        _release(registry, claimed)
        done += 1


def _release(registry, claimed):
    if registry is not None:
        for h in claimed:
            registry.release(h)
        claimed.clear()
