"""BlockFetch mini-protocol: download bodies for preferred candidates.

Reference: `MiniProtocol/BlockFetch/{ClientInterface,Server}.hs` plus the
fetch-decision logic the consensus layer feeds (preferAnchoredCandidate:
only fetch candidates strictly better than our chain by the protocol's
SelectView order). The full network-layer fetch governor (multi-peer
de-duplication, in-flight limits) is out of scope for the sim harness —
one fetch client per peer requests the candidate suffix it is missing
and pushes completed blocks into the ChainDB (addBlockAsync sink,
ClientInterface.hs mkBlockFetchConsensusInterface).

Wire messages:
  client → server: ("request_range", Point_from_exclusive|None, Point_to)
                   ("done",)
  server → client: ("start_batch",) ("block", block_bytes) ("batch_done",)
                   ("no_blocks",)
"""

from __future__ import annotations

from ..block.abstract import Point
from ..block.praos_block import Block
from ..utils.sim import Recv, Send, Sleep


def server(chain_db, rx, tx):
    """Serve block bodies from the ChainDB (Server.hs)."""
    while True:
        msg = yield Recv(rx)
        if msg[0] == "done":
            return
        if msg[0] != "request_range":
            raise RuntimeError(f"blockfetch server: bad message {msg[0]!r}")
        _from, to = msg[1], msg[2]
        # collect the requested window from our chain (volatile part —
        # candidates only ever reference recent blocks)
        chain = list(chain_db.current_chain)
        out = []
        seen_from = _from is None
        for b in chain:
            if not seen_from:
                if b.point == _from:
                    seen_from = True
                continue
            out.append(b)
            if b.point == to:
                break
        else:
            if out and out[-1].point != to:
                out = []
        if not out:
            # the chain may have switched away from the candidate
            yield Send(tx, ("no_blocks",))
            continue
        yield Send(tx, ("start_batch",))
        for b in out:
            yield Send(tx, ("block", b.bytes_))
        yield Send(tx, ("batch_done",))


def client(node, peer_name: str, rx, tx, candidate, *, poll_interval: float = 0.05, rounds: int | None = None):
    """Fetch-decision + download loop for one peer.

    Watches the peer's ChainSync candidate; when the candidate is
    preferred over our current chain (longer per PraosChainSelectView —
    via node.protocol.compare_candidates on select views), requests the
    missing suffix and feeds blocks to the ChainDB.
    """
    done = 0
    while rounds is None or done < rounds:
        headers = list(candidate.headers)
        if not headers:
            yield Sleep(poll_interval)
            done += 1
            continue
        # fetch only headers we don't already have on our chain
        have = {b.hash_ for b in node.chain_db.current_chain}
        missing = [h for h in headers if h.hash_ not in have]
        if not missing:
            yield Sleep(poll_interval)
            done += 1
            continue
        if not node.prefer_candidate(headers):
            yield Sleep(poll_interval)
            done += 1
            continue
        frm = missing[0].prev_hash
        frm_point = None
        if frm is not None:
            # the fetch range anchor: the predecessor's point
            for h in headers:
                if h.hash_ == frm:
                    frm_point = h.point
                    break
            if frm_point is None:
                for b in node.chain_db.current_chain:
                    if b.hash_ == frm:
                        frm_point = b.point
                        break
        yield Send(tx, ("request_range", frm_point, missing[-1].point))
        msg = yield Recv(rx)
        if msg[0] == "no_blocks":
            yield Sleep(poll_interval)
            done += 1
            continue
        assert msg[0] == "start_batch", msg
        while True:
            msg = yield Recv(rx)
            if msg[0] == "batch_done":
                break
            assert msg[0] == "block", msg
            block = Block.from_bytes(msg[1])
            res = node.chain_db.add_block(block)
            if res.selected:
                node.on_chain_changed()
        done += 1
