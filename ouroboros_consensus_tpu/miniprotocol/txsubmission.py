"""TxSubmission2, KeepAlive, PeerSharing mini-protocols.

Reference: the consensus side of the node-to-node `Apps` bundle
(ouroboros-consensus-diffusion `Network/NodeToNode.hs:434-466`):

  * **TxSubmission2** diffuses mempool transactions. The protocol is
    INBOUND-driven (the receiving side asks): the server requests txids
    from the peer's mempool (blocking when it has consumed everything),
    acks processed ids, requests the tx bodies it is missing, and adds
    them to its own mempool — which validates and rejects as the ledger
    dictates. The outbound side serves from its mempool snapshot in
    ticket order (Mempool/API.hs getSnapshot; `after(ticket)` is the
    reference's snapshotTxsAfter).
  * **KeepAlive** measures round trips and keeps the bearer warm
    (trivial cookie echo).
  * **PeerSharing** gossips known peer addresses.

Wire messages (sim/asyncio tuples like chainsync.py):
  inbound → outbound: ("request_txids", ack, req, blocking)
                      ("request_txs", [txid, ...])
                      ("done",)
  outbound → inbound: ("reply_txids", [(txid, size), ...])
                      ("reply_txs", [tx_bytes, ...])

  ("keepalive", cookie) / ("keepalive_response", cookie)
  ("share_peers", amount) / ("peers", [addr, ...])
"""

from __future__ import annotations

from ..ledger.mock import tx_id
from ..utils.sim import Recv, Send, Sleep

TXID_WINDOW = 16  # max unacknowledged txids (the reference's window)


def outbound(node, rx, tx, *, poll_interval: float = 0.1):
    """The mempool-serving side (runs at the peer OWNING the txs).
    Serves txids in ticket order; blocking requests wait until the
    mempool moves past the last served ticket."""
    last_ticket = -1
    unacked: list = []  # (txid, ticket) served but not yet acked
    while True:
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "request_txids":
            _, ack, req, blocking = msg
            del unacked[:ack]
            while True:
                snap = node.mempool.get_snapshot()
                fresh = list(snap.after(last_ticket))[:req]
                if fresh or not blocking:
                    break
                yield Sleep(poll_interval)  # blocking wait, sim-polled
            ids = []
            for t in fresh:
                ids.append((tx_id(t.tx), t.size))
                unacked.append((tx_id(t.tx), t.tx))
                last_ticket = t.number
            yield Send(tx, ("reply_txids", ids))
        elif kind == "request_txs":
            want = set(msg[1])
            bodies = [body for (i, body) in unacked if i in want]
            yield Send(tx, ("reply_txs", bodies))
        elif kind == "done":
            return
        else:
            raise RuntimeError(f"txsubmission outbound: bad message {kind!r}")


def inbound(node, peer_name: str, rx, tx, *, max_rounds: int | None = None,
            window: int = TXID_WINDOW):
    """The requesting side (runs at the peer RECEIVING the txs): pull
    txids, pull unknown bodies, feed the local mempool (which validates;
    invalid txs are dropped, not propagated)."""
    ack = 0
    rounds = 0
    while max_rounds is None or rounds < max_rounds:
        rounds += 1
        # blocking request when we have nothing outstanding (protocol
        # rule: MUST use the blocking variant once fully caught up)
        yield Send(tx, ("request_txids", ack, window, True))
        msg = yield Recv(rx)
        if msg[0] != "reply_txids":
            raise RuntimeError(f"txsubmission inbound: bad reply {msg[0]!r}")
        ids = msg[1]
        if not ids:
            continue
        known = {tx_id(t.tx) for t in node.mempool.get_snapshot().txs}
        missing = [i for (i, _size) in ids if i not in known]
        if missing:
            yield Send(tx, ("request_txs", missing))
            msg = yield Recv(rx)
            if msg[0] != "reply_txs":
                raise RuntimeError(f"txsubmission inbound: bad reply {msg[0]!r}")
            node.mempool.try_add_txs(msg[1])
        ack = len(ids)
    yield Send(tx, ("done",))


# -- KeepAlive ---------------------------------------------------------------


class KeepAliveTimeout(Exception):
    """The peer missed the KeepAlive response deadline — a
    peer-disconnect violation (the reference's KeepAlive agency timeout
    tears the connection down via the mux)."""


def keepalive_client(rx, tx, *, interval: float = 1.0, rounds: int = 10,
                     timeout: float = 10.0):
    """Sends a numbered cookie every `interval` and DEMANDS the echo
    within `timeout` — a missed deadline raises KeepAliveTimeout, which
    peer_guard classifies as a connection teardown (the reference's
    keep-alive timeout semantics)."""
    from ..utils.sim import TIMEOUT, RecvTimeout

    rtts: list[float] = []
    for cookie in range(rounds):
        yield Send(tx, ("keepalive", cookie))
        msg = yield RecvTimeout(rx, timeout)
        if msg is TIMEOUT:
            raise KeepAliveTimeout(
                f"no keepalive response within {timeout}s (cookie {cookie})"
            )
        if msg[0] != "keepalive_response" or msg[1] != cookie:
            raise RuntimeError(f"keepalive: bad response {msg!r}")
        rtts.append(1.0)  # sim has no task-local clock; presence = liveness
        yield Sleep(interval)
    return rtts


def keepalive_server(rx, tx):
    while True:
        msg = yield Recv(rx)
        if msg[0] == "done":
            return
        if msg[0] != "keepalive":
            raise RuntimeError(f"keepalive server: bad message {msg!r}")
        yield Send(tx, ("keepalive_response", msg[1]))


# -- PeerSharing -------------------------------------------------------------


def peersharing_client(rx, tx, amount: int):
    """One-shot: ask for up to `amount` peer addresses."""
    yield Send(tx, ("share_peers", amount))
    msg = yield Recv(rx)
    if msg[0] != "peers":
        raise RuntimeError(f"peersharing: bad reply {msg!r}")
    return msg[1]


def peersharing_server(node, rx, tx):
    """Serves the node's known peer addresses (NodeKernel's peer-sharing
    registry, NodeKernel.hs:88-114)."""
    while True:
        msg = yield Recv(rx)
        if msg[0] == "done":
            return
        if msg[0] != "share_peers":
            raise RuntimeError(f"peersharing server: bad message {msg!r}")
        peers = list(getattr(node, "known_peers", []))[: msg[1]]
        yield Send(tx, ("peers", peers))
