"""ChainSync mini-protocol: header diffusion with per-peer validation.

Reference: `MiniProtocol/ChainSync/{Client,Server}.hs`. The server feeds
headers of its current chain to the client from a ChainDB follower; the
client validates EVERY header (full crypto via the protocol instance —
Client.hs:55-57 → validateHeader) before extending its candidate
fragment, and disconnects the peer on the first invalid header
(ChainSyncClientException, Client.hs:1142).

Wire messages (typed-protocols codec analog — plain tuples over a
sim/asyncio Channel):
  client → server:  ("find_intersect", [Point])
                    ("request_next",)
  server → client:  ("intersect_found", Point|None, tip)
                    ("intersect_not_found", tip)
                    ("roll_forward", header_bytes, tip)
                    ("roll_backward", Point|None, tip)

The client tracks the candidate as (headers, header_states) so a
roll_backward is a O(1) truncation with the protocol state restored from
the kept prefix — the reference's `theirHeaderStateHistory`
(Client.hs:291, HeaderStateHistory.hs).

Both ends are written as generator tasks for the deterministic sim
runtime (utils/sim.py); the same logic drives the asyncio TCP transport.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..block.praos_block import Block, Header
from ..ledger.abstract import OutsideForecastRange
from ..ledger.header_history import HeaderStateHistory
from ..protocol import praos as praos_mod
from ..utils.sim import Recv, Send, Sleep, Wait

K_DEFAULT = 2160


class ChainSyncClientException(Exception):
    """Peer sent an invalid header / violated the protocol: disconnect
    (the rethrow-policy 'disconnect peer' class, Node/RethrowPolicy.hs)."""


@dataclass
class Candidate(HeaderStateHistory):
    """Per-peer candidate fragment: theirHeaderStateHistory (Client.hs:291).

    A HeaderStateHistory (ledger/header_history.py) whose entries are the
    peer's headers and whose states are raw protocol chain-dep states —
    states[0] is the state at the intersection (anchor), states[i+1] the
    state after validating headers[i], roll_backward is an O(1)
    truncation.

    The `settled` gate: only headers already adopted on OUR chain may be
    trimmed — dropping a not-yet-fetched header would orphan BlockFetch's
    anchor. The candidate stays bounded anyway: validation cannot outrun
    the forecast horizon (~3k/f ahead of our tip), which is what bounds
    the reference's fragment too. Rolling back deeper than k fails — the
    reference disconnects such peers (Client.hs rollback depth check).
    """


def server(
    chain_db, rx, tx, *, poll_interval: float | None = None,
    include_tentative: bool = True, follower=None,
    serve_blocks: bool = False,
):
    """ChainSync server task (Server.hs): answer find_intersect from the
    current chain, then stream follower updates as roll_forward /
    roll_backward. The MustReply wait BLOCKS on the follower's event
    (the reference blocks in STM on the follower's next instruction,
    MiniProtocol/ChainSync/Server.hs) — the serving ChainDB must have a
    runtime attached to fire it. `poll_interval` is an explicit opt-in
    for STATIC chain views whose followers have no event to fire
    (immdb-server's ImmutableChainView), never a silent fallback.

    `include_tentative` serves diffusion pipelining: headers of blocks
    still being validated stream out early (Impl/Follower.hs tentative
    followers), retracted by a rollback if validation rejects them.

    `serve_blocks` switches the payload to WHOLE SERIALISED BLOCKS —
    the local (node-to-client) ChainSync wallets consume
    (Network/NodeToClient.hs:92-121 chainSyncBlocksServer). Tentative
    headers are never served in this mode: a tentative block's body is
    still being validated."""
    if serve_blocks:
        include_tentative = False
        if follower is not None and follower.include_tentative:
            # a pipelining follower never re-announces a confirmed
            # tentative, so a blocks-mode server on it would silently
            # SKIP blocks — reject the combination outright
            raise ValueError(
                "serve_blocks requires a non-tentative follower"
            )
    created_follower = follower is None
    if follower is None:
        follower = chain_db.new_follower(include_tentative=include_tentative)
    decode = getattr(chain_db, "decode_block", Block.from_bytes)
    # pending instructions not yet sent (beyond the intersection)
    pending: list = []

    def tip():
        return chain_db.tip_point()

    try:
        yield from _server_loop(
            chain_db, rx, tx, follower, pending, tip, decode,
            poll_interval, serve_blocks,
        )
    finally:
        # a killed/disconnected server must not leak its follower
        if created_follower:
            follower.close()


def _server_loop(chain_db, rx, tx, follower, pending, tip, decode,
                 poll_interval, serve_blocks=False):
    # lazy stream of the immutable segment between the intersection and
    # the volatile fragment (never materialized: the immutable part can
    # be the whole database)
    imm_stream = None
    intersect_done = False
    while True:
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "find_intersect":
            # drain stale follower updates (and any pending-tentative
            # marker): everything up to NOW is covered by the chain
            # snapshot taken below
            follower.reset_position()
            points = msg[1]
            ours = {b.point: i for i, b in enumerate(chain_db.current_chain)}
            anchor = chain_db._anchor_point()
            # the reference server serves from ANY point on the chain,
            # including the immutable part (Impl/Follower.hs); a miss on
            # the volatile fragment must fall through to the ImmutableDB
            # rather than silently streaming a disconnected suffix
            found = None
            where = None  # "volatile" | "anchor" | "immutable" | "genesis"
            for p in points:
                if p is None:
                    found, where = None, "genesis"
                    break
                if p in ours:
                    found, where = p, "volatile"
                    break
                if p == anchor:
                    found, where = p, "anchor"
                    break
                try:
                    chain_db.immutable.get_block_bytes(p)
                except Exception:
                    continue
                found, where = p, "immutable"
                break
            if where is not None:
                pending.clear()
                imm_stream = None
                if where == "genesis":
                    imm_stream = chain_db.immutable.stream_all()
                elif where == "immutable":
                    imm_stream = chain_db.immutable.stream_from(found.slot)
                start = ours[found] + 1 if where == "volatile" else 0
                for b in chain_db.current_chain[start:]:
                    pending.append(("addblock", b))
                intersect_done = True
                yield Send(tx, ("intersect_found", found, tip()))
            else:
                yield Send(tx, ("intersect_not_found", tip()))
        elif kind == "request_next":
            if not intersect_done:
                raise RuntimeError("request_next before find_intersect")
            if imm_stream is not None:
                nxt = next(imm_stream, None)
                if nxt is None:
                    imm_stream = None
                else:
                    _e, raw = nxt
                    if serve_blocks:
                        yield Send(tx, ("roll_forward", raw, tip()))
                    else:
                        header = decode(raw).header
                        yield Send(
                            tx, ("roll_forward", header.bytes_, tip())
                        )
                    continue
            while True:
                pending.extend(follower.take_updates())
                if pending:
                    break
                if poll_interval is not None:
                    yield Sleep(poll_interval)  # static-view opt-in only
                else:
                    yield Wait(follower.event)  # blockUntilChanged analog
            op = pending.pop(0)
            if op[0] == "rollback":
                yield Send(tx, ("roll_backward", op[1], tip()))
            elif op[0] == "tentative":
                yield Send(tx, ("roll_forward", op[1].bytes_, tip()))
            elif serve_blocks:
                yield Send(tx, ("roll_forward", op[1].bytes_, tip()))
            else:
                yield Send(tx, ("roll_forward", op[1].header.bytes_, tip()))
        elif kind == "done":
            return
        else:
            raise RuntimeError(f"chainsync server: bad message {kind!r}")


def client(
    node,
    peer_name: str,
    rx,
    tx,
    candidate: Candidate,
    *,
    max_headers: int | None = None,
    max_in_flight: int = 10,
):
    """ChainSync client task (Client.hs:422), message-pipelined.

    `node` provides: .protocol (instances.PraosProtocol-shaped),
    .chain_db, .ledger_view_at(slot) — the forecast (bounded-horizon
    ledger view, Forecast.hs; static for the mock ledger).

    Validates each roll_forward header against the candidate's protocol
    state (full crypto) and extends the candidate; blockfetch drains it.

    Pipelining (`MkPipelineDecision`, Client.hs:422): while the
    candidate tip is behind the server's announced tip, keep up to
    `max_in_flight` request_next messages outstanding, collecting
    responses as they arrive; once caught up, degrade to strict
    request/response (pipelineDecisionLowHighMark shape). With a
    per-message channel delay d this turns 2·d per header into d per
    WINDOW of headers.
    """
    # findIntersect with points of our current chain (newest first —
    # Client.hs:464 uses the standard exponentially-spaced offsets; the
    # dense recent prefix suffices for test chains)
    # header codec seam, mirroring the ChainDB's decode_block seam: a
    # composite (HFC) network's eras may use non-Praos header layouts, so
    # the node (or its ChainDB) can supply the era-dispatching decoder
    decode_header = getattr(
        node, "decode_header",
        getattr(node.chain_db, "decode_header", Header.from_bytes),
    )
    our_points = [b.point for b in reversed(node.chain_db.current_chain)]
    our_points.append(None)  # genesis fallback
    yield Send(tx, ("find_intersect", our_points))
    msg = yield Recv(rx)
    if msg[0] == "intersect_not_found":
        raise ChainSyncClientException(f"{peer_name}: no intersection")
    intersection = msg[1]
    server_tip = msg[2]

    # seed candidate protocol state from OUR state at the intersection
    # (the candidate implicitly shares our chain up to it)
    candidate.reset(node.chain_dep_state_at(intersection))
    if candidate.k is None:
        candidate.k = getattr(node.protocol, "security_param", None)
    if candidate.settled is None:
        candidate.settled = lambda p: node.chain_db.get_block(p) is not None

    n = 0
    in_flight = 0
    while max_headers is None or n < max_headers:
        # pipeline decision: how far behind the server's tip are we?
        tip_pt = candidate.tip_point()
        behind = server_tip is not None and (
            tip_pt is None or tip_pt.slot < server_tip.slot
        )
        budget = max_in_flight if behind else 1
        if max_headers is not None:
            budget = min(budget, max_headers - n)
        while in_flight < budget:
            yield Send(tx, ("request_next",))
            in_flight += 1
        msg = yield Recv(rx)
        in_flight -= 1
        server_tip = msg[-1]
        kind = msg[0]
        if kind == "roll_forward":
            header = decode_header(msg[1])
            # forecast the ledger view for the header's slot. A header
            # past OUR forecast horizon is not (yet) validatable: the
            # reference client BLOCKS in STM until the node's own tip
            # advances far enough (Client.hs intersection/forecast
            # retry), it does not disconnect — BlockFetch applying the
            # already-validated prefix is what extends the horizon.
            while True:
                try:
                    lview = node.ledger_view_at(header.slot)
                    break
                except OutsideForecastRange:
                    yield Sleep(0.05)
            base = candidate.states[-1]
            ticked = node.protocol.tick(lview, header.slot, base)
            try:
                new_st = node.protocol.update(
                    header.to_view(), header.slot, ticked
                )
            except praos_mod.PraosValidationError as e:
                raise ChainSyncClientException(
                    f"{peer_name}: invalid header at slot {header.slot}: {e!r}"
                ) from e
            candidate.extend(header, new_st)
            n += 1
        elif kind == "roll_backward":
            point = msg[1]
            target = None if point == intersection else point
            if not candidate.truncate_to(target):
                raise ChainSyncClientException(
                    f"{peer_name}: rollback to unknown point {point}"
                )
            n += 1
        else:
            raise ChainSyncClientException(f"{peer_name}: bad message {kind!r}")
