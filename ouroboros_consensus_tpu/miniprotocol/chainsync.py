"""ChainSync mini-protocol: header diffusion with per-peer validation.

Reference: `MiniProtocol/ChainSync/{Client,Server}.hs`. The server feeds
headers of its current chain to the client from a ChainDB follower; the
client validates EVERY header (full crypto via the protocol instance —
Client.hs:55-57 → validateHeader) before extending its candidate
fragment, and disconnects the peer on the first invalid header
(ChainSyncClientException, Client.hs:1142).

Wire messages (typed-protocols codec analog — plain tuples over a
sim/asyncio Channel):
  client → server:  ("find_intersect", [Point])
                    ("request_next",)
  server → client:  ("intersect_found", Point|None, tip)
                    ("intersect_not_found", tip)
                    ("roll_forward", header_bytes, tip)
                    ("roll_backward", Point|None, tip)

The client tracks the candidate as (headers, header_states) so a
roll_backward is a O(1) truncation with the protocol state restored from
the kept prefix — the reference's `theirHeaderStateHistory`
(Client.hs:291, HeaderStateHistory.hs).

Both ends are written as generator tasks for the deterministic sim
runtime (utils/sim.py); the same logic drives the asyncio TCP transport.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ..block.abstract import Point
from ..block.praos_block import Block, Header
from ..protocol import praos as praos_mod
from ..utils.sim import Recv, Send, Sleep

K_DEFAULT = 2160


class ChainSyncClientException(Exception):
    """Peer sent an invalid header / violated the protocol: disconnect
    (the rethrow-policy 'disconnect peer' class, Node/RethrowPolicy.hs)."""


@dataclass
class Candidate:
    """Per-peer candidate fragment + protocol states per position.

    Invariant: len(states) == len(headers) + 1 — states[0] is the
    protocol state at the intersection (anchor), states[i+1] the state
    after validating headers[i]. This is theirHeaderStateHistory
    (Client.hs:291) with O(1) rollback.
    """

    headers: list = field(default_factory=list)
    states: list = field(default_factory=list)

    def tip_point(self) -> Point | None:
        return self.headers[-1].point if self.headers else None

    def reset(self, base_state) -> None:
        self.headers = []
        self.states = [base_state]

    def extend(self, header, state) -> None:
        self.headers.append(header)
        self.states.append(state)

    def truncate_to(self, point: Point | None) -> bool:
        """Roll back the suffix to `point` (None = back to the anchor).
        False if the point is not on the candidate."""
        if point is None:
            del self.headers[:]
            del self.states[1:]
            return True
        for i in range(len(self.headers) - 1, -1, -1):
            if self.headers[i].point == point:
                del self.headers[i + 1 :]
                del self.states[i + 2 :]
                return True
        return False


def server(chain_db, rx, tx, *, poll_interval: float = 0.05):
    """ChainSync server task (Server.hs): answer find_intersect from the
    current chain, then stream follower updates as roll_forward /
    roll_backward."""
    follower = chain_db.new_follower()
    # pending instructions not yet sent (beyond the intersection)
    pending: list = []
    # lazy stream of the immutable segment between the intersection and
    # the volatile fragment (never materialized: the immutable part can
    # be the whole database)
    imm_stream = None
    intersect_done = False

    def tip():
        return chain_db.tip_point()

    while True:
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "find_intersect":
            # drain stale follower updates: everything up to NOW is
            # covered by the chain snapshot taken below
            follower.take_updates()
            points = msg[1]
            ours = {b.point: i for i, b in enumerate(chain_db.current_chain)}
            anchor = chain_db._anchor_point()
            # the reference server serves from ANY point on the chain,
            # including the immutable part (Impl/Follower.hs); a miss on
            # the volatile fragment must fall through to the ImmutableDB
            # rather than silently streaming a disconnected suffix
            found = None
            where = None  # "volatile" | "anchor" | "immutable" | "genesis"
            for p in points:
                if p is None:
                    found, where = None, "genesis"
                    break
                if p in ours:
                    found, where = p, "volatile"
                    break
                if p == anchor:
                    found, where = p, "anchor"
                    break
                try:
                    chain_db.immutable.get_block_bytes(p)
                except Exception:
                    continue
                found, where = p, "immutable"
                break
            if where is not None:
                pending.clear()
                imm_stream = None
                if where == "genesis":
                    imm_stream = chain_db.immutable.stream_all()
                elif where == "immutable":
                    imm_stream = chain_db.immutable.stream_from(found.slot)
                start = ours[found] + 1 if where == "volatile" else 0
                for b in chain_db.current_chain[start:]:
                    pending.append(("addblock", b))
                intersect_done = True
                yield Send(tx, ("intersect_found", found, tip()))
            else:
                yield Send(tx, ("intersect_not_found", tip()))
        elif kind == "request_next":
            if not intersect_done:
                raise RuntimeError("request_next before find_intersect")
            if imm_stream is not None:
                nxt = next(imm_stream, None)
                if nxt is None:
                    imm_stream = None
                else:
                    _e, raw = nxt
                    header = Block.from_bytes(raw).header
                    yield Send(tx, ("roll_forward", header.bytes_, tip()))
                    continue
            while True:
                pending.extend(follower.take_updates())
                if pending:
                    break
                yield Sleep(poll_interval)  # MustReply/await analog
            op = pending.pop(0)
            if op[0] == "rollback":
                yield Send(tx, ("roll_backward", op[1], tip()))
            else:
                yield Send(tx, ("roll_forward", op[1].header.bytes_, tip()))
        elif kind == "done":
            return
        else:
            raise RuntimeError(f"chainsync server: bad message {kind!r}")


def client(
    node,
    peer_name: str,
    rx,
    tx,
    candidate: Candidate,
    *,
    max_headers: int | None = None,
):
    """ChainSync client task (Client.hs:422).

    `node` provides: .protocol (instances.PraosProtocol-shaped),
    .chain_db, .ledger_view_at(slot) — the forecast (bounded-horizon
    ledger view, Forecast.hs; static for the mock ledger).

    Validates each roll_forward header against the candidate's protocol
    state (full crypto) and extends the candidate; blockfetch drains it.
    """
    # findIntersect with points of our current chain (newest first —
    # Client.hs:464 uses the standard exponentially-spaced offsets; the
    # dense recent prefix suffices for test chains)
    our_points = [b.point for b in reversed(node.chain_db.current_chain)]
    our_points.append(None)  # genesis fallback
    yield Send(tx, ("find_intersect", our_points))
    msg = yield Recv(rx)
    if msg[0] == "intersect_not_found":
        raise ChainSyncClientException(f"{peer_name}: no intersection")
    intersection = msg[1]

    # seed candidate protocol state from OUR state at the intersection
    # (the candidate implicitly shares our chain up to it)
    candidate.reset(node.chain_dep_state_at(intersection))

    n = 0
    while max_headers is None or n < max_headers:
        yield Send(tx, ("request_next",))
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "roll_forward":
            header = Header.from_bytes(msg[1])
            base = candidate.states[-1]
            lview = node.ledger_view_at(header.slot)
            ticked = node.protocol.tick(lview, header.slot, base)
            try:
                new_st = node.protocol.update(
                    header.to_view(), header.slot, ticked
                )
            except praos_mod.PraosValidationError as e:
                raise ChainSyncClientException(
                    f"{peer_name}: invalid header at slot {header.slot}: {e!r}"
                ) from e
            candidate.extend(header, new_st)
            n += 1
        elif kind == "roll_backward":
            point = msg[1]
            target = None if point == intersection else point
            if not candidate.truncate_to(target):
                raise ChainSyncClientException(
                    f"{peer_name}: rollback to unknown point {point}"
                )
            n += 1
        else:
            raise ChainSyncClientException(f"{peer_name}: bad message {kind!r}")
