"""Per-peer rethrow policy: which exceptions mean DISCONNECT.

Reference: `Node/RethrowPolicy.hs` consensusRethrowPolicy — each
exception type is classified as peer-disconnect or node-shutdown. Here
the classification lives next to the protocols that raise, and
`peer_guard` is the reusable task wrapper every spawn site (ThreadNet
edges, node/apps bundles) applies: a peer violation ends the WHOLE
connection via `on_disconnect`, anything else still aborts the run
(node-level failure)."""

from __future__ import annotations

from .blockfetch import InvalidBlockFromPeer
from .chainsync import ChainSyncClientException
from .txsubmission import KeepAliveTimeout

# exceptions that condemn the PEER, not the node (ouroboros-consensus
# maps these to ShutdownPeer in consensusRethrowPolicy)
PEER_DISCONNECT_EXCEPTIONS = (
    ChainSyncClientException,
    InvalidBlockFromPeer,
    KeepAliveTimeout,
)


def peer_guard(gen, name: str, trace, on_disconnect=None):
    """Run `gen`; a peer violation traces + invokes `on_disconnect()`
    (tear down the connection's other protocol tasks) and ends this
    task. Other exceptions propagate — the node-shutdown class. The
    inner task's return value passes through (peersharing's peer list)."""
    try:
        return (yield from gen)
    except PEER_DISCONNECT_EXCEPTIONS as e:
        trace(f"{name}: disconnected peer: {e}")
        if on_disconnect is not None:
            on_disconnect()
