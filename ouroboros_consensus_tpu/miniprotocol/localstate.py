"""Local (node-to-client) mini-protocols: state query, tx submission,
tx monitor.

Reference: `MiniProtocol/LocalStateQuery/Server.hs` (acquire a ledger
state at a point, answer queries against it — Ledger/Query.hs:78-83
`GetSystemStart`/`GetChainBlockNo` plus ledger-specific queries),
`MiniProtocol/LocalTxSubmission/Server.hs` (submit txs to the mempool),
`MiniProtocol/LocalTxMonitor/Server.hs` (observe mempool contents).

Wire messages (tuples over sim/asyncio channels):
  state query:   ("acquire", Point|None) → ("acquired",) | ("failed", why)
                 ("query", name, args) → ("result", value)
                 ("release",) / ("done",)
  tx submission: ("submit", tx_bytes) → ("accepted",) | ("rejected", why)
  tx monitor:    ("acquire",) → ("acquired", slot)
                 ("next_tx",) → ("tx", bytes) | ("no_more",)
                 ("has_tx", txid) → ("bool", b)
                 ("get_sizes",) → ("sizes", capacity, used, n)
"""

from __future__ import annotations

from ..ledger.mock import InvalidTx, tx_id
from ..mempool import MempoolFull
from ..utils.sim import Recv, Send


class QueryError(Exception):
    pass


class QueryUnsupported(QueryError):
    """Query requires a newer negotiated NodeToClient version
    (Ledger/Query.hs queryVersion gating)."""


LATEST_QUERY_VERSION = 2

# queryVersion (Ledger/Query.hs): the minimum negotiated version each
# query needs — older clients cannot name newer queries
QUERY_MIN_VERSION = {
    "get_chain_block_no": 1,
    "get_chain_point": 1,
    "get_tip_slot": 1,
    "get_utxo": 1,
    "get_balance": 1,
    "get_pool_distr": 2,
}


def run_query(node, ext_state, name: str, args, version: int = LATEST_QUERY_VERSION):
    """The query vocabulary (Ledger/Query.hs + mock ledger queries)."""
    need = QUERY_MIN_VERSION.get(name)
    if need is not None and version < need:
        raise QueryUnsupported(
            f"query {name!r} needs NodeToClient version {need}, have {version}"
        )
    ledger_state = ext_state.ledger_state
    hs = ext_state.header_state
    if name == "get_chain_block_no":
        return hs.tip.block_no if hs.tip else None
    if name == "get_chain_point":
        return hs.tip.point if hs.tip else None
    if name == "get_tip_slot":
        return hs.tip.slot if hs.tip else None
    if name == "get_utxo":
        return dict(ledger_state.utxo)
    if name == "get_balance":
        addr = args[0]
        return sum(amt for (a, amt) in ledger_state.utxo.values() if a == addr)
    if name == "get_pool_distr":
        return node.ledger_view_at(hs.tip.slot if hs.tip else 0).pool_distr
    raise QueryError(f"unknown query {name!r}")


def state_query_server(node, rx, tx, version: int = LATEST_QUERY_VERSION):
    """LocalStateQuery server: acquire/query/release session. `version`
    is the negotiated NodeToClient version (handshake.py) gating the
    query vocabulary."""
    acquired = None
    while True:
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "acquire":
            point = msg[1]
            st = (
                node.chain_db.current_ledger()
                if point is None
                else node.chain_db.get_past_ledger(point)
            )
            if st is None:
                yield Send(tx, ("failed", "point not on chain"))
            else:
                acquired = st
                yield Send(tx, ("acquired",))
        elif kind == "query":
            if acquired is None:
                yield Send(tx, ("failed", "no state acquired"))
                continue
            try:
                val = run_query(node, acquired, msg[1], msg[2], version)
                yield Send(tx, ("result", val))
            except QueryError as e:
                yield Send(tx, ("failed", str(e)))
        elif kind == "release":
            acquired = None
        elif kind == "done":
            return
        else:
            yield Send(tx, ("failed", f"bad message {kind!r}"))


def tx_submission_server(node, rx, tx):
    """LocalTxSubmission server: mempool add with typed verdicts."""
    while True:
        msg = yield Recv(rx)
        if msg[0] == "done":
            return
        assert msg[0] == "submit", msg
        try:
            node.mempool.add_tx(msg[1])
            yield Send(tx, ("accepted",))
        except (InvalidTx, MempoolFull) as e:
            yield Send(tx, ("rejected", repr(e)))


def tx_monitor_server(node, rx, tx):
    """LocalTxMonitor server: iterate a mempool snapshot."""
    snap = None
    cursor = 0
    while True:
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "acquire":
            snap = node.mempool.get_snapshot()
            cursor = 0
            yield Send(tx, ("acquired", snap.ledger_slot))
        elif snap is None:
            yield Send(tx, ("failed", "no snapshot acquired"))
        elif kind == "next_tx":
            if cursor < len(snap.txs):
                yield Send(tx, ("tx", snap.txs[cursor].tx))
                cursor += 1
            else:
                yield Send(tx, ("no_more",))
        elif kind == "has_tx":
            yield Send(tx, ("bool", any(tx_id(t.tx) == msg[1] for t in snap.txs)))
        elif kind == "get_sizes":
            used = sum(t.size for t in snap.txs)
            yield Send(tx, ("sizes", node.mempool.capacity, used, len(snap.txs)))
        elif kind == "release":
            snap = None
        elif kind == "done":
            return
        else:
            yield Send(tx, ("failed", f"bad message {kind!r}"))
