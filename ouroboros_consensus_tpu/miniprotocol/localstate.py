"""Local (node-to-client) mini-protocols: state query, tx submission,
tx monitor.

Reference: `MiniProtocol/LocalStateQuery/Server.hs` (acquire a ledger
state at a point, answer queries against it — Ledger/Query.hs:78-83
`GetSystemStart`/`GetChainBlockNo` plus ledger-specific queries),
`MiniProtocol/LocalTxSubmission/Server.hs` (submit txs to the mempool),
`MiniProtocol/LocalTxMonitor/Server.hs` (observe mempool contents).

Wire messages (tuples over sim/asyncio channels):
  state query:   ("acquire", Point|None) → ("acquired",) | ("failed", why)
                 ("query", name, args) → ("result", value)
                 ("release",) / ("done",)
  tx submission: ("submit", tx_bytes) → ("accepted",) | ("rejected", why)
  tx monitor:    ("acquire",) → ("acquired", slot)
                 ("next_tx",) → ("tx", bytes) | ("no_more",)
                 ("has_tx", txid) → ("bool", b)
                 ("get_sizes",) → ("sizes", capacity, used, n)
"""

from __future__ import annotations

from ..ledger.mock import InvalidTx, tx_id
from ..mempool import MempoolFull
from ..utils.sim import Recv, Send


class QueryError(Exception):
    pass


class QueryUnsupported(QueryError):
    """Query requires a newer negotiated NodeToClient version
    (Ledger/Query.hs queryVersion gating)."""


LATEST_QUERY_VERSION = 3

# queryVersion (Ledger/Query.hs): the minimum negotiated version each
# query needs — older clients cannot name newer queries
# the Shelley ledger query family (shelley Ledger/Query.hs): era-
# specific — on a non-Shelley state they fail with EraMismatch, exactly
# the HFC's QueryIfCurrent behavior. Single source of truth: version
# gating below derives from this set.
_SHELLEY_QUERIES = frozenset({
    "get_epoch_no", "get_stake_distribution", "get_stake_pools",
    "get_stake_pool_params", "get_current_pparams",
    "get_proposed_pparams_updates", "get_rewards",
    "get_delegations_and_rewards", "get_utxo_by_address",
    "get_account_state",
    # round-4 breadth (shelley Ledger/Query.hs parity): genesis config,
    # pool lifecycle state, the three stake snapshots, the reward
    # calculation's inputs, and the full-state debug dump
    "get_genesis_config", "get_pool_state", "get_stake_snapshots",
    "get_reward_provenance", "debug_new_epoch_state",
})

# Byron-era queries (byron Ledger/Query.hs GetUpdateInterfaceState
# shape, collapsed to the delegation/fee surface our Byron ledger has):
# era-checked exactly like the Shelley family
_BYRON_QUERIES = frozenset({"get_delegation_map", "get_byron_state"})

QUERY_MIN_VERSION = {
    "get_chain_block_no": 1,
    "get_chain_point": 1,
    "get_tip_slot": 1,
    "get_utxo": 1,
    "get_balance": 1,
    "get_pool_distr": 2,
    **{q: 3 for q in _SHELLEY_QUERIES},
    **{q: 3 for q in _BYRON_QUERIES},
}


class EraMismatch(QueryError):
    """An era-specific query hit a state of another era — the HFC's
    QueryIfCurrent mismatch result (HardFork/Combinator/Ledger/Query.hs),
    surfaced as a failure the client can retry after the era bump."""


def _shelley_state(ledger_state):
    """Unwrap (possibly HFC-nested) state to a ShelleyState or raise
    EraMismatch."""
    from ..hardfork.combinator import HFState
    from ..ledger.shelley import ShelleyState

    st = ledger_state
    while isinstance(st, HFState):
        st = st.inner
    if not isinstance(st, ShelleyState):
        raise EraMismatch(
            f"Shelley query against {type(st).__name__} state"
        )
    return st


# argument spec per query: () = no args, "scalar" = one bytes-like,
# "collection" = one list/tuple/set (bytes would silently iterate as
# ints, so it is explicitly NOT a collection). Client-fault shapes are a
# QUERY failure — the server stays up and the client can tell its own
# mistake from a server bug.
_QUERY_ARGSPEC = {
    "get_balance": "scalar",
    "get_stake_pool_params": "collection",
    "get_rewards": "collection",
    "get_delegations_and_rewards": "collection",
    "get_utxo_by_address": "collection",
    "get_pool_state": "collection",
    "get_stake_snapshots": "collection",
}


def _check_args(name: str, args) -> None:
    spec = _QUERY_ARGSPEC.get(name)
    if spec is None:
        if len(args) != 0:
            raise QueryError(f"{name} takes no arguments, got {args!r}")
        return
    if len(args) != 1:
        raise QueryError(f"{name} takes 1 argument, got {args!r}")
    if spec == "collection" and not isinstance(
        args[0], (list, tuple, set, frozenset)
    ):
        raise QueryError(
            f"{name} takes a collection, got {type(args[0]).__name__}"
        )
    if spec == "scalar" and not isinstance(args[0], (bytes, bytearray)):
        raise QueryError(
            f"{name} takes an address, got {type(args[0]).__name__}"
        )


def _run_shelley_query(st, name: str, args):
    """shelley Ledger/Query.hs vocabulary over the REAL STS state."""
    from fractions import Fraction

    if name == "get_epoch_no":
        return st.epoch
    if name == "get_stake_distribution":
        # GetStakeDistribution: the SET snapshot's per-pool fractions
        # (what the current epoch elects with)
        per = st.set_.pool_stake()
        total = sum(per.values())
        if total == 0:
            return {}
        return {pid: Fraction(amt, total) for pid, amt in sorted(per.items())}
    if name == "get_stake_pools":
        return set(st.pools)
    if name == "get_stake_pool_params":
        (pids,) = args
        return {pid: st.pools[pid] for pid in pids if pid in st.pools}
    if name == "get_current_pparams":
        return st.pparams
    if name == "get_proposed_pparams_updates":
        return dict(st.proposals)
    if name == "get_rewards":
        (creds,) = args
        return {c: st.rewards[c] for c in creds if c in st.rewards}
    if name == "get_delegations_and_rewards":
        (creds,) = args
        return (
            {c: st.delegations[c] for c in creds if c in st.delegations},
            {c: st.rewards[c] for c in creds if c in st.rewards},
        )
    if name == "get_utxo_by_address":
        (addrs,) = args
        want = set(addrs)
        return {
            k: (a, c) for k, (a, c) in st.utxo.items() if a[0] in want
        }
    if name == "get_account_state":
        # GetAccountState: the treasury and reserves pots
        return {"treasury": st.treasury, "reserves": st.reserves}
    if name == "get_pool_state":
        # GetPoolState: registered params + pending retirements +
        # the deposits actually held, for the requested pools
        (pids,) = args
        want = set(pids)
        return {
            "pools": {p: st.pools[p] for p in want if p in st.pools},
            "retiring": {
                p: st.retiring[p] for p in want if p in st.retiring
            },
            "deposits": {
                p: st.pool_deposits[p]
                for p in want if p in st.pool_deposits
            },
        }
    if name == "get_stake_snapshots":
        # GetStakeSnapshots: per-pool stake in each of mark/set/go plus
        # the snapshot totals (the cardano-cli "stake-snapshot" shape)
        (pids,) = args
        want = set(pids)
        out = {}
        for label, snap in (("mark", st.mark), ("set", st.set_),
                            ("go", st.go)):
            per = snap.pool_stake()
            out[label] = {
                "pools": {p: per.get(p, 0) for p in want},
                "total": sum(snap.stake.values()),
            }
        return out
    if name == "get_reward_provenance":
        # GetRewardProvenance (simplified to our RUPD inputs): what the
        # NEXT reward update will be computed from
        return {
            "epoch": st.epoch,
            "pots": {
                "treasury": st.treasury, "reserves": st.reserves,
                "fees": st.fees, "prev_fees": st.prev_fees,
                "deposits": st.deposits,
            },
            "blocks_prev": dict(st.blocks_prev),
            "blocks_current": dict(st.blocks_current),
            "total_go_stake": sum(st.go.stake.values()),
        }
    if name == "debug_new_epoch_state":
        # DebugNewEpochState: the whole ledger state — deep-copied (the
        # reference serializes it for offline inspection; handing out
        # the node's LIVE mutable dicts would let a client corrupt it)
        import copy

        return copy.deepcopy(st)
    raise QueryError(f"unknown Shelley query {name!r}")


def run_query(node, ext_state, name: str, args, version: int = LATEST_QUERY_VERSION):
    """The query vocabulary (Ledger/Query.hs + mock ledger queries)."""
    need = QUERY_MIN_VERSION.get(name)
    if need is not None and version < need:
        raise QueryUnsupported(
            f"query {name!r} needs NodeToClient version {need}, have {version}"
        )
    if need is not None:
        _check_args(name, args)
    ledger_state = ext_state.ledger_state
    hs = ext_state.header_state
    if name == "get_chain_block_no":
        return hs.tip.block_no if hs.tip else None
    if name == "get_chain_point":
        return hs.tip.point if hs.tip else None
    if name == "get_tip_slot":
        return hs.tip.slot if hs.tip else None
    if name == "get_utxo":
        return dict(ledger_state.utxo)
    if name == "get_balance":
        addr = args[0]
        # era-shape aware: mock utxo values are (addr, amt); Shelley's
        # are ((payment, staking), amt) — match on the payment part so
        # a v1 client gets the right balance on any era's state
        total = 0
        for (a, amt) in ledger_state.utxo.values():
            payment = a[0] if isinstance(a, tuple) else a
            if payment == addr:
                total += amt
        return total
    if name == "get_pool_distr":
        return node.ledger_view_at(hs.tip.slot if hs.tip else 0).pool_distr
    if name in _BYRON_QUERIES:
        return _run_byron_query(_byron_state(ledger_state), name)
    if name == "get_genesis_config":
        # GetGenesisConfig: the static Shelley genesis the LEDGER was
        # configured with (not part of the state) — era-checked like
        # every Shelley query
        _shelley_state(ledger_state)
        return _shelley_genesis_of(node.ledger)
    if name in _SHELLEY_QUERIES:
        return _run_shelley_query(_shelley_state(ledger_state), name, args)
    raise QueryError(f"unknown query {name!r}")


def _byron_state(ledger_state):
    """Unwrap (possibly HFC-nested, possibly Dual-paired) state to a
    ByronState or raise EraMismatch."""
    from ..hardfork.combinator import HFState
    from ..ledger.byron import ByronState
    from ..ledger.byron_spec import DualByronState

    st = ledger_state
    while isinstance(st, HFState):
        st = st.inner
    if isinstance(st, DualByronState):
        st = st.impl
    if not isinstance(st, ByronState):
        raise EraMismatch(f"Byron query against {type(st).__name__} state")
    return st


def _run_byron_query(st, name: str):
    import copy

    if name == "get_delegation_map":
        return dict(st.delegation)
    if name == "get_byron_state":
        return copy.deepcopy(st)  # debug dump, isolated from the node
    raise QueryError(f"unknown Byron query {name!r}")


def _shelley_genesis_of(ledger):
    """Find the ShelleyGenesis behind a (possibly HFC-composed) ledger."""
    from ..ledger.shelley import ShelleyGenesis, ShelleyLedger

    if isinstance(ledger, ShelleyLedger):
        return ledger.genesis
    for era in getattr(ledger, "eras", ()):
        if isinstance(era.ledger, ShelleyLedger):
            return era.ledger.genesis
    raise QueryError("no Shelley ledger behind this node")


def state_query_server(node, rx, tx, version: int = LATEST_QUERY_VERSION):
    """LocalStateQuery server: acquire/query/release session. `version`
    is the negotiated NodeToClient version (handshake.py) gating the
    query vocabulary."""
    acquired = None
    while True:
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "acquire":
            point = msg[1]
            st = (
                node.chain_db.current_ledger()
                if point is None
                else node.chain_db.get_past_ledger(point)
            )
            if st is None:
                yield Send(tx, ("failed", "point not on chain"))
            else:
                acquired = st
                yield Send(tx, ("acquired",))
        elif kind == "query":
            if acquired is None:
                yield Send(tx, ("failed", "no state acquired"))
                continue
            try:
                val = run_query(node, acquired, msg[1], msg[2], version)
                yield Send(tx, ("result", val))
            except QueryError as e:
                yield Send(tx, ("failed", str(e)))
            except (ValueError, IndexError, TypeError, KeyError) as e:
                # anything else escaping a handler is a SERVER-side
                # defect: reply distinctly (triageable, not confusable
                # with client fault) but keep the session alive
                yield Send(tx, ("failed", f"internal query error: {e!r}"))
        elif kind == "release":
            acquired = None
        elif kind == "done":
            return
        else:
            yield Send(tx, ("failed", f"bad message {kind!r}"))


def tx_submission_server(node, rx, tx):
    """LocalTxSubmission server: mempool add with typed verdicts."""
    while True:
        msg = yield Recv(rx)
        if msg[0] == "done":
            return
        assert msg[0] == "submit", msg
        try:
            node.mempool.add_tx(msg[1])
            yield Send(tx, ("accepted",))
        except (InvalidTx, MempoolFull) as e:
            yield Send(tx, ("rejected", repr(e)))


def tx_monitor_server(node, rx, tx):
    """LocalTxMonitor server: iterate a mempool snapshot."""
    snap = None
    cursor = 0
    while True:
        msg = yield Recv(rx)
        kind = msg[0]
        if kind == "acquire":
            snap = node.mempool.get_snapshot()
            cursor = 0
            yield Send(tx, ("acquired", snap.ledger_slot))
        elif snap is None:
            yield Send(tx, ("failed", "no snapshot acquired"))
        elif kind == "next_tx":
            if cursor < len(snap.txs):
                yield Send(tx, ("tx", snap.txs[cursor].tx))
                cursor += 1
            else:
                yield Send(tx, ("no_more",))
        elif kind == "has_tx":
            yield Send(tx, ("bool", any(tx_id(t.tx) == msg[1] for t in snap.txs)))
        elif kind == "get_sizes":
            used = sum(t.size for t in snap.txs)
            yield Send(tx, ("sizes", node.mempool.capacity, used, len(snap.txs)))
        elif kind == "release":
            snap = None
        elif kind == "done":
            return
        else:
            yield Send(tx, ("failed", f"bad message {kind!r}"))
