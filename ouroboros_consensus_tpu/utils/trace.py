"""Tracing: structured event emission threaded through every component.

Reference: contravariant `Tracer`s everywhere (contra-tracer; master
record `Tracers'` at diffusion Node/Tracers.hs:50-64; ChainDB's event
algebra at ChainDB/Impl.hs:10-28) plus `Enclose` start/end brackets for
latency measurement (Util/Enclose.hs).

The TPU build keeps the same shape with plain callables: a Tracer is any
`Callable[[event], None]`; combinators below mirror contramap / nullTracer
/ condTracer; `Enclose` is a context manager stamping monotonic start/end
events. Events are dataclasses (typed, matchable) — rendering is the
embedding application's job, exactly as in the reference (§5.5)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

Tracer = Callable[[Any], None]


def null_tracer(_event: Any) -> None:
    """nullTracer: drop everything."""


def contramap(f: Callable[[Any], Any], tracer: Tracer) -> Tracer:
    """contramap: adapt event type before forwarding."""

    def t(ev):
        tracer(f(ev))

    return t


def cond_tracer(pred: Callable[[Any], bool], tracer: Tracer) -> Tracer:
    def t(ev):
        if pred(ev):
            tracer(ev)

    return t


def fanout(*tracers: Tracer) -> Tracer:
    def t(ev):
        for tr in tracers:
            tr(ev)

    return t


class ListTracer:
    """Test helper: collect events (the recordingTracerIORef analog)."""

    def __init__(self):
        self.events: list = []

    def __call__(self, ev):
        self.events.append(ev)


def stderr_tracer(prefix: str = "") -> Tracer:
    """db-analyser-style locked stderr tracer with monotonic timestamps
    (DBAnalyser/Run.hs:122-131)."""
    import sys
    import threading

    lock = threading.Lock()
    t0 = time.monotonic()

    def t(ev):
        with lock:
            print(f"[{time.monotonic() - t0:10.3f}] {prefix}{ev}", file=sys.stderr)

    return t


@dataclass(frozen=True)
class EncloseEvent:
    """Start/end bracket (Util/Enclose.hs RisingEdge/FallingEdge).
    Frozen like every other event dataclass: the end edge is a NEW
    event carrying the duration, never a mutated start event."""

    label: str
    edge: str  # "start" | "end"
    t: float
    duration: float | None = None  # set on the end edge


class Enclose:
    """Context manager emitting start/end events around an action:

        with Enclose(tracer, "volatile-write"):
            ...
    """

    def __init__(self, tracer: Tracer, label: str):
        self.tracer = tracer
        self.label = label
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.monotonic()
        self.tracer(EncloseEvent(self.label, "start", self._t0))
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        self.tracer(EncloseEvent(self.label, "end", t1, t1 - self._t0))
        return False


@dataclass(frozen=True)
class TransferEvent:
    """Device-boundary byte accounting for one batch-path phase: H2D
    staged bytes at dispatch, D2H verdict/nonce bytes at materialize.
    Emitted through the same batch tracer as the Enclose brackets so
    bench/profiling runs can report bytes-per-window alongside wall
    time (protocol/batch.py packed-staging contract)."""

    phase: str  # "dispatch" | "materialize"
    lanes: int  # padded window size
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    packed: bool = False  # packed staging / packed verdict path


# -- per-window pipeline spans (the obs/ flight-recorder vocabulary) ---------
# Per-WINDOW granularity by design: a 100k-header replay emits ~21 of
# these, so the 118.7k headers/s host ceiling is untaxed (the round-8
# object-tax lesson applied to telemetry).


@dataclass(frozen=True)
class WindowStaged:
    """One window left dispatch_batch: how it staged and, when the
    packed wire declined, WHICH qualification gate said no (the PR 5
    columnar/packed gates were silent about why a window fell back)."""

    index: int  # process-wide dispatch sequence number
    lanes: int  # true window size (pre bucket pad)
    lanes_padded: int
    outcome: str  # "packed-agg" | "packed" | "generic"
    gate: str | None  # decline reason when outcome == "generic"
    stage_s: float
    dispatch_s: float


@dataclass(frozen=True)
class LadderEvent:
    """One warm-while-serving compile-ladder transition
    (protocol/batch.WarmLadder): the replay engaged a rung, the
    background production-bucket compile started/landed, or the loop
    re-tiled windows onto the production executables (`swap`)."""

    kind: str  # "engaged" | "bg-compile-started" | "bg-compile-done"
    # | "bg-compile-failed" | "swap"
    rung: int | None  # active rung lane cap (None = production)
    target: int  # production bucket lane count


@dataclass(frozen=True)
class StallEvent:
    """The live-plane stall watchdog (obs/live.py) tripped: no
    recorder/warmup progress for `age_s` seconds against the
    OCT_STALL_BUDGET_S budget. `phase` is the live classification at
    trip time (what the run LOOKED like while it hung); `dump_path`
    names the all-thread stack forensics file written. Escalation is
    the parent's job — this event is evidence, never a kill."""

    phase: str
    age_s: float
    budget_s: float
    dump_path: str | None


@dataclass(frozen=True)
class RecoveryEvent:
    """The recovery supervisor (obs/recovery.py) took an action for a
    failing window: one event per LADDER TRANSITION, so the trajectory
    of an episode (retry -> stage-split -> ... -> recovered/exhausted)
    is a readable event sequence and a countable metric
    (oct_recovery_total{action=}). `fault` is the failure class being
    recovered (the exception type, e.g. DeviceChaosError,
    XlaRuntimeError); `ok` is set on the terminal event of the episode."""

    action: str  # "retry" | "restage" | "stage-split" | "xla-twin"
    # | "host-reference" | "chunk-reread" | "recovered" | "exhausted"
    window: int  # retire-order window index (or -1 when unknown)
    lanes: int
    attempt: int  # 1-based position in the episode's ladder
    fault: str  # exception class name of the original failure
    detail: str  # repr of the triggering exception, trimmed
    ok: bool | None = None  # terminal events: did the episode recover?


@dataclass(frozen=True)
class CheckpointEvent:
    """The crash-consistent progress record (obs/recovery.py) moved:
    a per-retired-window atomic write, or a resume that seeded a replay
    from a record instead of genesis."""

    kind: str  # "write" | "resume" | "complete"
    headers: int  # cumulative retired headers at this point
    windows: int  # cumulative retired windows


@dataclass(frozen=True)
class RepairEvent:
    """The durable store mutated (or, dry-run, WOULD have mutated)
    itself back to consistency (storage/repair.py): a corrupted chunk
    tail truncated on disk, a secondary index rebuilt from chunk
    bytes, a wholly corrupt chunk dropped, an orphaned index swept, or
    a dirty open escalating its validation policy. Snipped bytes are
    QUARANTINED (never deleted); `applied=False` marks a read-only /
    --dry-run scan that only computed the action. Counted into
    ``oct_repair_total{action=}``."""

    action: str  # "truncate-chunk" | "rebuild-index" | "drop-chunk"
    # | "sweep-orphan-index" | "sweep-orphan-sidecar"
    # | "dirty-open-escalated"
    chunk: int  # chunk number (-1 for store-level actions)
    blocks_kept: int
    blocks_dropped: int
    bytes_quarantined: int
    applied: bool  # False = dry-run: computed, not written
    detail: str = ""


@dataclass(frozen=True)
class SidecarEvent:
    """One columnar-sidecar probe/build outcome (storage/sidecar.py):
    the stream loader probed a chunk's ``NNNNN.cols`` seal (hit / miss
    / stale / torn) or backfilled one through the tmp+rename protocol
    (rebuilt). Counted into ``oct_sidecar_total{outcome=}``; a
    non-hit outcome costs exactly one parse fallback, never a verdict
    change."""

    outcome: str  # "hit" | "miss" | "stale" | "rebuilt" | "torn"
    chunk: int = -1


@dataclass(frozen=True)
class ShardSpan:
    """Per-shard WindowSpan analogue for one sharded SPMD dispatch
    (parallel/spmd.sharded_run_batch): how one mesh position fared.
    Emitted host-side after the psum/pmin collectives land — one event
    per shard per window, so a pod-scale replay stays per-window cheap.
    `wall_s` is the whole sharded dispatch wall (identical across the
    window's shards: SPMD lockstep)."""

    index: int  # process-wide sharded-dispatch sequence number
    shard: int  # mesh position
    lanes: int  # shard-local padded lane count
    lanes_real: int  # non-pad lanes this shard carried
    n_ok: int  # popcount of ok verdicts over the real lanes
    pad_lanes: int  # bucket-pad waste in this shard
    wall_s: float


@dataclass(frozen=True)
class AggRedispatch:
    """An aggregated (RLC/MSM) window came back dirty: its per-lane
    flags are meaningless, so materialize_verdicts re-dispatched the
    unchanged per-lane stage kernels (one extra round trip)."""

    lanes: int


@dataclass(frozen=True)
class ForgeSpan:
    """One election window retired through the batched forging
    pipeline (protocol/forge.py via tools/db_synthesizer): the
    pools×slots election grid dispatched, the elected set scattered
    back, and the sequential assembly tail signed + appended. Counted
    into oct_forge_windows_total{engine=} / oct_forge_elected_total /
    oct_forge_signed_total. Per-WINDOW granularity like WindowSpan: a
    10⁷-header synthesis emits ~thousands, never per-block."""

    index: int  # process-wide forge-window sequence number
    engine: str  # "device" | "host" (the loop engine emits none)
    slots: int  # window width in slots
    pairs: int  # pools × slots election grid size
    elected: int  # slots won in this window
    signed: int  # blocks forged + appended (a limit may truncate)
    elect_s: float
    assemble_s: float


@dataclass(frozen=True)
class WindowSpan:
    """One window fully retired through validate_chain's pipelined
    loop: the complete per-phase wall plus the dispatch->materialize
    device latency (t_materialized - t_dispatch)."""

    index: int
    lanes: int
    outcome: str  # WindowStaged.outcome
    gate: str | None
    stage_s: float
    dispatch_s: float
    materialize_s: float  # host wait for the device result
    epilogue_s: float
    t_dispatch: float  # monotonic at dispatch return
    t_materialized: float  # monotonic when the device result landed
    t_done: float  # monotonic after the epilogue
    n_valid: int
    failed: bool  # this window carried the chain's first error


# -- the consensus event vocabulary (Tracers' record, condensed) -------------


@dataclass(frozen=True)
class AddedBlock:
    slot: int
    block_no: int
    hash_: bytes


@dataclass(frozen=True)
class SwitchedToFork:
    n_rollback: int
    new_tip_slot: int


@dataclass(frozen=True)
class InvalidBlockEvent:
    slot: int
    hash_: bytes
    reason: str


# -- the ChainDB event algebra (ChainDB/Impl.hs:10-28) -----------------------
# One dataclass per constructor family: the add-block lifecycle,
# validation verdicts, diffusion pipelining, followers, and the
# copy/snapshot/GC background — typed and matchable so tests assert
# event SEQUENCES, not log strings.


@dataclass(frozen=True)
class IgnoreBlockOlderThanK:
    slot: int
    hash_: bytes


@dataclass(frozen=True)
class IgnoreInvalidBlock:
    slot: int
    hash_: bytes


@dataclass(frozen=True)
class AddedBlockToQueue:
    slot: int
    hash_: bytes
    queue_len: int


@dataclass(frozen=True)
class PoppedBlockFromQueue:
    slot: int
    hash_: bytes


@dataclass(frozen=True)
class AddedBlockToVolatileDB:
    slot: int
    hash_: bytes


@dataclass(frozen=True)
class StoreButDontChange:
    slot: int
    hash_: bytes


@dataclass(frozen=True)
class AddedToCurrentChain:
    n_blocks: int
    new_tip_slot: int


@dataclass(frozen=True)
class SwitchedToAFork:
    n_rollback: int
    n_blocks: int
    new_tip_slot: int


@dataclass(frozen=True)
class ValidCandidate:
    n_blocks: int
    tip_slot: int


@dataclass(frozen=True)
class SetTentativeHeader:
    slot: int
    hash_: bytes


@dataclass(frozen=True)
class TrapTentativeHeader:
    slot: int
    hash_: bytes


@dataclass(frozen=True)
class NewFollowerEvent:
    include_tentative: bool


@dataclass(frozen=True)
class CopiedToImmutableDB:
    n_blocks: int
    up_to_slot: int


@dataclass(frozen=True)
class TookSnapshot:
    n_since_last: int


@dataclass(frozen=True)
class ScheduledGC:
    slot: int


@dataclass(frozen=True)
class PerformedGC:
    slot: int


@dataclass(frozen=True)
class ForgedBlock:
    slot: int
    block_no: int
    adopted: bool


@dataclass(frozen=True)
class ValidatedBatch:
    """The TPU-specific event: one fused device batch completed."""

    n_headers: int
    n_valid: int
    device_s: float


@dataclass
class NodeTracers:
    """Tracers' (Node/Tracers.hs:50): one tracer per subsystem, all
    defaulting to null."""

    chain_db: Tracer = null_tracer
    chain_sync_client: Tracer = null_tracer
    chain_sync_server: Tracer = null_tracer
    block_fetch: Tracer = null_tracer
    mempool: Tracer = null_tracer
    forge: Tracer = null_tracer
    batch_validation: Tracer = null_tracer

    @classmethod
    def all_to(cls, tracer: Tracer) -> "NodeTracers":
        # derive the count from the dataclass fields: a hardcoded arity
        # silently desyncs the moment a tracer field is added (the
        # subsystem after the cut-off would keep its null default)
        import dataclasses

        return cls(**{f.name: tracer for f in dataclasses.fields(cls)})
