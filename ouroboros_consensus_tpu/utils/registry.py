"""ResourceRegistry + RAWLock: structured concurrency for the sim runtime.

Reference: `Ouroboros.Consensus.Util.ResourceRegistry` (1,341 LoC) —
hierarchical ownership of threads/resources with guaranteed reverse-order
release and exception linking to the registry owner — and
`Util/MonadSTM/RAWLock.hs` — the Read/Append/Write lock coordinating
ImmutableDB readers, the single appender, and exclusive writers (GC).

The sim runtime (utils/sim.py) already gives exception LINKING — a task
that raises aborts the whole Sim.run with TaskFailed, which is the
`forkLinkedThread` behavior. The registry adds the ownership half:
resources/tasks registered here die with the registry, LIFO, exactly
once (ResourceRegistry.hs releaseAll).
"""

from __future__ import annotations

from typing import Any, Callable, Generator

from .sim import Event, Sim, Wait


class RegistryClosed(Exception):
    pass


class ResourceRegistry:
    """Owns resources + linked tasks; `close()` kills tasks and releases
    resources in reverse allocation order (ResourceRegistry.hs:releaseAll).
    Usable as a context manager (the reference's withRegistry)."""

    def __init__(self, sim: Sim | None = None):
        self.sim = sim
        self._resources: list[tuple[Any, Callable[[Any], None]]] = []
        self._tasks: list = []
        self._closed = False

    # -- resources -----------------------------------------------------------

    def allocate(self, acquire: Callable[[], Any], release: Callable[[Any], None]):
        """allocate (ResourceRegistry.hs): acquire now, release at close."""
        if self._closed:
            raise RegistryClosed()
        r = acquire()
        self._resources.append((r, release))
        return r

    # -- linked tasks --------------------------------------------------------

    def fork_linked(self, gen: Generator, name: str = "linked"):
        """forkLinkedThread: the task dies with the registry; its
        exceptions already propagate to Sim.run (TaskFailed)."""
        if self._closed:
            raise RegistryClosed()
        assert self.sim is not None, "fork_linked needs a Sim"
        task = self.sim.spawn(gen, name)
        self._tasks.append(task)
        return task

    # -- shutdown ------------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for t in reversed(self._tasks):
            t.alive = False
            # close the generator so try/finally cleanup (e.g. RAWLock
            # waiter counters) runs deterministically, not at GC time
            try:
                t.gen.close()
            except Exception:
                pass
        for r, release in reversed(self._resources):
            release(r)
        self._resources.clear()
        self._tasks.clear()

    def __enter__(self) -> "ResourceRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RAWLock:
    """Read-Append-Write lock (Util/MonadSTM/RAWLock.hs): any number of
    concurrent readers AND at most one appender; a writer excludes
    everyone. Writers take priority over new readers/appenders so they
    cannot starve (the reference's ordering guarantee).

    Usage from sim tasks:   yield from lock.acquire_read()
                            ... lock.release_read()
    """

    def __init__(self, runtime):
        self.runtime = runtime  # anything with .fire(Event)
        self._readers = 0
        self._appender = False
        self._writer = False
        self._writers_waiting = 0
        self._changed = Event("rawlock")

    def _wake(self):
        self.runtime.fire(self._changed)

    # -- read ----------------------------------------------------------------

    def acquire_read(self):
        while self._writer or self._writers_waiting:
            yield Wait(self._changed)
        self._readers += 1

    def release_read(self):
        assert self._readers > 0
        self._readers -= 1
        self._wake()

    # -- append (one at a time, compatible with readers) ---------------------

    def acquire_append(self):
        while self._appender or self._writer or self._writers_waiting:
            yield Wait(self._changed)
        self._appender = True

    def release_append(self):
        assert self._appender
        self._appender = False
        self._wake()

    # -- write (exclusive) ---------------------------------------------------

    def acquire_write(self):
        self._writers_waiting += 1
        try:
            while self._readers or self._appender or self._writer:
                yield Wait(self._changed)
        finally:
            # runs on normal exit AND on generator close (a parked
            # writer killed via ResourceRegistry teardown must not
            # leave the priority counter stuck, starving readers)
            self._writers_waiting -= 1
        self._writer = True

    def release_write(self):
        assert self._writer
        self._writer = False
        self._wake()


def watcher(read, on_change, event: Event, initial=None):
    """Watcher (Util/STM.hs:112): a sim task that re-reads `read()`
    whenever `event` fires and calls `on_change(new)` on every CHANGE of
    the observed value — the forkLinkedWatcher shape driving the forging
    loop (slot changes) and fetch decisions (candidate changes) in the
    reference. Run it under a ResourceRegistry so it dies with its
    owner."""
    last = initial
    while True:
        cur = read()
        if cur != last:
            last = cur
            on_change(cur)
        yield Wait(event)
