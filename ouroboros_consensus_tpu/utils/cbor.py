"""Minimal deterministic CBOR (RFC 8949 subset) encoder/decoder.

Reference equivalent: the `cborg` codecs used throughout the reference for
block/header/ledger serialisation (e.g. the Praos header codec at
ouroboros-consensus-protocol/.../Protocol/Praos/Header.hs:168-238 and the
storage codecs in ouroboros-consensus/.../Storage/Serialisation.hs).

Supports: unsigned/negative ints, byte strings, text strings, definite
arrays/maps, tags, bools/null, and floats (decode only for floats we never
emit). Always emits canonical (smallest-width) heads — encoding is
deterministic, a requirement for hashing headers and golden tests.
"""

from __future__ import annotations

import struct
from typing import Any

_MAJOR_UINT = 0
_MAJOR_NEGINT = 1
_MAJOR_BYTES = 2
_MAJOR_TEXT = 3
_MAJOR_ARRAY = 4
_MAJOR_MAP = 5
_MAJOR_TAG = 6
_MAJOR_SIMPLE = 7


class Tag:
    __slots__ = ("tag", "value")

    def __init__(self, tag: int, value: Any):
        self.tag = tag
        self.value = value

    def __eq__(self, other):
        return (
            isinstance(other, Tag) and other.tag == self.tag and other.value == self.value
        )

    def __repr__(self):
        return f"Tag({self.tag}, {self.value!r})"


def _encode_head(major: int, arg: int) -> bytes:
    mb = major << 5
    if arg < 24:
        return bytes([mb | arg])
    if arg < 1 << 8:
        return bytes([mb | 24, arg])
    if arg < 1 << 16:
        return bytes([mb | 25]) + arg.to_bytes(2, "big")
    if arg < 1 << 32:
        return bytes([mb | 26]) + arg.to_bytes(4, "big")
    if arg < 1 << 64:
        return bytes([mb | 27]) + arg.to_bytes(8, "big")
    raise ValueError("CBOR head argument too large")


def encode(obj: Any) -> bytes:
    out = bytearray()
    _encode_into(obj, out)
    return bytes(out)


def _encode_into(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if obj >= 0:
            out += _encode_head(_MAJOR_UINT, obj)
        else:
            out += _encode_head(_MAJOR_NEGINT, -1 - obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out += _encode_head(_MAJOR_BYTES, len(b))
        out += b
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out += _encode_head(_MAJOR_TEXT, len(b))
        out += b
    elif isinstance(obj, (list, tuple)):
        out += _encode_head(_MAJOR_ARRAY, len(obj))
        for item in obj:
            _encode_into(item, out)
    elif isinstance(obj, dict):
        out += _encode_head(_MAJOR_MAP, len(obj))
        # canonical: sort by encoded key
        items = sorted(((encode(k), v) for k, v in obj.items()), key=lambda kv: kv[0])
        for kenc, v in items:
            out += kenc
            _encode_into(v, out)
    elif isinstance(obj, Tag):
        out += _encode_head(_MAJOR_TAG, obj.tag)
        _encode_into(obj.value, out)
    elif isinstance(obj, float):
        out.append(0xFB)
        out += struct.pack(">d", obj)
    else:
        raise TypeError(f"cannot CBOR-encode {type(obj)}")


class DecodeError(ValueError):
    pass


def decode(data: bytes) -> Any:
    obj, off = _decode_item(data, 0)
    if off != len(data):
        raise DecodeError(f"trailing bytes at {off}")
    return obj


def decode_prefix(data: bytes, offset: int = 0) -> tuple[Any, int]:
    """Decode one item starting at `offset`; return (value, next_offset)."""
    return _decode_item(data, offset)


def _read_head(data: bytes, off: int) -> tuple[int, int, int]:
    if off >= len(data):
        raise DecodeError("truncated")
    ib = data[off]
    major, info = ib >> 5, ib & 0x1F
    off += 1
    if info < 24:
        return major, info, off
    if info == 24:
        n = 1
    elif info == 25:
        n = 2
    elif info == 26:
        n = 4
    elif info == 27:
        n = 8
    else:
        raise DecodeError(f"unsupported head info {info}")
    if off + n > len(data):
        raise DecodeError("truncated head")
    return major, int.from_bytes(data[off : off + n], "big"), off + n


def _decode_item(data: bytes, off: int) -> tuple[Any, int]:
    if off < len(data) and (data[off] >> 5) == _MAJOR_SIMPLE:
        return _decode_simple(data, off)
    major, arg, off = _read_head(data, off)
    if major == _MAJOR_UINT:
        return arg, off
    if major == _MAJOR_NEGINT:
        return -1 - arg, off
    if major == _MAJOR_BYTES:
        if off + arg > len(data):
            raise DecodeError("truncated bytes")
        return data[off : off + arg], off + arg
    if major == _MAJOR_TEXT:
        if off + arg > len(data):
            raise DecodeError("truncated text")
        return data[off : off + arg].decode("utf-8"), off + arg
    if major == _MAJOR_ARRAY:
        items = []
        for _ in range(arg):
            item, off = _decode_item(data, off)
            items.append(item)
        return items, off
    if major == _MAJOR_MAP:
        d = {}
        for _ in range(arg):
            k, off = _decode_item(data, off)
            v, off = _decode_item(data, off)
            if isinstance(k, (bytes, str, int)):
                d[k] = v
            else:
                raise DecodeError("unhashable map key")
        return d, off
    if major == _MAJOR_TAG:
        v, off = _decode_item(data, off)
        return Tag(arg, v), off
    raise DecodeError(f"unsupported major type {major}")


def _decode_simple(data: bytes, off: int) -> tuple[Any, int]:
    """Major type 7: simple values and floats, dispatched on the head INFO
    (not the decoded argument — float bits are payload, not a length)."""
    info = data[off] & 0x1F
    off += 1
    if info == 20:
        return False, off
    if info == 21:
        return True, off
    if info == 22:
        return None, off
    if info in (25, 26, 27):
        n = {25: 2, 26: 4, 27: 8}[info]
        if off + n > len(data):
            raise DecodeError("truncated float")
        fmt = {25: ">e", 26: ">f", 27: ">d"}[info]
        return struct.unpack(fmt, data[off : off + n])[0], off + n
    raise DecodeError(f"unsupported simple value {info}")
