"""HasFS: the filesystem seam.

Reference: the external `fs-api` package (re-exported via
`Ouroboros.Consensus.Storage.FS`) gives every storage component a
`HasFS m h` record instead of raw IO, and `fs-sim` provides an in-memory
implementation with fault injection — the substrate of the q-s-m storage
state-machine tests (SURVEY §4 tier 2; `Test/Util/FS/Sim/MockFS.hs`,
`Test/Util/Corruption.hs`).

Here the seam is a small duck-typed interface sized to what the storage
layer actually does (whole-file reads, positional reads, appends,
atomic-replace writes, fsync, listing, removal):

  * `RealFS` — thin shim over `os`/`open`; rooted at a directory.
  * `MockFS` — in-memory files with an fsync watermark. `crash()`
    reverts every file to its last-synced prefix and then tears the
    unsynced suffix at a caller-chosen fraction — the torn-write model
    the reference injects via fs-sim. `corrupt_byte`/`truncate_file`/
    `wipe` are the q-s-m Corruption commands (StateMachine.hs corrupt/
    wipe generators).

Paths are plain strings (POSIX-joined); components never hold handles
open across calls, so the interface is stateless per operation — which
is also what makes the mock's crash semantics tractable.
"""

from __future__ import annotations

import os
import posixpath


class FsError(OSError):
    """Mock analog of the IO errors the real FS raises (FsError in
    fs-api): storage code catches OSError, so subclass it."""


class RealFS:
    """HasFS over the real filesystem, rooted at `root` (the reference's
    `ioHasFS` with a MountPoint)."""

    def __init__(self, root: str = "/"):
        self.root = root

    def _p(self, path: str) -> str:
        if self.root == "/":
            return path
        # a MountPoint must CONTAIN its paths: absolute inputs are
        # re-rooted, not allowed to escape (os.path.join would discard
        # the root for an absolute second argument)
        return os.path.join(self.root, path.lstrip("/"))

    # -- directories ---------------------------------------------------------

    def makedirs(self, path: str) -> None:
        os.makedirs(self._p(path), exist_ok=True)

    def listdir(self, path: str) -> list[str]:
        return os.listdir(self._p(path))

    def isdir(self, path: str) -> bool:
        return os.path.isdir(self._p(path))

    # -- queries -------------------------------------------------------------

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))

    def getsize(self, path: str) -> int:
        return os.path.getsize(self._p(path))

    # -- reads ---------------------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        with open(self._p(path), "rb") as f:
            return f.read()

    def read_at(self, path: str, offset: int, size: int) -> bytes:
        with open(self._p(path), "rb") as f:
            f.seek(offset)
            return f.read(size)

    # -- writes --------------------------------------------------------------

    def append(self, path: str, data: bytes) -> None:
        with open(self._p(path), "ab") as f:
            f.write(data)

    def write_bytes(self, path: str, data: bytes) -> None:
        with open(self._p(path), "wb") as f:
            f.write(data)

    def write_atomic(self, path: str, data: bytes) -> None:
        """tmp-write + fsync + rename — the snapshot/index discipline."""
        tmp = self._p(path) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._p(path))

    def replace(self, src: str, dst: str) -> None:
        """Atomic rename (the tail of write_atomic, for callers that
        staged + fsynced their own tmp file)."""
        os.replace(self._p(src), self._p(dst))

    def truncate(self, path: str, size: int) -> None:
        with open(self._p(path), "r+b") as f:
            f.truncate(size)

    def remove(self, path: str) -> None:
        if os.path.exists(self._p(path)):
            os.remove(self._p(path))

    def fsync(self, path: str) -> None:
        fd = os.open(self._p(path), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


class _MockFile:
    __slots__ = ("data", "synced", "durable")

    def __init__(self, data: bytes = b""):
        self.data = bytearray(data)
        self.synced = len(data)  # fsync watermark (crash keeps ≤ this)
        # has the file's EXISTENCE been made durable (fsync/atomic
        # rename)? A created-but-never-synced file's directory entry
        # need not survive a crash.
        self.durable = False


class MockFS:
    """In-memory HasFS with crash/corruption injection (fs-sim analog)."""

    def __init__(self):
        self._files: dict[str, _MockFile] = {}
        self._dirs: set[str] = {""}
        # flock analog: held advisory locks live OUTSIDE the file data —
        # a crash (all processes die) releases them all, exactly like
        # the kernel dropping flocks on process death
        self.advisory_locks: set[str] = set()

    @staticmethod
    def _norm(path: str) -> str:
        p = posixpath.normpath(path).lstrip("/")
        return "" if p == "." else p

    # -- directories ---------------------------------------------------------

    def makedirs(self, path: str) -> None:
        p = self._norm(path)
        parts = p.split("/") if p else []
        for i in range(len(parts)):
            self._dirs.add("/".join(parts[: i + 1]))

    def listdir(self, path: str) -> list[str]:
        p = self._norm(path)
        if p not in self._dirs:
            raise FsError(f"no such directory: {path}")
        prefix = p + "/" if p else ""
        out = set()
        for f in self._files:
            if f.startswith(prefix):
                out.add(f[len(prefix):].split("/")[0])
        for d in self._dirs:
            if d != p and d.startswith(prefix):
                out.add(d[len(prefix):].split("/")[0])
        return sorted(out)

    def isdir(self, path: str) -> bool:
        return self._norm(path) in self._dirs

    # -- queries -------------------------------------------------------------

    def exists(self, path: str) -> bool:
        p = self._norm(path)
        return p in self._files or p in self._dirs

    def getsize(self, path: str) -> int:
        f = self._files.get(self._norm(path))
        if f is None:
            raise FsError(f"no such file: {path}")
        return len(f.data)

    # -- reads ---------------------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        f = self._files.get(self._norm(path))
        if f is None:
            raise FsError(f"no such file: {path}")
        return bytes(f.data)

    def read_at(self, path: str, offset: int, size: int) -> bytes:
        return self.read_bytes(path)[offset : offset + size]

    # -- writes --------------------------------------------------------------

    def append(self, path: str, data: bytes) -> None:
        f = self._files.setdefault(self._norm(path), _MockFile())
        f.data.extend(data)

    def write_bytes(self, path: str, data: bytes) -> None:
        p = self._norm(path)
        f = self._files.get(p)
        if f is None:
            self._files[p] = _MockFile(data)
            self._files[p].synced = 0
        else:
            f.data = bytearray(data)
            f.synced = min(f.synced, 0)

    def write_atomic(self, path: str, data: bytes) -> None:
        # rename after fsync: atomic + durable in one step
        p = self._norm(path)
        nf = _MockFile(data)
        nf.synced = len(data)
        nf.durable = True
        self._files[p] = nf

    def replace(self, src: str, dst: str) -> None:
        # atomic rename: the destination inherits the source file whole
        # (synced/durable state included)
        s = self._norm(src)
        f = self._files.pop(s, None)
        if f is None:
            raise FsError(f"no such file: {src}")
        self._files[self._norm(dst)] = f

    def truncate(self, path: str, size: int) -> None:
        f = self._files.get(self._norm(path))
        if f is None:
            raise FsError(f"no such file: {path}")
        del f.data[size:]
        f.synced = min(f.synced, size)

    def remove(self, path: str) -> None:
        self._files.pop(self._norm(path), None)

    def fsync(self, path: str) -> None:
        f = self._files.get(self._norm(path))
        if f is not None:
            f.synced = len(f.data)
            f.durable = True

    # -- fault injection (fs-sim / Test/Util/Corruption.hs) ------------------

    def crash(self, keep_fraction: float = 0.0) -> None:
        """Simulated process/OS crash: unsynced suffixes survive only up
        to `keep_fraction` of their length (0 = lose all unsynced bytes,
        1 = lose nothing) — the torn-write model. Files whose EXISTENCE
        was never made durable (no fsync/atomic write) and that lose all
        their bytes vanish entirely — which is also how a crashed
        process's advisory lock file disappears."""
        self.advisory_locks.clear()  # every holder died with the crash
        for name in list(self._files):
            f = self._files[name]
            if len(f.data) > f.synced:
                keep = f.synced + int((len(f.data) - f.synced) * keep_fraction)
                del f.data[keep:]
            if not f.durable and not f.data:
                del self._files[name]

    def corrupt_byte(self, path: str, offset: int, xor: int = 0xFF) -> None:
        f = self._files[self._norm(path)]
        if 0 <= offset < len(f.data):
            f.data[offset] ^= xor

    def truncate_file(self, path: str, size: int) -> None:
        self.truncate(path, size)

    def wipe(self, path: str) -> None:
        """Remove a file or a whole directory tree (the directory node
        itself included — q-s-m's wipe command semantics)."""
        p = self._norm(path)
        for k in [k for k in self._files if k == p or k.startswith(p + "/")]:
            del self._files[k]
        for d in [d for d in self._dirs if d == p or d.startswith(p + "/")]:
            if d:  # never drop the root
                self._dirs.discard(d)

    def files(self) -> list[str]:
        return sorted(self._files)


REAL_FS = RealFS()
