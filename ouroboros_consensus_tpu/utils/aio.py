"""AsyncRuntime: the asyncio interpreter of the sim effect language —
the IO side of the IOLike seam.

Reference: `Util/IOLike.hs` — the reference writes every component
against `IOLike m` so the SAME code runs under io-sim (deterministic
tests) or IO (the real node). Here the mini-protocols, forging loop and
ChainDB runners are generators yielding Sleep/Recv/Send/Wait/Fire/Spawn
effects (utils/sim.py); this module interprets those SAME generators on
asyncio with real time and real sockets — nothing in the protocol code
changes between a ThreadNet run and a TCP deployment, which is the whole
point of the seam (SURVEY §1 layer 1).

The runtime also satisfies the two attributes synchronous node code
reads: `.fire(event)` (ChainDB notifying followers/add-block runners)
and `.now` (monotonic seconds since runtime start, the wallclock analog
of the Sim's virtual time).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Generator

from .sim import (
    TIMEOUT,
    Channel,
    Event,
    Fire,
    Recv,
    RecvTimeout,
    Send,
    Sleep,
    Spawn,
    Stop,
    Wait,
)


class AsyncRuntime:
    """Drives sim-effect generators on an asyncio event loop."""

    def __init__(self):
        self.t0 = time.monotonic()
        self._chan_q: dict[int, asyncio.Queue] = {}  # id(Channel) -> queue
        self._ev: dict[int, asyncio.Event] = {}  # id(Event) -> generation
        self.tasks: list[asyncio.Task] = []

    @property
    def now(self) -> float:
        return time.monotonic() - self.t0

    # -- channels ----------------------------------------------------------

    def _q(self, chan: Channel) -> asyncio.Queue:
        q = self._chan_q.get(id(chan))
        if q is None:
            q = self._chan_q[id(chan)] = asyncio.Queue()
        return q

    def deliver(self, chan: Channel, msg: Any) -> None:
        """Push an inbound message (the transport's rx pump calls this)."""
        self._q(chan).put_nowait(msg)

    def send(self, chan: Channel, msg: Any) -> None:
        remote = getattr(chan, "remote_send", None)
        if remote is not None:
            remote(msg)  # a transport-bound channel: straight to the wire
        elif chan.delay:
            asyncio.get_running_loop().call_later(
                chan.delay, self._q(chan).put_nowait, msg
            )
        else:
            self._q(chan).put_nowait(msg)

    # -- events ------------------------------------------------------------

    def fire(self, event: Event) -> None:
        """Wake ALL current waiters (broadcast): the per-generation
        asyncio.Event is set and retired; later waiters get a fresh one.
        Callable from synchronous code inside a task step — the
        STM-TVar-write analog, same contract as Sim.fire."""
        ev = self._ev.pop(id(event), None)
        if ev is not None:
            ev.set()

    def _wait_event(self, event: Event) -> asyncio.Event:
        ev = self._ev.get(id(event))
        if ev is None:
            ev = self._ev[id(event)] = asyncio.Event()
        return ev

    # -- task driving ------------------------------------------------------

    def spawn(self, gen: Generator, name: str = "task") -> asyncio.Task:
        t = asyncio.get_running_loop().create_task(
            self._drive(gen, name), name=name
        )
        self.tasks.append(t)
        return t

    async def _drive(self, gen: Generator, name: str) -> Any:
        value: Any = None
        try:
            while True:
                try:
                    eff = gen.send(value)
                except StopIteration as e:
                    return e.value
                value = None
                if isinstance(eff, Sleep):
                    await asyncio.sleep(eff.dt)
                elif isinstance(eff, Recv):
                    value = await self._q(eff.chan).get()
                elif isinstance(eff, RecvTimeout):
                    try:
                        value = await asyncio.wait_for(
                            self._q(eff.chan).get(), eff.dt
                        )
                    except asyncio.TimeoutError:
                        value = TIMEOUT
                elif isinstance(eff, Send):
                    self.send(eff.chan, eff.msg)
                elif isinstance(eff, Wait):
                    await self._wait_event(eff.event).wait()
                elif isinstance(eff, Fire):
                    self.fire(eff.event)
                elif isinstance(eff, Spawn):
                    value = self.spawn(eff.gen, eff.name)
                elif isinstance(eff, Stop):
                    return None
                else:
                    raise TypeError(f"task {name!r} yielded {eff!r}")
        finally:
            gen.close()

    async def shutdown(self) -> None:
        for t in self.tasks:
            t.cancel()
        await asyncio.gather(*self.tasks, return_exceptions=True)
        self.tasks.clear()
