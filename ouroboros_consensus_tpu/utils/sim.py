"""Deterministic discrete-event simulation runtime — the io-sim analog.

The reference runs its entire node against the `IOLike` abstraction so
any component executes unmodified under io-sim's simulated scheduler and
virtual clock (Util/IOLike.hs; runSimOrThrow at ThreadNet/General.hs:37).
This module provides the same property for the TPU framework's control
plane: cooperative tasks are plain Python generators yielding effect
requests to a scheduler whose order is a pure function of (spawn order,
virtual time) — every run of the same program is bit-identical, so
multi-node tests (testing/threadnet.py) are reproducible, and a failing
schedule can be replayed under a debugger.

Effects a task can yield:
  Sleep(dt)        — resume at now + dt
  Recv(chan)       — resume when a message is available (returns it)
  Send(chan, msg)  — enqueue (arrives after chan.delay); never blocks
  Wait(event)      — resume when the event fires
  Fire(event)      — wake all waiters
  Spawn(gen)       — start a child task, resume immediately (returns Task)
  Stop()           — kill this task

Determinism rule: the run queue is ordered by (time, seq) where seq
increases monotonically with every scheduling action — FIFO among
same-time wakeups. No real clock, no OS threads, no races: the analog of
io-sim's schedule exploration is varying spawn order / delays via the
test's PRNG seed.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Any, Generator, Iterable


# -- effect requests ---------------------------------------------------------


@dataclass(frozen=True)
class Sleep:
    dt: float


@dataclass(frozen=True)
class Recv:
    chan: "Channel"


@dataclass(frozen=True)
class RecvTimeout:
    """Recv that resumes with the TIMEOUT sentinel after `dt` if no
    message arrived — the typed-protocols timeout analog (the reference
    enforces e.g. the KeepAlive response deadline this way)."""

    chan: "Channel"
    dt: float


class _Timeout:
    def __repr__(self):
        return "TIMEOUT"


TIMEOUT = _Timeout()  # the RecvTimeout sentinel (identity-compared)


@dataclass(frozen=True)
class Send:
    chan: "Channel"
    msg: Any


@dataclass(frozen=True)
class Wait:
    event: "Event"


@dataclass(frozen=True)
class Fire:
    event: "Event"


@dataclass(frozen=True)
class Spawn:
    gen: Generator
    name: str = "task"


@dataclass(frozen=True)
class Stop:
    pass


class Channel:
    """Unbounded FIFO with a fixed per-message delivery delay (the
    ThreadNet `createConnectedChannelsWithDelay` analog, Network.hs:1341)."""

    def __init__(self, delay: float = 0.0, name: str = "chan"):
        self.delay = delay
        self.name = name
        self._ready: list = []  # heap of (deliver_time, seq, msg)
        self._waiters: list = []  # Tasks blocked on Recv, FIFO


class Event:
    """Broadcast wakeup (the Watcher-on-a-TVar analog, Util/STM.hs:112)."""

    def __init__(self, name: str = "event"):
        self.name = name
        self._waiters: list = []


class TaskFailed(Exception):
    """A task raised; the failure propagates out of Sim.run — the
    ResourceRegistry link-to-parent semantics (Util/ResourceRegistry.hs)."""

    def __init__(self, task_name: str, exc: BaseException):
        super().__init__(f"task {task_name!r} failed: {exc!r}")
        self.task_name = task_name
        self.exc = exc


@dataclass
class Task:
    name: str
    gen: Generator
    alive: bool = True
    result: Any = None
    wait_seq: int = 0  # identifies the CURRENT park (stale-timeout guard)


class Sim:
    """The deterministic scheduler.

    `seed` enables SCHEDULE EXPLORATION (io-sim's strongest property,
    exercised in the reference by varying QuickCheck seeds, SURVEY §5.2):
    same-time wakeups are ordered by a seed-keyed permutation instead of
    FIFO. Every seed still yields a fully deterministic, replayable run —
    a property that fails under seed 1234 fails under seed 1234 forever —
    but DIFFERENT seeds exercise different interleavings of the same
    program, surfacing order-dependent bugs that one schedule would hide.
    """

    def __init__(self, seed: int | None = None):
        self.now = 0.0
        self._seq = 0
        self.seed = seed
        # heap entries: (time, order_key, seq, kind, payload)
        #   kind "task":    payload = (Task, resume_value)
        #   kind "deliver": payload = Channel — flush due messages
        self._runq: list = []
        self.tasks: list[Task] = []
        self.stopped = False

    # -- plumbing ----------------------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _order_key(self, seq: int) -> int:
        """FIFO by default; a seeded pseudo-random tiebreak otherwise
        (deterministic per (seed, seq) — replayable)."""
        if self.seed is None:
            return seq
        # splitmix-style integer hash of (seed, seq)
        z = (seq + self.seed * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        return z ^ (z >> 31)

    def _schedule(self, t: float, task: Task, value: Any = None) -> None:
        seq = self._next_seq()
        heapq.heappush(
            self._runq, (t, self._order_key(seq), seq, "task", (task, value))
        )

    def fire(self, event: Event) -> None:
        """Wake all waiters of `event`. Callable both from task context
        (the Fire effect routes here) and from synchronous code holding
        the scheduler — e.g. ChainDB.add_block_async notifying the
        add-block runner (the STM-TVar-write analog)."""
        for w in event._waiters:
            self._schedule(self.now, w)
        event._waiters.clear()

    def _schedule_delivery(self, t: float, chan: Channel) -> None:
        seq = self._next_seq()
        heapq.heappush(
            self._runq, (t, self._order_key(seq), seq, "deliver", chan)
        )

    def spawn(self, gen: Generator, name: str = "task") -> Task:
        task = Task(name, gen)
        self.tasks.append(task)
        self._schedule(self.now, task)
        return task

    def _flush_channel(self, chan: Channel) -> None:
        """Hand due messages to blocked receivers (FIFO both sides)."""
        while chan._waiters and chan._ready and chan._ready[0][0] <= self.now:
            _, _, msg = heapq.heappop(chan._ready)
            task = chan._waiters.pop(0)
            self._schedule(self.now, task, msg)

    # -- effect handling ---------------------------------------------------

    def _step(self, task: Task, value: Any) -> None:
        if not task.alive:
            return
        try:
            eff = task.gen.send(value)
        except StopIteration as e:
            task.alive = False
            task.result = e.value
            return
        except Exception as e:
            task.alive = False
            raise TaskFailed(task.name, e) from e

        if isinstance(eff, Sleep):
            self._schedule(self.now + eff.dt, task)
        elif isinstance(eff, (Recv, RecvTimeout)):
            chan = eff.chan
            if chan._ready and chan._ready[0][0] <= self.now and not chan._waiters:
                _, _, msg = heapq.heappop(chan._ready)
                self._schedule(self.now, task, msg)
            else:
                # earlier receivers are queued: join the FIFO behind them
                # (a due message must not let a latecomer jump the queue)
                chan._waiters.append(task)
                task.wait_seq = self._next_seq()
                if chan._ready:  # in-flight message: wake at its due time
                    self._schedule_delivery(chan._ready[0][0], chan)
                if isinstance(eff, RecvTimeout):
                    seq = self._next_seq()
                    heapq.heappush(self._runq, (
                        self.now + eff.dt, self._order_key(seq), seq,
                        "timeout", (chan, task, task.wait_seq),
                    ))
        elif isinstance(eff, Send):
            due = self.now + eff.chan.delay
            heapq.heappush(eff.chan._ready, (due, self._next_seq(), eff.msg))
            if eff.chan._waiters:
                self._schedule_delivery(due, eff.chan)
            self._schedule(self.now, task)
        elif isinstance(eff, Wait):
            eff.event._waiters.append(task)
        elif isinstance(eff, Fire):
            self.fire(eff.event)
            self._schedule(self.now, task)
        elif isinstance(eff, Spawn):
            child = self.spawn(eff.gen, eff.name)
            self._schedule(self.now, task, child)
        elif isinstance(eff, Stop):
            task.alive = False
        else:
            raise TypeError(f"task {task.name!r} yielded {eff!r}")

    # -- run loop ----------------------------------------------------------

    def run(self, until: float | None = None, max_steps: int = 10_000_000) -> float:
        """Run until the queue drains or virtual time passes `until`.
        Returns the final virtual time."""
        steps = 0
        while self._runq and not self.stopped:
            t, _, _, kind, payload = self._runq[0]
            if until is not None and t > until:
                self.now = until
                return self.now
            heapq.heappop(self._runq)
            self.now = max(self.now, t)
            if kind == "deliver":
                self._flush_channel(payload)
                continue
            if kind == "timeout":
                chan, task, wait_seq = payload
                # fire only if the task is STILL in this very park (a
                # delivered message, or a later re-park on the same
                # channel, invalidates the timer)
                if (task.alive and task.wait_seq == wait_seq
                        and task in chan._waiters):
                    chan._waiters.remove(task)
                    self._schedule(self.now, task, TIMEOUT)
                continue
            task, value = payload
            self._step(task, value)
            steps += 1
            if steps >= max_steps:
                raise RuntimeError("sim exceeded max_steps (livelock?)")
        return self.now


# -- convenience for tests ---------------------------------------------------


def run_sim(mains: Iterable[tuple[str, Generator]], until: float | None = None) -> Sim:
    sim = Sim()
    for name, gen in mains:
        sim.spawn(gen, name)
    sim.run(until=until)
    return sim
