"""Numpy-backed metrics registry: counters, gauges, fixed-bucket
histograms, Prometheus text exposition and JSON snapshots.

Reference: cardano-node maps the consensus tracers onto EKG/Prometheus
gauges (SURVEY.md layer 4-5: "tracers -> EKG/Prometheus"); the registry
here is the TPU build's equivalent sink. Everything is host-side and
allocation-light: a histogram is one int64 numpy counts array indexed by
`np.searchsorted` over a fixed upper-bound vector, so observing a value
(or a whole column of values at once via `observe_many`) costs no Python
object churn on the hot path — the round-8 "object tax" lesson applied
to telemetry itself.

Vocabulary (one metric family per name, optional labels):

    reg = MetricsRegistry()
    wins = reg.counter("oct_windows_total", "windows", ("outcome",))
    wins.labels(outcome="packed").inc()
    lat = reg.histogram("oct_window_materialize_seconds", "d2h wait")
    lat.observe(0.012)
    print(reg.expose_text())      # Prometheus text format 0.0.4
    json.dumps(reg.snapshot())    # machine-readable twin
"""

from __future__ import annotations

import threading
from typing import Iterable, Mapping, Sequence

import numpy as np

# default latency buckets (seconds): µs-scale staging through the
# ~410 s compile walls the warmup forensics must still resolve
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 60.0, 120.0, 300.0, 600.0,
)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render without a decimal."""
    f = float(v)
    if f.is_integer() and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _label_str(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotone counter (one labeled child of a family)."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    """Instantaneous value."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Histogram:
    """Fixed-bucket histogram: `uppers` are the finite upper bounds; the
    +Inf bucket is implicit. Counts live in one int64 numpy array."""

    def __init__(self, lock: threading.Lock,
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        ups = np.asarray(sorted(buckets), np.float64)
        if ups.size == 0:
            raise ValueError("histogram needs at least one bucket")
        self._lock = lock
        self.uppers = ups
        self.counts = np.zeros(ups.size + 1, np.int64)
        self.sum = 0.0
        self.dropped_nonfinite = 0

    @property
    def count(self) -> int:
        """Total observations. Takes the registry lock: `counts` is
        mutated under it by concurrent observe()/observe_many(), so an
        unlocked sum could tear against a mid-flight bincount add (the
        SLO endpoint scrapes while the serving scheduler observes)."""
        with self._lock:
            return self._count_locked()

    def _count_locked(self) -> int:
        # caller holds self._lock (exposition renders under it and the
        # shared lock is non-reentrant, so the public property would
        # deadlock — same split as _snapshot_locked/_expose_text_locked)
        return int(self.counts.sum())

    def observe(self, value: float) -> None:
        v = float(value)
        if not np.isfinite(v):
            # a NaN/inf observation (a timing bug, a poisoned column)
            # would poison `sum` forever and leak NaN into every JSON
            # snapshot — including the bench round file, which must stay
            # strict-JSON. Drop it, but keep the drop countable.
            with self._lock:
                self.dropped_nonfinite += 1
            return
        with self._lock:
            self.counts[int(np.searchsorted(self.uppers, v))] += 1
            self.sum += v

    def observe_many(self, values) -> None:
        """Vectorized observe of a whole column (one searchsorted + one
        bincount — no per-value Python). Non-finite entries are dropped
        (and counted) like `observe` does."""
        a = np.asarray(values, np.float64).ravel()
        if a.size == 0:
            return
        finite = np.isfinite(a)
        n_bad = int(a.size - finite.sum())
        if n_bad:
            a = a[finite]
        idx = np.searchsorted(self.uppers, a)
        with self._lock:
            self.dropped_nonfinite += n_bad
            if a.size:
                self.counts += np.bincount(idx, minlength=self.counts.size)
                self.sum += float(a.sum())

    def quantile(self, q: float) -> float | None:
        """Bucket-interpolated quantile (the Prometheus histogram_quantile
        estimate). None when empty — NEVER NaN: a NaN here would ride
        the p50/p99 fields of `snapshot()` into the bench round JSON
        and break strict-JSON consumers. The +Inf bucket clamps to the
        last finite bound.

        Takes the registry lock: the cumsum must see one consistent
        `counts` array, not a row torn against a concurrent observe —
        the recorder's `latency_summary()` and the serve SLO snapshot
        both call this from scrape threads while the run observes."""
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> float | None:
        # caller holds self._lock (non-reentrant; snapshot() renders
        # every child's p50/p99 under it)
        total = self._count_locked()
        if total == 0:
            return None
        rank = q * total
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, rank, side="left"))
        if i >= self.uppers.size:  # landed in +Inf
            return float(self.uppers[-1])
        lo = 0.0 if i == 0 else float(self.uppers[i - 1])
        hi = float(self.uppers[i])
        below = 0 if i == 0 else int(cum[i - 1])
        in_bucket = int(self.counts[i])
        if in_bucket == 0:
            return hi
        v = lo + (hi - lo) * (rank - below) / in_bucket
        return v if np.isfinite(v) else None


_TYPES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class _Family:
    """One named metric family; children keyed by label values."""

    def __init__(self, registry: "MetricsRegistry", name: str, help_: str,
                 cls, labelnames: tuple[str, ...], **kw):
        self.name = name
        self.help = help_
        self.cls = cls
        self.labelnames = labelnames
        self._kw = kw
        self._lock = registry._lock
        self._children: dict[tuple, object] = {}  # guarded-by: _lock
        if not labelnames:
            self._default = self._make(())

    def _make(self, key: tuple):
        child = self.cls(self._lock, **self._kw)
        self._children[key] = child
        return child

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(kv)}"
            )
        key = tuple(str(kv[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is not None:
            return child
        # create under the registry lock: two racing first-touches must
        # share ONE child (a lost duplicate would drop its increments),
        # and a concurrent exposition must never see the dict mid-insert
        with self._lock:
            child = self._children.get(key)
            return child if child is not None else self._make(key)

    # unlabeled families proxy the child API directly
    def __getattr__(self, attr):
        if not self.labelnames:
            return getattr(self._default, attr)
        raise AttributeError(attr)

    def samples(self):
        """[(labels dict, child)] in stable (sorted) order."""
        for key in sorted(self._children):
            yield dict(zip(self.labelnames, key)), self._children[key]


class MetricsRegistry:
    """Name -> family. One lock per registry: events arrive from both
    the dispatch thread and the materialize worker."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}  # guarded-by: _lock

    def _family(self, name: str, help_: str, cls, labelnames, **kw):
        # registration and exposition share the registry lock: a scrape
        # must never iterate _families/_children mid-insert
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.cls is not cls or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered differently"
                    )
                return fam
            fam = _Family(self, name, help_, cls, tuple(labelnames), **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "",
                labelnames: Iterable[str] = ()) -> _Family:
        return self._family(name, help_, Counter, labelnames)

    def gauge(self, name: str, help_: str = "",
              labelnames: Iterable[str] = ()) -> _Family:
        return self._family(name, help_, Gauge, labelnames)

    def histogram(self, name: str, help_: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> _Family:
        return self._family(name, help_, Histogram, labelnames,
                            buckets=buckets)

    # -- exposition ---------------------------------------------------------

    def expose_text(self) -> str:
        """Prometheus text exposition format 0.0.4. Holds the registry
        lock for the render: concurrent label first-touches and
        increments wait instead of mutating the dicts mid-iteration."""
        with self._lock:
            return self._expose_text_locked()

    def _expose_text_locked(self) -> str:
        out: list[str] = []
        for name in sorted(self._families):
            fam = self._families[name]
            out.append(f"# HELP {name} {fam.help}")
            out.append(f"# TYPE {name} {_TYPES[fam.cls]}")
            for labels, child in fam.samples():
                if isinstance(child, Histogram):
                    cum = 0
                    for upper, c in zip(child.uppers, child.counts):
                        cum += int(c)
                        le = dict(labels)
                        le["le"] = _fmt(float(upper))
                        out.append(f"{name}_bucket{_label_str(le)} {cum}")
                    le = dict(labels)
                    le["le"] = "+Inf"
                    n = child._count_locked()
                    out.append(
                        f"{name}_bucket{_label_str(le)} {n}"
                    )
                    out.append(
                        f"{name}_sum{_label_str(labels)} {_fmt(child.sum)}"
                    )
                    out.append(
                        f"{name}_count{_label_str(labels)} {n}"
                    )
                else:
                    out.append(
                        f"{name}{_label_str(labels)} {_fmt(child.value)}"
                    )
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> dict:
        """JSON-able twin of the exposition (bench.py banks this)."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        snap: dict = {}
        for name in sorted(self._families):
            fam = self._families[name]
            rows = []
            for labels, child in fam.samples():
                if isinstance(child, Histogram):
                    rows.append({
                        "labels": labels,
                        "count": child._count_locked(),
                        "sum": child.sum,
                        **({"dropped_nonfinite": child.dropped_nonfinite}
                           if child.dropped_nonfinite else {}),
                        "buckets": {
                            _fmt(float(u)): int(c)
                            for u, c in zip(child.uppers, child.counts)
                        },
                        "inf": int(child.counts[-1]),
                        "p50": child._quantile_locked(0.5),
                        "p99": child._quantile_locked(0.99),
                    })
                else:
                    rows.append({"labels": labels, "value": child.value})
            snap[name] = {
                "type": _TYPES[fam.cls], "help": fam.help, "samples": rows,
            }
        return snap


_DEFAULT: MetricsRegistry | None = None


def default_registry() -> MetricsRegistry:
    """The process-wide registry (immdb_server exposition, the flight
    recorder's sink)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = MetricsRegistry()
    return _DEFAULT


def reset_default_registry() -> None:
    """Test isolation: drop the process-wide registry."""
    global _DEFAULT
    _DEFAULT = None
