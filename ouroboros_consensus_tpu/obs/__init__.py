"""obs: pipeline-wide telemetry — the flight recorder.

The reference threads contravariant `Tracer`s through every subsystem
and maps them onto EKG/Prometheus gauges (SURVEY.md layers 4-5); this
package is the TPU build's equivalent surface, all host-side:

  * `registry`  — numpy-backed counters / gauges / fixed-bucket
                  histograms, Prometheus text exposition + JSON snapshot
  * `recorder`  — the FlightRecorder batch tracer: per-window spans
                  through validate_chain's pipelined loop, fed into the
                  registry (see `OCT_TRACE` below)
  * `warmup`    — compile/warmup forensics: per-stage first-execute
                  walls, pk-AOT load/reject attribution, the bench
                  cache probe; crash-safe JSON via $OCT_WARMUP_REPORT
  * `perfetto`  — Chrome trace-event (chrome://tracing / Perfetto)
                  export of a replay's event stream (+ warmup track)
  * `ledger`    — append-only JSONL run ledger (.oct_ledger/): one
                  provenance-complete record per bench / suite /
                  profile run — git rev+dirty, PJRT build id, every
                  OCT_* kill-switch, metrics, warmup, banked result
  * `resources` — device resource accounting: FLOPs / bytes / HBM per
                  dispatched stage program (oct_stage_* gauges, the
                  budgets.json "device_resources" ratchet)
  * `live`      — the LIVE run plane: in-run heartbeat snapshots
                  (OCT_HEARTBEAT), the stall watchdog with all-thread
                  stack forensics (OCT_STALL_BUDGET_S), armed by
                  db_analyser.revalidate / bench / profile_replay
  * `server`    — the one HTTP exposition implementation (/metrics,
                  /metrics.json, /healthz, /progress): asyncio for
                  immdb_server, thread-hosted for replays
                  (OCT_METRICS_PORT)

Env levers:

  OCT_TRACE=1          install the flight recorder for replays
                       (db_analyser.revalidate, profile_replay, bench)
  OCT_WARMUP_REPORT=f  flush warmup forensics to `f` after every note
  OCT_LEDGER=d|0       run-ledger directory override / kill-switch
  OCT_STAGE_RESOURCES  =0 kills per-stage resource capture; =1 forces
                       it; unset follows the installed recorder
  OCT_HEARTBEAT=f      rewrite a live JSON heartbeat to `f` every ~2 s
  OCT_STALL_BUDGET_S=n stall watchdog: no-progress budget before an
                       all-thread stack dump (+ oct_stalls_total)
  OCT_STALL_DUMP=f     stall forensics file override (default: next to
                       the warmup report)
  OCT_METRICS_PORT=p   serve /metrics /metrics.json /healthz /progress
                       from inside the replay on port p

Everything stays OFF the hot path unless installed: with OCT_TRACE
unset, `protocol.batch.BATCH_TRACER` remains None and the only residual
cost is one module-level assignment per declined packed window."""

from __future__ import annotations

import os
import threading

from .recorder import FlightRecorder
from .registry import MetricsRegistry, default_registry
from .warmup import WARMUP

_ENV = "OCT_TRACE"

_LOCK = threading.Lock()
_RECORDER: FlightRecorder | None = None
_INSTALL_DEPTH = 0
_PREV_TRACER = None


def enabled() -> bool:
    """The OCT_TRACE lever (read per call so tests can flip it)."""
    return os.environ.get(_ENV, "0") not in ("0", "")


def installed() -> bool:
    """True while at least one install() is outstanding — the default
    gate for the per-stage resource capture (obs/resources.py): replays
    that installed the recorder account device resources, bare unit
    runs pay nothing."""
    with _LOCK:
        return _INSTALL_DEPTH > 0


def recorder() -> FlightRecorder:
    """The process-wide FlightRecorder (created on first use)."""
    global _RECORDER
    with _LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def install() -> FlightRecorder:
    """Chain the flight recorder into protocol.batch.BATCH_TRACER
    (keeping any tracer an embedding application already set).
    Re-entrant: nested installs share one chain entry."""
    global _INSTALL_DEPTH, _PREV_TRACER
    rec = recorder()
    with _LOCK:
        if _INSTALL_DEPTH == 0:
            from ..protocol import batch as pbatch

            prev = pbatch.BATCH_TRACER
            _PREV_TRACER = prev
            if prev is None:
                pbatch.set_batch_tracer(rec)
            else:
                def chained(ev, _prev=prev, _rec=rec):
                    _prev(ev)
                    _rec(ev)

                pbatch.set_batch_tracer(chained)
        _INSTALL_DEPTH += 1
    return rec


def uninstall() -> None:
    """Undo one `install`; the outermost uninstall restores the
    previous tracer."""
    global _INSTALL_DEPTH, _PREV_TRACER
    with _LOCK:
        if _INSTALL_DEPTH == 0:
            return
        _INSTALL_DEPTH -= 1
        if _INSTALL_DEPTH == 0:
            from ..protocol import batch as pbatch

            pbatch.set_batch_tracer(_PREV_TRACER)
            _PREV_TRACER = None


def maybe_install() -> bool:
    """install() iff OCT_TRACE is set; returns whether it installed
    (pair with uninstall())."""
    if enabled():
        install()
        return True
    return False


def reset_for_tests() -> None:
    """Drop the process-wide recorder + registry (test isolation)."""
    global _RECORDER, _INSTALL_DEPTH, _PREV_TRACER
    from .registry import reset_default_registry

    # an armed live plane holds a recorder reference — drop it first
    from . import live as _live

    _live.reset_for_tests()
    with _LOCK:
        if _INSTALL_DEPTH > 0:
            from ..protocol import batch as pbatch

            pbatch.set_batch_tracer(_PREV_TRACER)
        _RECORDER = None
        _INSTALL_DEPTH = 0
        _PREV_TRACER = None
        reset_default_registry()
