"""The live metrics/health HTTP endpoint — ONE implementation.

Factored out of `tools/immdb_server.serve_metrics` so every long-lived
process serves the same surface: the immdb block service mounts the
asyncio coroutine (`serve_metrics`), while replays (bench's device
child, `profile_replay.py`, `db_analyser.revalidate`) mount the
thread-hosted twin via `OCT_METRICS_PORT` (`start_in_thread` /
`obs.live.maybe_arm`). This is the SLO surface ROADMAP item 3's
serving tier will scrape.

Routes (minimal HTTP/1.0, no dependencies):

    GET /metrics        Prometheus text exposition format 0.0.4
    GET /metrics.json   the registry's JSON snapshot
    GET /healthz        the live heartbeat document (obs/live.py)
    GET /progress       compact progress twin: phase / headers /
                        headers_per_s / age_s / window_index
    GET /slo            serving-plane SLO document (node/serve.py
                        `ValidationService.slo_snapshot`): p50/p99
                        verdict latency, aggregate headers/s, queue
                        depths, degraded-mode flag + intervals. 404
                        when no serving plane is mounted (`slo_doc`
                        unset) — replays have no SLO surface.

Every request increments `oct_metrics_scrapes_total{path=}` (label
values are the FIXED route names, never wire input)."""

from __future__ import annotations

import json
import os
import threading

_PORT_ENV = "OCT_METRICS_PORT"

_PROGRESS_KEYS = (
    "phase", "headers", "headers_per_s", "age_s", "window_index",
    "stalls", "ts_unix", "seq",
)


def metrics_port() -> int | None:
    v = os.environ.get(_PORT_ENV)
    if not v:
        return None
    try:
        port = int(v)
    except ValueError:
        return None
    # 0 would be a valid ephemeral bind, but as an env lever it means
    # "disabled" (the immdb --metrics-port convention)
    return port if port > 0 else None


def _live_doc(live_doc) -> dict:
    if live_doc is not None:
        return live_doc()
    from . import live

    return live.live_snapshot()


def handle_path(path: str, registry=None, live_doc=None, slo_doc=None):
    """Route one GET -> (status: bytes, content-type: bytes, body:
    bytes). Shared by the asyncio and threaded servers so the two can
    never drift."""
    from .registry import default_registry

    reg = registry if registry is not None else default_registry()
    scrapes = reg.counter(
        "oct_metrics_scrapes_total", "metric-endpoint requests", ("path",)
    )
    if path.startswith("/metrics.json"):
        scrapes.labels(path="/metrics.json").inc()
        return (b"200 OK", b"application/json",
                json.dumps(reg.snapshot()).encode())
    if path.startswith("/metrics"):
        scrapes.labels(path="/metrics").inc()
        return (b"200 OK", b"text/plain; version=0.0.4",
                reg.expose_text().encode())
    if path.startswith("/healthz"):
        scrapes.labels(path="/healthz").inc()
        return (b"200 OK", b"application/json",
                json.dumps(_live_doc(live_doc)).encode())
    if path.startswith("/progress"):
        scrapes.labels(path="/progress").inc()
        doc = _live_doc(live_doc)
        slim = {k: doc.get(k) for k in _PROGRESS_KEYS if k in doc}
        return (b"200 OK", b"application/json", json.dumps(slim).encode())
    if path.startswith("/slo"):
        scrapes.labels(path="/slo").inc()
        if slo_doc is None:
            return (b"404 Not Found", b"text/plain",
                    b"no serving plane mounted\n")
        return (b"200 OK", b"application/json",
                json.dumps(slo_doc()).encode())
    return (b"404 Not Found", b"text/plain",
            b"try /metrics /metrics.json /healthz /progress /slo\n")


def _render(status: bytes, ctype: bytes, body: bytes) -> bytes:
    return (b"HTTP/1.0 " + status + b"\r\nContent-Type: " + ctype
            + b"\r\nContent-Length: " + str(len(body)).encode()
            + b"\r\n\r\n" + body)


# ---------------------------------------------------------------------------
# asyncio server (mounted by tools/immdb_server beside the block service)
# ---------------------------------------------------------------------------


async def serve_metrics(host: str = "127.0.0.1", port: int = 9100,
                        registry=None, live_doc=None, slo_doc=None):
    """Minimal HTTP/1.0 responder over asyncio — the cardano-node
    EKG/Prometheus bridge analog. `port=0` binds ephemeral (tests)."""
    import asyncio

    async def handle(reader, writer):
        try:
            req = await reader.readline()
            while True:  # drain headers
                line = await reader.readline()
                if line in (b"", b"\n", b"\r\n"):
                    break
            parts = req.split()
            path = (parts[1].decode("ascii", "replace")
                    if len(parts) > 1 else "/")
            writer.write(_render(*handle_path(
                path, registry, live_doc, slo_doc)))
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    return await asyncio.start_server(handle, host, port)


# ---------------------------------------------------------------------------
# thread-hosted server (replays: synchronous callers, OCT_METRICS_PORT)
# ---------------------------------------------------------------------------


class MetricsServer:
    """The same responder on a daemon thread with its own socket loop,
    for synchronous hosts (a replay has no event loop to mount on).
    `port=0` binds ephemeral; `.port` reports the bound port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry=None, live_doc=None, slo_doc=None):
        import socket

        self.registry = registry
        self.live_doc = live_doc
        self.slo_doc = slo_doc
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self._sock.settimeout(0.5)  # close() latency bound
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="oct-metrics-http", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        import socket

        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed under us
            try:
                conn.settimeout(5.0)
                data = b""
                while b"\r\n\r\n" not in data and b"\n\n" not in data:
                    chunk = conn.recv(4096)
                    if not chunk:
                        break
                    data += chunk
                parts = data.split(None, 2)
                path = (parts[1].decode("ascii", "replace")
                        if len(parts) > 1 else "/")
                conn.sendall(_render(*handle_path(
                    path, self.registry, self.live_doc, self.slo_doc
                )))
            except OSError:
                pass  # a broken scrape never breaks the replay
            except Exception:  # noqa: BLE001 — and neither does a
                # handler bug: count it, answer 500, keep serving
                self._note_handler_error(conn)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _note_handler_error(self, conn) -> None:
        from .registry import default_registry

        reg = (self.registry if self.registry is not None
               else default_registry())
        reg.counter(
            "oct_metrics_scrape_errors_total", "scrape-handler failures"
        ).inc()
        try:
            conn.sendall(_render(b"500 Internal Server Error",
                                 b"text/plain", b"scrape handler error\n"))
        except OSError:
            pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5)


def start_in_thread(port: int | None = None, host: str = "127.0.0.1",
                    registry=None, live_doc=None,
                    slo_doc=None) -> MetricsServer | None:
    """Mount the thread-hosted endpoint on `port` (default: the
    OCT_METRICS_PORT lever; None/unset -> no server). Fail-soft: a
    port already in use logs to stderr and returns None rather than
    killing the replay it was meant to observe."""
    import sys

    port = metrics_port() if port is None else port
    if port is None:
        return None
    try:
        srv = MetricsServer(host=host, port=port, registry=registry,
                            live_doc=live_doc, slo_doc=slo_doc)
    except OSError as e:
        print(f"# obs/server: cannot bind metrics port {port}: {e}",
              file=sys.stderr)
        return None
    print(f"# obs/server: live metrics on http://{srv.host}:{srv.port}"
          "/metrics (/metrics.json /healthz /progress /slo)",
          file=sys.stderr)
    return srv
