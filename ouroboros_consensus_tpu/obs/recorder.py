"""FlightRecorder: the batch-tracer sink behind the OCT_TRACE lever.

One process-wide recorder chains into `protocol.batch.BATCH_TRACER`
(preserving whatever tracer an embedding application already set),
keeps the timed event stream for Perfetto export, and folds every
event into the metrics registry:

    oct_windows_total{outcome=}            dispatched windows
    oct_gate_declines_total{gate=}         why packed staging said no
    oct_headers_validated_total            retired lanes
    oct_agg_redispatch_total               dirty aggregate windows
    oct_h2d_bytes_total / oct_d2h_bytes_total
    oct_window_{stage,dispatch,materialize,epilogue}_seconds   histograms
    oct_window_device_latency_seconds      dispatch->materialize wall
    oct_stalls_total{phase=}               stall-watchdog trips (obs/live)
    oct_recovery_total{action=}            recovery-ladder transitions
    oct_checkpoint_events_total{kind=}     progress-record movement
                                           (obs/recovery)
    oct_repair_total{action=}              on-disk store repairs applied
                                           (storage/repair)
    oct_sidecar_total{outcome=}            columnar-sidecar probe/build
                                           outcomes (storage/sidecar)
    oct_shard_{windows,lanes,ok_lanes,pad_lanes}_total{shard=}
                                           per-shard SPMD telemetry
    oct_forge_windows_total{engine=}       election windows dispatched
                                           (protocol/forge ForgeSpan)
    oct_forge_elected_total                slots won across windows
    oct_forge_signed_total                 blocks forged + appended

Per-window granularity only — a 1M-header replay emits a few hundred
events, so the host feed ceiling is untaxed."""

from __future__ import annotations

import threading
import time

from ..utils.trace import (
    AggRedispatch, CheckpointEvent, EncloseEvent, ForgeSpan, LadderEvent,
    RecoveryEvent, RepairEvent, ShardSpan, SidecarEvent, StallEvent,
    TransferEvent, WindowSpan, WindowStaged,
)
from . import registry as _registry

# bounded event buffer: a pathological run cannot grow without limit
MAX_EVENTS = 200_000


class FlightRecorder:
    def __init__(self, reg: "_registry.MetricsRegistry | None" = None):
        self.registry = reg if reg is not None else _registry.default_registry()
        self._lock = threading.Lock()
        self.events: list[tuple[float, object]] = []
        self.dropped = 0
        r = self.registry
        self._windows = r.counter(
            "oct_windows_total", "dispatched device windows", ("outcome",)
        )
        self._gates = r.counter(
            "oct_gate_declines_total",
            "packed-staging qualification gate declines", ("gate",),
        )
        self._headers = r.counter(
            "oct_headers_validated_total", "lanes retired valid"
        )
        self._redisp = r.counter(
            "oct_agg_redispatch_total",
            "aggregate windows re-dispatched per-lane",
        )
        self._ladder = r.counter(
            "oct_ladder_events_total",
            "warm-ladder transitions (engaged/bg-compile/swap)", ("kind",),
        )
        self._h2d = r.counter("oct_h2d_bytes_total", "bytes staged to device")
        self._d2h = r.counter("oct_d2h_bytes_total", "bytes returned to host")
        self._phase_h = {
            p: r.histogram(
                f"oct_window_{p}_seconds", f"per-window {p} wall"
            )
            for p in ("stage", "dispatch", "materialize", "epilogue")
        }
        self._latency = r.histogram(
            "oct_window_device_latency_seconds",
            "dispatch->materialize wall per window",
        )
        # live plane (obs/live.py): stall-watchdog trips by the phase
        # the run was wedged in at trip time
        self._stalls = r.counter(
            "oct_stalls_total", "stall-watchdog trips", ("phase",)
        )
        # recovery plane (obs/recovery.py): ladder transitions per
        # action, and checkpoint record movement (write/resume/complete)
        self._recovery = r.counter(
            "oct_recovery_total",
            "recovery-supervisor ladder transitions", ("action",),
        )
        self._checkpoints = r.counter(
            "oct_checkpoint_events_total",
            "progress-record writes/resumes/completions", ("kind",),
        )
        # durable-store repair plane (storage/repair.py): on-disk
        # repairs the open-with-repair scan applied (truncated tails,
        # rebuilt indices, dropped chunks, dirty-open escalations) —
        # dry-run/would-repair events are NOT counted here, they only
        # ride the warmup report's `repairs` rows
        self._repairs = r.counter(
            "oct_repair_total",
            "on-disk store repair actions applied", ("action",),
        )
        # columnar-sidecar plane (storage/sidecar.py): every freshness
        # probe / backfill outcome — hit is the parse-free fast path,
        # everything else costs exactly one parse fallback
        self._sidecar = r.counter(
            "oct_sidecar_total",
            "columnar-sidecar probe/build outcomes", ("outcome",),
        )
        # per-shard SPMD telemetry (parallel/spmd.py ShardSpan events):
        # label cardinality is the mesh size — bounded by hardware
        self._shard_windows = r.counter(
            "oct_shard_windows_total",
            "sharded windows dispatched per mesh position", ("shard",),
        )
        self._shard_lanes = r.counter(
            "oct_shard_lanes_total",
            "real (non-pad) lanes dispatched per shard", ("shard",),
        )
        self._shard_ok = r.counter(
            "oct_shard_ok_lanes_total",
            "lanes retired valid per shard (psum popcount vocabulary)",
            ("shard",),
        )
        self._shard_pad = r.counter(
            "oct_shard_pad_lanes_total",
            "bucket-pad waste lanes per shard", ("shard",),
        )
        # forge plane (protocol/forge.py ForgeSpan events): the batched
        # synthesizer's election windows, elected slots and signed
        # blocks — label cardinality is the engine set (device/host)
        self._forge_windows = r.counter(
            "oct_forge_windows_total",
            "forge election windows dispatched", ("engine",),
        )
        self._forge_elected = r.counter(
            "oct_forge_elected_total", "slots won in forge windows"
        )
        self._forge_signed = r.counter(
            "oct_forge_signed_total", "blocks forged and appended"
        )
        # heartbeat source: the most recent event (kept even after the
        # bounded buffer fills) + the latest retired window index
        self._last: "tuple[float, object] | None" = None
        self._last_span_index = -1

    # -- the tracer ---------------------------------------------------------

    def __call__(self, ev) -> None:
        now = time.monotonic()
        with self._lock:
            self._last = (now, ev)
            if len(self.events) < MAX_EVENTS:
                self.events.append((now, ev))
            else:
                self.dropped += 1
        if isinstance(ev, WindowStaged):
            self._windows.labels(outcome=ev.outcome).inc()
            if ev.outcome == "generic":
                self._gates.labels(gate=ev.gate or "packed-off").inc()
            elif ev.gate:
                # a non-generic outcome can still carry a gate: the
                # octwall pre-flight refusal ("compile-wall-refused")
                # rides a PACKED window that fell back off the
                # aggregate path — it must be countable, not only
                # visible to someone reading raw event streams
                self._gates.labels(gate=ev.gate).inc()
        elif isinstance(ev, WindowSpan):
            with self._lock:
                if ev.index > self._last_span_index:
                    self._last_span_index = ev.index
            self._headers.inc(ev.n_valid)
            self._phase_h["stage"].observe(ev.stage_s)
            self._phase_h["dispatch"].observe(ev.dispatch_s)
            self._phase_h["materialize"].observe(ev.materialize_s)
            self._phase_h["epilogue"].observe(ev.epilogue_s)
            self._latency.observe(
                max(0.0, ev.t_materialized - ev.t_dispatch)
            )
        elif isinstance(ev, AggRedispatch):
            self._redisp.inc()
        elif isinstance(ev, LadderEvent):
            self._ladder.labels(kind=ev.kind).inc()
        elif isinstance(ev, TransferEvent):
            if ev.phase == "dispatch":
                self._h2d.inc(ev.h2d_bytes)
            else:
                self._d2h.inc(ev.d2h_bytes)
        elif isinstance(ev, StallEvent):
            self._stalls.labels(phase=ev.phase).inc()
        elif isinstance(ev, RecoveryEvent):
            self._recovery.labels(action=ev.action).inc()
        elif isinstance(ev, CheckpointEvent):
            self._checkpoints.labels(kind=ev.kind).inc()
        elif isinstance(ev, RepairEvent):
            if ev.applied:
                self._repairs.labels(action=ev.action).inc()
        elif isinstance(ev, SidecarEvent):
            self._sidecar.labels(outcome=ev.outcome).inc()
        elif isinstance(ev, ShardSpan):
            s = str(ev.shard)
            self._shard_windows.labels(shard=s).inc()
            self._shard_lanes.labels(shard=s).inc(ev.lanes_real)
            self._shard_ok.labels(shard=s).inc(ev.n_ok)
            self._shard_pad.labels(shard=s).inc(ev.pad_lanes)
            # shards also count as headers retired on the sharded path
            # ONLY through their WindowSpan-carrying replay loop — the
            # per-shard families never double-fold into oct_headers_*
        elif isinstance(ev, ForgeSpan):
            self._forge_windows.labels(engine=ev.engine).inc()
            self._forge_elected.inc(ev.elected)
            self._forge_signed.inc(ev.signed)
        # EncloseEvent: kept in the event stream (Perfetto slices) only

    # -- live plane (obs/live.py heartbeat source) --------------------------

    def last_event(self) -> "tuple[float, object] | None":
        """(monotonic t, event) of the newest event seen — kept fresh
        even once the bounded buffer is full, so a week-long run's
        heartbeat never reads a stale phase."""
        with self._lock:
            return self._last

    def progress_fingerprint(self) -> tuple:
        """A cheap value that changes whenever the replay makes ANY
        observable progress (the stall watchdog's no-progress test):
        headers retired, last retired window index, and the timestamp
        of the newest event."""
        with self._lock:
            last_t = self._last[0] if self._last is not None else 0.0
            n = len(self.events) + self.dropped
        return (self._headers.value, self._last_span_index, last_t, n)

    def headers_retired(self) -> int:
        return int(self._headers.value)

    def last_window_index(self) -> int:
        with self._lock:
            return self._last_span_index

    # -- reporting ----------------------------------------------------------

    def timed_events(self) -> list[tuple[float, object]]:
        with self._lock:
            return list(self.events)

    def _warmup_state(self) -> tuple[dict, float]:
        """This process's warmup forensics + the recorder's monotonic
        epoch: the Perfetto export places stage first-execute slices
        (the compile walls) on the same timeline as the window spans."""
        from .warmup import WARMUP

        return WARMUP.report(), WARMUP.t0

    def chrome_trace(self) -> dict:
        from . import perfetto

        report, t0 = self._warmup_state()
        return perfetto.to_chrome_trace(self.timed_events(), report, t0)

    def write_chrome_trace(self, path: str) -> dict:
        from . import perfetto

        report, t0 = self._warmup_state()
        return perfetto.write(path, self.timed_events(), report, t0)

    def latency_summary(self) -> dict:
        """p50/p99 of the dispatch->materialize device latency plus the
        per-phase p50s — the serving-north-star numbers (ROADMAP #3)."""
        out = {
            "device_latency_p50_s": self._latency.quantile(0.5),
            "device_latency_p99_s": self._latency.quantile(0.99),
            "windows": self._latency.count,
        }
        for p, h in self._phase_h.items():
            out[f"{p}_p50_s"] = h.quantile(0.5)
        return out

    def clear(self) -> None:
        with self._lock:
            self.events.clear()
            self.dropped = 0
            self._last = None
            self._last_span_index = -1
