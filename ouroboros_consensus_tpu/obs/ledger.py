"""The run ledger: append-only provenance for every replay.

Five bench rounds in, the single biggest fact about the trajectory —
r01 banked a device number, r02–r05 banked nothing — was only
discoverable by hand-diffing `BENCH_r0*.json`; WHAT changed between
rounds (git state, PJRT build, kill-switch flips) was archaeology. The
ledger turns it into a query: every `bench.py` run, `bench_suite`
config and `profile_replay` invocation appends ONE provenance-complete
JSONL record, so "what was different when r01 banked?" is a
`read_runs()` filter, and `scripts/perf_report.py` folds the ledger
into the cross-round trajectory report.

Layout: `<repo>/.oct_ledger/runs-YYYYMMDD.jsonl`, one JSON object per
line, keyed by day so a long-lived box rotates naturally and a day's
runs diff cleanly. Append-only by construction — records are never
rewritten; a corrupt line (a crash mid-append) is skipped and counted
by `read_runs`, never fatal.

Record schema (SCHEMA_VERSION = 1, validated by `validate_record` and
the tier-1 schema test):

    schema        int     — SCHEMA_VERSION
    kind          str     — "bench" | "bench_suite" | "profile_replay"
                            | "replay" | ...
    ts_unix       float   — epoch seconds at append
    ts_iso        str     — UTC ISO-8601 twin (human grep)
    git           dict    — {"rev": str|None, "dirty": bool|None}
    build_id      str|None— PJRT platform_version when a backend is up
    env           dict    — every OCT_* value plus JAX_PLATFORMS and
                            BENCH_* (the kill-switch state that made
                            r02–r05 archaeology)
    host          dict    — {"platform", "pid", "argv"}
    config        dict|None — chain/config shape (headers, max_batch,
                            kes_depth, ...)
    result        dict|None — the banked outcome (bench's JSON line,
                            a suite row, profile numbers)
    wall_s        float|None
    phases_s      dict|None — per-phase wall attribution
    warmup_report dict|None — the obs/warmup block
    metrics       dict|None — a MetricsRegistry snapshot
    metrics_summary dict|None
    device_resources dict|None — obs/resources.RESOURCES.report()
    extra         dict|None

Env lever: `OCT_LEDGER=<dir>` overrides the directory; `OCT_LEDGER=0`
is the kill-switch (record_run becomes a no-op returning None).
Everything is fail-soft: a read-only filesystem or a git-less checkout
degrades to partial provenance, never a crashed replay."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_ENV = "OCT_LEDGER"

SCHEMA_VERSION = 1

# env keys banked verbatim: the OCT_* kill-switch family plus the knobs
# that shaped the run (chain scale, platform pin)
_ENV_PREFIXES = ("OCT_", "BENCH_")
_ENV_EXTRA = ("JAX_PLATFORMS",)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_DIR = os.path.join(_REPO, ".oct_ledger")

# optional dict-typed payload sections (None when the run had none)
_OPTIONAL_DICTS = (
    "config", "result", "phases_s", "warmup_report", "metrics",
    "metrics_summary", "device_resources", "extra",
)


def ledger_dir() -> str | None:
    """Resolved ledger directory, or None when the kill-switch is on."""
    v = os.environ.get(_ENV)
    if v == "0":
        return None
    return v or DEFAULT_DIR


def day_file(dir_: str, ts: float | None = None) -> str:
    day = time.strftime("%Y%m%d", time.gmtime(
        time.time() if ts is None else ts))
    return os.path.join(dir_, f"runs-{day}.jsonl")


# ---------------------------------------------------------------------------
# Provenance probes (each best-effort: None beats a crashed replay)
# ---------------------------------------------------------------------------


def git_provenance(repo: str | None = None) -> dict:
    """{"rev": ..., "dirty": ...} of the working tree, None/None when
    git is unavailable — the r01→r02 question ('what code was this?')
    answered at append time, not reconstructed later."""
    repo = repo or _REPO
    rev = dirty = None
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=repo, timeout=10, check=True,
        ).stdout.strip() or None
        status = subprocess.run(
            ["git", "status", "--porcelain"], capture_output=True,
            text=True, cwd=repo, timeout=10, check=True,
        ).stdout
        dirty = bool(status.strip())
    except Exception:  # noqa: BLE001 — git-less checkouts stay recordable
        pass
    return {"rev": rev, "dirty": dirty}


def runtime_build_id() -> str | None:
    """PJRT platform_version of an ALREADY-INITIALIZED backend. Never
    initializes one: probing jax.devices() on this box can hang a
    wedged TPU tunnel (the round-2 postmortem), and the parent bench
    process deliberately never touches the backend."""
    if "jax" not in sys.modules:
        return None
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return None
        import jax

        return str(jax.devices()[0].client.platform_version)
    except Exception:  # noqa: BLE001
        return None


def env_snapshot() -> dict:
    return {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith(_ENV_PREFIXES) or k in _ENV_EXTRA
    }


# ---------------------------------------------------------------------------
# Record construction / validation / append
# ---------------------------------------------------------------------------


def build_record(kind: str, *, config: dict | None = None,
                 result: dict | None = None,
                 wall_s: float | None = None,
                 phases_s: dict | None = None,
                 warmup_report: dict | None = None,
                 metrics: dict | None = None,
                 metrics_summary: dict | None = None,
                 device_resources: dict | None = None,
                 build_id: str | None = None,
                 extra: dict | None = None) -> dict:
    """One provenance-complete record (not yet appended)."""
    now = time.time()
    return {
        "schema": SCHEMA_VERSION,
        "kind": str(kind),
        "ts_unix": now,
        "ts_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)),
        "git": git_provenance(),
        "build_id": build_id if build_id is not None else runtime_build_id(),
        "env": env_snapshot(),
        "host": {
            "platform": sys.platform,
            "pid": os.getpid(),
            "argv": list(sys.argv),
        },
        "config": config,
        "result": result,
        "wall_s": None if wall_s is None else float(wall_s),
        "phases_s": phases_s,
        "warmup_report": warmup_report,
        "metrics": metrics,
        "metrics_summary": metrics_summary,
        "device_resources": device_resources,
        "extra": extra,
    }


def validate_record(rec) -> list[str]:
    """Schema gate (tier-1 runs this over every appended record):
    returns problems, [] = well-formed."""
    errs: list[str] = []
    if not isinstance(rec, dict):
        return ["record is not an object"]
    if rec.get("schema") != SCHEMA_VERSION:
        errs.append(f"schema must be {SCHEMA_VERSION}, got "
                    f"{rec.get('schema')!r}")
    if not isinstance(rec.get("kind"), str) or not rec.get("kind"):
        errs.append("kind missing or not a non-empty string")
    if not isinstance(rec.get("ts_unix"), (int, float)):
        errs.append("ts_unix missing or not a number")
    if not isinstance(rec.get("ts_iso"), str):
        errs.append("ts_iso missing or not a string")
    git = rec.get("git")
    if not isinstance(git, dict) or "rev" not in git or "dirty" not in git:
        errs.append("git must be a dict with rev and dirty")
    if not (rec.get("build_id") is None
            or isinstance(rec.get("build_id"), str)):
        errs.append("build_id must be a string or null")
    if not isinstance(rec.get("env"), dict):
        errs.append("env missing or not a dict")
    host = rec.get("host")
    if not isinstance(host, dict) or "platform" not in host:
        errs.append("host must be a dict with platform")
    for key in _OPTIONAL_DICTS:
        v = rec.get(key)
        if v is not None and not isinstance(v, dict):
            errs.append(f"{key} must be a dict or null")
    w = rec.get("wall_s")
    if w is not None and not isinstance(w, (int, float)):
        errs.append("wall_s must be a number or null")
    try:
        json.dumps(rec, allow_nan=False)
    except (TypeError, ValueError) as e:
        errs.append(f"not strict-JSON-serializable: {e}")
    return errs


def append(rec: dict, path: str | None = None) -> str | None:
    """Append one record as one JSONL line (single write — concurrent
    appenders interleave at line granularity under O_APPEND). Returns
    the file written, or None when the ledger is disabled/unwritable
    (telemetry never breaks the run it describes)."""
    if path is None:
        dir_ = ledger_dir()
        if dir_ is None:
            return None
        path = day_file(dir_)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        line = json.dumps(rec, sort_keys=True, allow_nan=False)
        # The ledger is an append-only JSONL journal, not a
        # rewrite-in-place document: O_APPEND keeps concurrent appenders
        # line-atomic and iter_runs tolerates a torn tail line, so
        # tmp+rename would break (not add) the durability protocol here.
        with open(path, "a", encoding="utf-8") as f:  # octsync: disable=SYNC207
            f.write(line + "\n")
        return path
    except (OSError, TypeError, ValueError):
        return None


def record_run(kind: str, **kw) -> dict | None:
    """build_record + append in one call — the one-liner every script
    uses. Returns the record (with `_path` noting where it landed) or
    None when the kill-switch is on."""
    if ledger_dir() is None:
        return None
    rec = build_record(kind, **kw)
    path = append(rec)
    if path is None:
        return None
    rec["_path"] = path
    return rec


def record_replay(kind: str, recorder=None, **kw) -> dict | None:
    """record_run with the obs state folded in automatically: the
    flight recorder's registry snapshot + latency summary, the warmup
    report, and the stage resource ledger — what profile_replay and the
    bench child bank without each caller re-plumbing obs."""
    from .resources import RESOURCES
    from .warmup import WARMUP

    if recorder is not None:
        kw.setdefault("metrics", recorder.registry.snapshot())
        kw.setdefault("metrics_summary", recorder.latency_summary())
    kw.setdefault("warmup_report", WARMUP.report())
    res = RESOURCES.report()
    if res:
        kw.setdefault("device_resources", res)
    return record_run(kind, **kw)


# ---------------------------------------------------------------------------
# Queries
# ---------------------------------------------------------------------------


def iter_runs(dir_: str | None = None):
    """Yield (record, file, lineno) over every day file, oldest day
    first; corrupt lines are skipped (never fatal)."""
    dir_ = dir_ if dir_ is not None else ledger_dir()
    if dir_ is None or not os.path.isdir(dir_):
        return
    for name in sorted(os.listdir(dir_)):
        if not (name.startswith("runs-") and name.endswith(".jsonl")):
            continue
        path = os.path.join(dir_, name)
        try:
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line), path, i
                    except json.JSONDecodeError:
                        continue  # torn append: skip, keep reading
        except OSError:
            continue


def read_runs(dir_: str | None = None, kind: str | None = None) -> list[dict]:
    """All (optionally kind-filtered) records, append order."""
    return [rec for rec, _p, _i in iter_runs(dir_)
            if kind is None or rec.get("kind") == kind]


# ---------------------------------------------------------------------------
# CLI: `python -m ouroboros_consensus_tpu.obs.ledger tail --last N`
# ---------------------------------------------------------------------------


def _result_blurb(rec: dict) -> str:
    """One human line out of a record's banked result — "what did this
    run do" without hand-parsing JSONL."""
    res = rec.get("result") or {}
    parts = []
    if res.get("value") is not None:
        unit = res.get("unit", "")
        parts.append(f"{res['value']} {unit}".strip())
    elif res.get("rate_per_s") is not None:
        parts.append(f"{res['rate_per_s']} headers/s")
    elif res.get("ceiling_per_s") is not None:
        parts.append(f"ceiling {res['ceiling_per_s']} headers/s")
    if res.get("device_unavailable"):
        parts.append("NO-DEVICE"
                     + (f" ({res['no_device_reason']})"
                        if res.get("no_device_reason") else ""))
    if res.get("headers") is not None:
        parts.append(f"{res['headers']} headers")
    ms = rec.get("metrics_summary") or {}
    if ms.get("windows"):
        parts.append(f"{ms['windows']} windows")
    metrics = rec.get("metrics") or {}
    stalls = sum(
        int(s.get("value", 0))
        for s in (metrics.get("oct_stalls_total") or {}).get("samples", [])
    )
    if stalls:
        parts.append(f"{stalls} STALL(s)")
    shard_fams = [k for k in metrics if k.startswith("oct_shard_")]
    if shard_fams:
        shards = {
            (s.get("labels") or {}).get("shard")
            for k in shard_fams
            for s in (metrics.get(k) or {}).get("samples", [])
        }
        parts.append(f"per-shard telemetry x{len(shards - {None})}")
    return ", ".join(parts) or "(no result banked)"


def format_run(rec: dict) -> str:
    build = rec.get("build_id") or "-"
    if len(build) > 24:
        build = build[:21] + "..."
    wall = rec.get("wall_s")
    wall_s = f"{wall:.0f}s" if isinstance(wall, (int, float)) else "?"
    return (
        f"{rec.get('ts_iso', '?'):20s} {rec.get('kind', '?'):14s} "
        f"build={build:24s} wall={wall_s:6s} " + _result_blurb(rec)
    )


def main(argv: list[str] | None = None) -> int:
    """`tail --last N [--kind K] [--build-id SUBSTR] [--json]`: the
    "what did the last live session do" one-liner over read_runs."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m ouroboros_consensus_tpu.obs.ledger",
        description="query the append-only run ledger",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    tail = sub.add_parser(
        "tail", help="newest runs, one line each (newest last)"
    )
    tail.add_argument("--last", type=int, default=10, metavar="N",
                      help="show the newest N runs (default 10)")
    tail.add_argument("--kind", default=None,
                      help="filter by record kind (bench / multichip / "
                           "profile_replay / ...)")
    tail.add_argument("--build-id", default=None, dest="build_id",
                      help="substring filter over the PJRT build id")
    tail.add_argument("--dir", default=None,
                      help="ledger directory (default: the repo ledger / "
                           "OCT_LEDGER)")
    tail.add_argument("--json", action="store_true",
                      help="print the full records as JSONL instead")
    args = ap.parse_args(argv)

    runs = read_runs(args.dir, kind=args.kind)
    if args.build_id is not None:
        runs = [r for r in runs if args.build_id in (r.get("build_id") or "")]
    runs = runs[-args.last:] if args.last > 0 else []
    if not runs:
        print("(no matching ledger records)")
        return 1
    for rec in runs:
        if args.json:
            print(json.dumps(rec, sort_keys=True))
        else:
            print(format_run(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
