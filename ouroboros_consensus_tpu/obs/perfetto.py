"""Chrome trace-event (Perfetto / chrome://tracing) export.

Converts the flight recorder's timed event stream — `Enclose` phase
brackets, `TransferEvent` byte accounting, `WindowStaged`/`WindowSpan`
pipeline spans — into the Trace Event Format JSON that Perfetto and
chrome://tracing load directly:

    python scripts/profile_replay.py --trace-out /tmp/replay.json
    # then open ui.perfetto.dev and drag the file in

Layout: one process ("oct replay"), one thread row per phase label
(stage / dispatch / materialize / epilogue / stream), a "windows" row
holding one complete ("X") slice per retired window whose args carry
lanes / outcome / gate / n_valid, counter ("C") tracks for the H2D
and D2H bytes per window, and a "warmup" row rebuilt from the warmup
recorder (obs/warmup.py): one slice per stage FIRST execute (the
compile wall that dominates cold runs — previously invisible in the
very tool meant to visualize walls) plus instants for every pk-AOT
load outcome and octwall pre-flight refusal. The warmup rows need the
recorder's own monotonic t0 to share the event stream's timeline, so
they appear when exporting from a live process (FlightRecorder
.chrome_trace / scripts/profile_replay.py --trace-out), not when
rendering a report file from another process.

`validate_chrome_trace` is the schema gate the tier-1 test runs over a
replay export: structural validation of the JSON object model per the
Trace Event Format spec (required keys, phase vocabulary, numeric
non-negative ts/dur, JSON-serializability)."""

from __future__ import annotations

import json
from typing import Iterable

from ..utils.trace import (
    EncloseEvent, TransferEvent, WindowSpan, WindowStaged,
)

PID = 1
# stable thread ids per track; unknown phase labels allocate past these
_TIDS = {
    "windows": 1, "stage": 2, "dispatch": 3, "materialize": 4,
    "epilogue": 5, "stream": 6, "warmup": 7,
}

_ALLOWED_PH = {"X", "B", "E", "i", "C", "M"}


def _meta(name: str, tid: int | None = None) -> dict:
    ev = {
        "name": "process_name" if tid is None else "thread_name",
        "ph": "M",
        "pid": PID,
        "ts": 0,
        "args": {"name": name},
    }
    if tid is not None:
        ev["tid"] = tid
    else:
        ev["tid"] = 0
    return ev


def to_chrome_trace(timed_events: Iterable[tuple[float, object]],
                    warmup_report: dict | None = None,
                    warmup_t0: float | None = None) -> dict:
    """[(t_monotonic_received, event)] -> Trace Event Format document.

    `EncloseEvent` end edges become complete "X" slices on their label's
    track (their own t/duration stamps, not the receive time);
    `WindowSpan`s become "X" slices on the windows track; dirty-window
    re-dispatches and other events ride as instants on track 0;
    `TransferEvent`s become per-window byte counters.

    `warmup_report` (with `warmup_t0`, the recorder's monotonic epoch —
    report timestamps are relative to it) adds the warmup track:
    per-stage first-execute slices with aot/jit attribution, pk-AOT
    load-outcome instants, and octwall pre-flight refusal instants."""
    timed = list(timed_events)
    tids = dict(_TIDS)

    def tid_of(label: str) -> int:
        t = tids.get(label)
        if t is None:
            t = tids[label] = max(tids.values()) + 1
        return t

    wu = warmup_report if (warmup_report and warmup_t0 is not None) else None

    # normalize all timestamps against the earliest one observed — the
    # warmup slices usually start BEFORE the first window event (the
    # compile precedes the replay), so they join the minimum
    t_zero = None
    for t_recv, ev in timed:
        cand = t_recv
        if isinstance(ev, EncloseEvent):
            cand = ev.t - (ev.duration or 0.0)
        t_zero = cand if t_zero is None else min(t_zero, cand)
    if wu:
        for row in wu.get("stages", {}).values():
            cand = warmup_t0 + float(row.get("t", 0.0)) - float(
                row.get("wall_s", 0.0))
            t_zero = cand if t_zero is None else min(t_zero, cand)
        for ev_row in (wu.get("aot_events", []) + wu.get("refusals", [])
                       + wu.get("ladder", [])):
            cand = warmup_t0 + float(ev_row.get("t", 0.0))
            t_zero = cand if t_zero is None else min(t_zero, cand)
    if t_zero is None:
        t_zero = 0.0

    def us(t: float) -> float:
        return max(0.0, (t - t_zero) * 1e6)

    events: list[dict] = [_meta("oct replay")]
    for label, t in sorted(_TIDS.items(), key=lambda kv: kv[1]):
        events.append(_meta(label, t))

    n_xfer = 0
    for t_recv, ev in timed:
        if isinstance(ev, EncloseEvent):
            if ev.edge != "end" or ev.duration is None:
                continue  # start edges carry no duration; the end edge
                # alone reconstructs the complete slice
            events.append({
                "name": ev.label, "cat": "phase", "ph": "X",
                "ts": us(ev.t - ev.duration), "dur": ev.duration * 1e6,
                "pid": PID, "tid": tid_of(ev.label),
            })
        elif isinstance(ev, WindowSpan):
            t0 = ev.t_dispatch - ev.dispatch_s - ev.stage_s
            events.append({
                "name": f"window {ev.index} [{ev.outcome}]",
                "cat": "window", "ph": "X",
                "ts": us(t0), "dur": max(0.0, (ev.t_done - t0) * 1e6),
                "pid": PID, "tid": _TIDS["windows"],
                "args": {
                    "lanes": ev.lanes, "outcome": ev.outcome,
                    "gate": ev.gate or "", "n_valid": ev.n_valid,
                    "failed": ev.failed,
                    "device_latency_ms": round(
                        (ev.t_materialized - ev.t_dispatch) * 1e3, 3
                    ),
                },
            })
        elif isinstance(ev, TransferEvent):
            n_xfer += 1
            counter = ("h2d_bytes" if ev.phase == "dispatch"
                       else "d2h_bytes")
            events.append({
                "name": counter, "cat": "transfer", "ph": "C",
                "ts": us(t_recv), "pid": PID, "tid": 0,
                "args": {counter: ev.h2d_bytes or ev.d2h_bytes},
            })
        elif isinstance(ev, WindowStaged):
            # instants only for declined windows — the WindowSpan slice
            # already tells the packed story
            if ev.outcome == "generic":
                events.append({
                    "name": f"gate: {ev.gate or 'packed-off'}",
                    "cat": "gate", "ph": "i", "s": "t",
                    "ts": us(t_recv), "pid": PID, "tid": _TIDS["windows"],
                })

    if wu:
        wtid = _TIDS["warmup"]
        for stage, row in sorted(wu.get("stages", {}).items()):
            wall = float(row.get("wall_s", 0.0))
            end = warmup_t0 + float(row.get("t", 0.0))
            args = {"via": row.get("via", "jit"),
                    "wall_s": wall}
            if row.get("feature_hash"):
                args["feature_hash"] = row["feature_hash"]
            events.append({
                "name": f"{stage} first-execute [{row.get('via', 'jit')}]",
                "cat": "warmup", "ph": "X",
                "ts": us(end - wall), "dur": max(0.0, wall * 1e6),
                "pid": PID, "tid": wtid, "args": args,
            })
        for ev_row in wu.get("aot_events", []):
            events.append({
                "name": (f"aot {ev_row.get('stage', '?')}: "
                         f"{ev_row.get('outcome', '?')}"),
                "cat": "warmup", "ph": "i", "s": "t",
                "ts": us(warmup_t0 + float(ev_row.get("t", 0.0))),
                "pid": PID, "tid": wtid,
            })
        for ref in wu.get("refusals", []):
            events.append({
                "name": (f"compile-wall refused: {ref.get('stage', '?')} "
                         f"(predicted {ref.get('predicted_s', '?')}s > "
                         f"remaining {ref.get('remaining_s', '?')}s)"),
                "cat": "warmup", "ph": "i", "s": "t",
                "ts": us(warmup_t0 + float(ref.get("t", 0.0))),
                "pid": PID, "tid": wtid,
            })
        # the warm-ladder trajectory: the background production compile
        # renders as a SLICE (bg-compile-started -> bg-compile-done, the
        # wall the ladder hides behind served windows), every other
        # event as an instant carrying its rung/hash args
        bg_start = None
        for lad in wu.get("ladder", []):
            kind = lad.get("kind", "?")
            t_abs = warmup_t0 + float(lad.get("t", 0.0))
            if kind == "bg-compile-started":
                bg_start = t_abs
            if kind in ("bg-compile-done", "bg-compile-failed") and \
                    bg_start is not None:
                events.append({
                    "name": f"ladder background compile [{kind[11:]}]",
                    "cat": "warmup", "ph": "X",
                    "ts": us(bg_start),
                    "dur": max(0.0, (t_abs - bg_start) * 1e6),
                    "pid": PID, "tid": wtid,
                    "args": {k: v for k, v in lad.items() if k != "t"},
                })
                bg_start = None
                continue
            events.append({
                "name": f"ladder: {kind}"
                        + (f" rung={lad['rung']}" if lad.get("rung") else "")
                        + (f" -> {lad['target']}"
                           if kind == "swap" and lad.get("target") else ""),
                "cat": "warmup", "ph": "i", "s": "t",
                "ts": us(t_abs), "pid": PID, "tid": wtid,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write(path: str, timed_events, warmup_report: dict | None = None,
          warmup_t0: float | None = None) -> dict:
    doc = to_chrome_trace(timed_events, warmup_report, warmup_t0)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return doc


def validate_chrome_trace(doc) -> list[str]:
    """Structural validation against the Chrome trace-event JSON object
    model; returns a list of problems (empty = valid)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents missing or not a list"]
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as e:
        errs.append(f"not JSON-serializable: {e}")
    for i, ev in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errs.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str):
            errs.append(f"{where}: name missing or not a string")
        ph = ev.get("ph")
        if ph not in _ALLOWED_PH:
            errs.append(f"{where}: ph {ph!r} not in {sorted(_ALLOWED_PH)}")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errs.append(f"{where}: ts must be a non-negative number")
        for k in ("pid", "tid"):
            if not isinstance(ev.get(k), int):
                errs.append(f"{where}: {k} missing or not an int")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errs.append(f"{where}: X event needs non-negative dur")
        if ph in ("C", "M") and not isinstance(ev.get("args"), dict):
            errs.append(f"{where}: {ph} event needs an args object")
    return errs
