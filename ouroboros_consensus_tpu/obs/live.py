"""The live run plane: in-run heartbeat + stall watchdog.

Every obs layer before this one is post-hoc — the flight recorder,
warmup forensics and run ledger all explain a run AFTER it ended. A
live replay (the r06 proof point) is a black box WHILE it runs: a
400 s compile, a wedged staging thread and a hung AOT deserialize all
look identical to progress until the wall kills the child. The
reference serves its EKG/Prometheus surface live while validating
(cardano-node, SURVEY.md layers 4-5); this module is the equivalent
in-run surface for the batched pipeline:

  * `Heartbeat` — a daemon thread that atomically rewrites a JSON
    snapshot every ~2 s (`OCT_HEARTBEAT=<file>`): current phase from
    the recorder's last event, retired window index, headers retired,
    a rolling headers/s, ladder/bg-compile state from the warmup
    notes, and the age since the last observable progress. The bench
    parent and `scripts/tpu_watchdog.sh` read it to tell *compiling* /
    *staging* / *running* / *stalled* / *dead* apart in real time.
  * `StallWatchdog` — a monotonic no-progress budget
    (`OCT_STALL_BUDGET_S`). On trip it dumps ALL thread stacks
    (`sys._current_frames` + a raw `faulthandler` twin) plus a
    warmup/metrics snapshot into a forensics file next to the warmup
    report, increments `oct_stalls_total{phase=}` and emits a
    first-class `StallEvent` on the recorder. Escalation stays the
    parent's job — the dump is evidence, not a kill.
  * `maybe_arm()` — the one-call mount used by `db_analyser.revalidate`
    (and through it bench's device child and `profile_replay.py`):
    heartbeat + watchdog + the `obs/server.py` HTTP endpoint
    (`OCT_METRICS_PORT`), ref-counted like `obs.install`.

Everything is host-side and per-beat (one dict build + one atomic
rename every ~2 s): the instrumentation-purity ratchet and the
host-ceiling 2% bound both hold with the full plane armed.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque

_HB_ENV = "OCT_HEARTBEAT"
_STALL_ENV = "OCT_STALL_BUDGET_S"
_DUMP_ENV = "OCT_STALL_DUMP"

# heartbeat cadence; the dead-vs-alive staleness threshold derives from
# it (classify() below), so parent and child agree on one constant
BEAT_INTERVAL_S = 2.0
# rolling-rate window: long enough to smooth per-window jitter, short
# enough that a rate collapse shows within a few beats
RATE_WINDOW_S = 30.0


def heartbeat_path() -> str | None:
    return os.environ.get(_HB_ENV) or None


def stall_budget_s() -> float | None:
    v = os.environ.get(_STALL_ENV)
    if not v:
        return None
    try:
        budget = float(v)
    except ValueError:
        return None
    return budget if budget > 0 else None


def stall_dump_path() -> str:
    """Where the stall forensics land: `OCT_STALL_DUMP` when set, else
    next to the warmup report (the crash-forensics neighborhood), else
    next to the heartbeat file, else the cwd."""
    explicit = os.environ.get(_DUMP_ENV)
    if explicit:
        return explicit
    for anchor in (os.environ.get("OCT_WARMUP_REPORT"), heartbeat_path()):
        if anchor:
            return os.path.join(
                os.path.dirname(os.path.abspath(anchor)), "stall_dump.json"
            )
    return "stall_dump.json"


# ---------------------------------------------------------------------------
# phase classification
# ---------------------------------------------------------------------------


def phase_of(ev) -> str:
    """Map a recorder event to the live phase vocabulary. Import-free
    of jax; events are plain dataclasses."""
    from ..utils import trace as T

    if isinstance(ev, T.EncloseEvent):
        return ev.label  # stage | dispatch | materialize | epilogue | stream
    if isinstance(ev, T.WindowStaged):
        return "dispatch"
    if isinstance(ev, (T.WindowSpan, T.ShardSpan)):
        return "retired"
    if isinstance(ev, T.TransferEvent):
        return ev.phase
    if isinstance(ev, T.LadderEvent):
        return "ladder"
    if isinstance(ev, T.AggRedispatch):
        return "agg-redispatch"
    if isinstance(ev, T.RecoveryEvent):
        return "recovery"
    if isinstance(ev, T.CheckpointEvent):
        return "retired"  # a checkpoint write trails a retired window
    if isinstance(ev, T.StallEvent):
        return "stalled"
    return type(ev).__name__


def _warmup_live(report: dict) -> dict:
    """The compile-side slice of the heartbeat: is a first-execute or a
    background ladder compile in flight right now?"""
    notes = report.get("notes") or []
    ladder = report.get("ladder") or []
    bg = None
    for row in ladder:
        kind = row.get("kind", "")
        if kind == "bg-compile-started":
            bg = "running"
        elif kind in ("bg-compile-done", "bg-compile-failed", "swap"):
            bg = kind
    last_note = notes[-1] if notes else None
    # a stage's "<label> first execute starting" note lands BEFORE its
    # compile-inclusive first execute and the completion note_stage
    # after — so "starting" with no matching stage row means a compile
    # is in flight RIGHT NOW (the ~410 s wall, live)
    compiling_now = False
    if last_note and last_note.endswith("first execute starting"):
        label = last_note.split("] ", 1)[-1]
        label = label[: -len(" first execute starting")]
        compiling_now = label not in (report.get("stages") or {})
    return {
        "n_stages": report.get("n_stages", 0),
        "compile_total_s": report.get("compile_total_s", 0.0),
        "last_note": last_note,
        "ladder": ladder[-1].get("kind") if ladder else None,
        "bg_compile": bg,
        "compiling_now": compiling_now,
    }


def live_snapshot(rec=None, clock=time.monotonic) -> dict:
    """One heartbeat document (also what `/healthz` serves). Cheap by
    construction: counter reads, the recorder's last event, and the
    warmup report dict — no device interaction ever."""
    from .warmup import WARMUP

    from .. import obs

    rec = rec if rec is not None else obs.recorder()
    now = clock()
    last = rec.last_event()
    report = WARMUP.report()
    wu = _warmup_live(report)
    if last is not None:
        phase = phase_of(last[1])
        age = max(0.0, now - last[0])
    else:
        # nothing dispatched yet: the run is warming up (or idle)
        phase = "warmup" if (wu["last_note"] or wu["n_stages"]) else "idle"
        age = report.get("elapsed_s", 0.0)
    doc = {
        "v": 1,
        "pid": os.getpid(),
        "ts_unix": time.time(),
        "t_mono": now,
        "phase": phase,
        "age_s": round(age, 3),
        "headers": rec.headers_retired(),
        "window_index": rec.last_window_index(),
        "stalls": _stall_count(rec),
        "warmup": wu,
    }
    return doc


def _stall_count(rec) -> int:
    try:
        # under the registry lock: the watchdog's trip counter rides
        # label first-touches from other threads, and samples() iterates
        # the child dict that first-touch inserts into
        with rec.registry._lock:
            fam = rec.registry._families.get("oct_stalls_total")
            if fam is None:
                return 0
            return int(sum(child.value for _l, child in fam.samples()))
    except Exception:  # noqa: BLE001 — the heartbeat never raises
        return 0


def classify(doc: dict | None, now_unix: float | None = None,
             interval_s: float = BEAT_INTERVAL_S) -> str:
    """Reader-side classification of a heartbeat document — the
    vocabulary the bench parent banks and tpu_watchdog.sh logs:

        no-heartbeat   no document (never armed, or never beat)
        dead           the file stopped being rewritten (> 5 beats old)
        stalled        the child's watchdog is tripped RIGHT NOW
                       (`stalled_now`; the cumulative `stalls` count is
                       informational — a recovered run classifies by
                       its live phase again)
        compiling      a stage first-execute / bg ladder compile is the
                       freshest activity (warmup moving, no spans yet,
                       or the last note names an in-flight compile)
        staging        host-side window prep (stage/stream/prechecks)
        running        device windows dispatching/retiring
        idle           armed but nothing has happened yet
    """
    if not isinstance(doc, dict) or "ts_unix" not in doc:
        return "no-heartbeat"
    now_unix = time.time() if now_unix is None else now_unix
    if now_unix - float(doc["ts_unix"]) > 5 * interval_s:
        return "dead"
    if doc.get("stalled_now"):
        return "stalled"
    phase = doc.get("phase", "idle")
    wu = doc.get("warmup") or {}
    if (
        phase in ("warmup",)
        # a foreground first-execute is compiling RIGHT NOW, whatever
        # phase the dispatch loop froze in when it hit the cold stage
        or wu.get("compiling_now")
        or (wu.get("bg_compile") == "running" and phase in ("idle",))
    ):
        return "compiling"
    if phase in ("stage", "stream", "prechecks"):
        return "staging"
    if phase in ("dispatch", "materialize", "epilogue", "retired",
                 "ladder", "agg-redispatch", "recovery"):
        return "running"
    if phase == "stalled":
        return "stalled"
    return "idle" if phase == "idle" else "running"


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


class StallWatchdog:
    """Monotonic no-progress budget over the recorder + warmup state.

    `check()` is drive-able with an injected clock (the tier-1 stubbed
    clock test); production calls arrive from the Heartbeat thread each
    beat. One dump per stall episode: after a trip the watchdog stays
    quiet until progress resumes, so a 30-minute hang produces one
    forensics file, not 900."""

    def __init__(self, budget_s: float, rec=None,
                 dump_path: str | None = None, clock=time.monotonic):
        from .. import obs

        self.budget_s = float(budget_s)
        self.rec = rec if rec is not None else obs.recorder()
        self.dump_path = dump_path or stall_dump_path()
        self.clock = clock
        self.tripped = False
        self.dumps = 0
        now = self.clock()
        self._last_progress_t = now
        self._fingerprint = self._current_fingerprint()

    def _current_fingerprint(self) -> tuple:
        from .warmup import WARMUP

        with WARMUP._lock:
            wu = (len(WARMUP.stages), len(WARMUP.notes),
                  len(WARMUP.ladder), len(WARMUP.aot_events),
                  # recovery-ladder transitions ARE progress: a window
                  # being walked down the degradation ladder must not
                  # read as a wedge (and a stall episode re-arms the
                  # moment recovery starts moving)
                  len(WARMUP.recovery))
        return self.rec.progress_fingerprint() + wu

    def check(self, now: float | None = None) -> dict | None:
        """Advance the watchdog; returns the dump document on a trip,
        None otherwise."""
        now = self.clock() if now is None else now
        fp = self._current_fingerprint()
        if fp != self._fingerprint:
            self._fingerprint = fp
            self._last_progress_t = now
            self.tripped = False
            return None
        age = now - self._last_progress_t
        if self.tripped or age <= self.budget_s:
            return None
        self.tripped = True
        return self._dump(age)

    # -- forensics ----------------------------------------------------------

    def _thread_stacks(self) -> dict:
        """{thread name: [frame strings]} for every live thread — the
        wedged stage is IN here by function name (dispatch_batch,
        materialize_verdicts, a blocking device read...)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        for ident, frame in sys._current_frames().items():
            label = f"{names.get(ident, 'thread')}-{ident}"
            out[label] = [
                ln.rstrip("\n")
                for ln in traceback.format_stack(frame)
            ]
        return out

    def _dump(self, age: float) -> dict:
        from .warmup import WARMUP
        from ..utils.trace import StallEvent

        last = self.rec.last_event()
        phase = phase_of(last[1]) if last is not None else "warmup"
        doc = {
            "v": 1,
            "pid": os.getpid(),
            "ts_unix": time.time(),
            "phase": phase,
            "age_s": round(age, 3),
            "budget_s": self.budget_s,
            "threads": self._thread_stacks(),
            "heartbeat": live_snapshot(self.rec, clock=self.clock),
            "warmup_report": WARMUP.report(),
            "metrics_summary": self.rec.latency_summary(),
        }
        path = self.dump_path
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)
            # the raw faulthandler twin (C-level, signal-safe format):
            # belt-and-braces in case the interpreter state is too
            # wedged for the structured walk above to be trusted
            import faulthandler

            with open(path + ".txt", "w", encoding="utf-8") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
            doc["_path"] = path
        except OSError:
            doc["_path"] = None  # forensics are best-effort
        self.dumps += 1
        # countable + first-class on the recorder: a later reader of
        # the event stream / metrics snapshot sees the trip without the
        # dump file
        self.rec(StallEvent(
            phase=phase, age_s=age, budget_s=self.budget_s,
            dump_path=doc.get("_path"),
        ))
        # the StallEvent itself just advanced the recorder's event
        # stream — refresh the fingerprint so the watchdog's own
        # evidence never reads as progress (it would re-arm and
        # re-dump the SAME wedge every budget_s, misattributed to
        # phase="stalled")
        self._fingerprint = self._current_fingerprint()
        return doc


# ---------------------------------------------------------------------------
# heartbeat thread
# ---------------------------------------------------------------------------


class Heartbeat:
    """Daemon thread: every `interval_s`, compose `live_snapshot()`,
    fold in the rolling headers/s, atomically rewrite `path` (tmp +
    rename — a SIGKILL mid-rewrite leaves the previous complete beat
    readable, mirroring the warmup recorder's contract), and drive the
    watchdog. `path=None` runs beats without a file (watchdog-only)."""

    def __init__(self, path: str | None, rec=None,
                 interval_s: float = BEAT_INTERVAL_S,
                 watchdog: StallWatchdog | None = None,
                 clock=time.monotonic):
        from .. import obs

        self.path = path
        self.rec = rec if rec is not None else obs.recorder()
        self.interval_s = interval_s
        self.watchdog = watchdog
        self.clock = clock
        self._beat_lock = threading.Lock()
        self.seq = 0  # guarded-by: _beat_lock
        self._samples: deque[tuple[float, int]] = deque()  # guarded-by: _beat_lock
        self.beat_errors = 0  # guarded-by: _beat_lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- one beat (unit-testable without the thread) ------------------------

    def beat(self) -> dict:
        # one beat at a time: stop()'s final beat can race a
        # join-timed-out _run still mid-beat — serializing keeps
        # seq/_samples coherent and the tmp+rename below un-torn
        with self._beat_lock:
            now = self.clock()
            doc = live_snapshot(self.rec, clock=self.clock)
            self._samples.append((now, doc["headers"]))
            # age out samples older than the window but ALWAYS keep a
            # two-sample anchor: a silent stretch then reads 0.0
            # headers/s (informative for a stall), never None
            while (len(self._samples) > 2
                   and now - self._samples[1][0] > RATE_WINDOW_S):
                self._samples.popleft()
            t0, h0 = self._samples[0]
            dt = now - t0
            doc["headers_per_s"] = (
                round((doc["headers"] - h0) / dt, 1) if dt > 0.5 else None
            )
            doc["seq"] = self.seq
            doc["interval_s"] = self.interval_s
            if self.beat_errors:
                doc["beat_errors"] = self.beat_errors
            self.seq += 1
            if self.watchdog is not None:
                self.watchdog.check(now)
                doc["stalls"] = _stall_count(self.rec)
                # CURRENT state, not the lifetime count: tripped resets
                # the moment progress resumes, so a run that stalled
                # once at window 10 and recovered classifies by its live
                # phase again instead of reading "stalled" forever
                doc["stalled_now"] = self.watchdog.tripped
            if self.path:
                try:
                    tmp = self.path + ".tmp"
                    with open(tmp, "w", encoding="utf-8") as f:
                        json.dump(doc, f)
                    os.replace(tmp, self.path)
                except OSError:
                    pass  # the heartbeat never breaks the run it describes
            return doc

    # -- thread lifecycle ---------------------------------------------------

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        try:
            self.beat()  # an armed plane is visible IMMEDIATELY
        except Exception as exc:  # noqa: BLE001 — diagnostics must
            self._note_beat_error(exc)  # never break the run they
            # describe; the thread below keeps trying every interval
        self._thread = threading.Thread(
            target=self._run, name="oct-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except Exception as exc:  # noqa: BLE001 — keep beating,
                self._note_beat_error(exc)  # but never silently

    def _note_beat_error(self, exc: BaseException) -> None:
        """A failing beat must stay visible without being able to kill
        the plane: count it (the next good beat publishes the count as
        `beat_errors`) and note the FIRST one into the warmup report —
        bounded, so a wedged snapshot source cannot spam a note per
        interval."""
        with self._beat_lock:
            self.beat_errors += 1
            first = self.beat_errors == 1
        if not first:
            return
        try:
            from .warmup import WARMUP

            WARMUP.note(
                f"heartbeat beat failed: {type(exc).__name__}: {exc}"
            )
        except Exception:  # noqa: BLE001 — the seam itself failing
            pass           # must not take the heartbeat thread down

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5)
            self._thread = None
        # final beat so the file's last word reflects the finished run
        try:
            self.beat()
        except Exception as exc:  # noqa: BLE001
            self._note_beat_error(exc)


def read_heartbeat(path: str) -> dict | None:
    """Read a heartbeat document; None when absent/torn — callers treat
    that as 'no heartbeat' (the atomic rewrite makes torn reads rare:
    only a never-completed FIRST write can produce one)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


# ---------------------------------------------------------------------------
# the one-call mount (db_analyser.revalidate, profile_replay, bench child)
# ---------------------------------------------------------------------------


class LivePlane:
    """One armed live plane: heartbeat (+watchdog) thread and the HTTP
    endpoint, with the recorder installed underneath so phase events
    actually flow. `disarm()` undoes exactly one `arm`."""

    def __init__(self, heartbeat: Heartbeat, server=None):
        self.heartbeat = heartbeat
        self.server = server

    def disarm(self) -> None:
        _disarm(self)


_LOCK = threading.Lock()
_DEPTH = 0
_PLANE: LivePlane | None = None


def maybe_arm(rec=None) -> LivePlane | None:
    """Arm the live plane iff any of its env levers is set
    (OCT_HEARTBEAT / OCT_STALL_BUDGET_S / OCT_METRICS_PORT). Ref-counted
    like obs.install: nested replays share one plane; the outermost
    disarm stops the thread and the server."""
    from . import server as obs_server

    hb_path = heartbeat_path()
    budget = stall_budget_s()
    port = obs_server.metrics_port()
    if hb_path is None and budget is None and port is None:
        return None
    global _DEPTH, _PLANE
    with _LOCK:
        _DEPTH += 1
        if _PLANE is not None:
            return _PLANE
        from .. import obs

        # install() is re-entrant and ALWAYS paired by _disarm's
        # uninstall — phase events flow even when OCT_TRACE is unset.
        # Arming is exception-SAFE end to end: a failure ANYWHERE past
        # the depth bump (install itself included) must unwind
        # everything it did — a leaked ref-count would pin the recorder
        # (and every later-armed plane) forever, and a bound-but-
        # unowned socket is an orphan listener on OCT_METRICS_PORT no
        # later disarm can ever reach.
        installed = None
        hb = None
        srv = None
        try:
            installed = obs.install()
            rec = rec if rec is not None else installed
            wd = (StallWatchdog(budget, rec=rec)
                  if budget is not None else None)
            hb = Heartbeat(hb_path, rec=rec, watchdog=wd).start()
            if port is not None:
                srv = obs_server.start_in_thread(
                    port=port, registry=rec.registry,
                    live_doc=lambda: live_snapshot(rec),
                )
            _PLANE = LivePlane(hb, srv)
        except BaseException:
            if srv is not None:
                srv.close()
            if hb is not None:
                hb.stop()
            if installed is not None:
                obs.uninstall()
            _DEPTH -= 1
            raise
        return _PLANE


def _disarm(plane: LivePlane) -> None:
    global _DEPTH, _PLANE
    with _LOCK:
        if _PLANE is not plane or _DEPTH == 0:
            return
        _DEPTH -= 1
        if _DEPTH > 0:
            return
        _PLANE = None
    plane.heartbeat.stop()
    if plane.server is not None:
        plane.server.close()
    from .. import obs

    obs.uninstall()


def reset_for_tests() -> None:
    """Drop any armed plane (test isolation)."""
    global _DEPTH, _PLANE
    with _LOCK:
        plane, _PLANE, _DEPTH = _PLANE, None, 0
    if plane is not None:
        plane.heartbeat.stop()
        if plane.server is not None:
            plane.server.close()
