"""Compile/warmup flight recorder: which stage ate the wall?

BENCH r02-r05 all died inside warmup — probe timeouts, ~410 s compile
walls, axon-format AOT cache rejections — and banked nothing but a
driver-side rc=124. This module is the black box that survives the
crash: every first-execute of a stage jit (ops/pk/kernels._stage_call,
the XLA-twin jits in protocol/batch), every pk-AOT load outcome
(ops/pk/aot.load: loaded / failed / format-rejected / marker-skipped)
and the bench child's persistent-cache startup probe record themselves
here, and — when `OCT_WARMUP_REPORT` names a file — every note is
immediately flushed as atomic JSON. A child killed at the wall mid-
compile leaves a readable per-stage diagnosis on disk; bench.py folds
it into the round JSON as the `warmup_report` block whether or not a
device number was ever banked.

Recording is always-on (a dict insert + a rare atomic file write per
FIRST execute — nothing per warm call), so the forensics need no env
lever to have been enabled before the crash."""

from __future__ import annotations

import json
import os
import threading
import time

_REPORT_ENV = "OCT_WARMUP_REPORT"


class WarmupRecorder:
    """Process-wide warmup/compile forensics accumulator."""

    def __init__(self):
        self._lock = threading.Lock()
        # separate from _lock (report() takes _lock inside a flush):
        # serializes the tmp-write + rename so two threads' first
        # executes (main dispatch + the materialize worker's aggregate
        # re-dispatch) can never interleave on the shared tmp path and
        # publish a truncated report — the one file a crash must leave
        # readable
        self._flush_lock = threading.Lock()
        self.t0 = time.monotonic()
        # stage -> {"wall_s", "via", "t"} — FIRST execute only (the
        # compile happens synchronously inside that call)
        self.stages: dict[str, dict] = {}  # guarded-by: _lock
        # aot outcome counts + the per-stage detail rows
        self.aot: dict[str, int] = {}  # guarded-by: _lock
        self.aot_events: list[dict] = []  # guarded-by: _lock
        # pre-flight refusals (analysis/costmodel.preflight): dispatches
        # whose PREDICTED cold-compile wall did not fit the remaining
        # bench budget — the decision is forensics too
        self.refusals: list[dict] = []  # guarded-by: _lock
        # warm-while-serving compile ladder (protocol/batch.WarmLadder):
        # engagement, background-compile start/land and every rung swap,
        # each with the octwall feature hash of the program involved
        self.ladder: list[dict] = []  # guarded-by: _lock
        self.cache_probe: dict | None = None  # guarded-by: _lock
        self.notes: list[str] = []  # guarded-by: _lock
        # recovery-supervisor episodes (obs/recovery.py): every ladder
        # transition for a failing window — banked with the rest of the
        # forensics so the round JSON and ledger carry the recovery
        # story (perf_report classifies recovered rounds from this)
        self.recovery: list[dict] = []  # guarded-by: _lock
        # durable-store repair plane (storage/repair.py): every
        # on-disk repair (or dry-run would-repair) the open-with-repair
        # scan took — truncated chunk tails, rebuilt indices, dropped
        # chunks, dirty-open escalations — banked with the forensics so
        # perf_report can classify a round `repaired@<action>`
        self.repairs: list[dict] = []  # guarded-by: _lock

    # -- recording ----------------------------------------------------------

    def note_stage(self, stage: str, wall_s: float, via: str = "jit",
                   feature_hash: str | None = None) -> bool:
        """Record a stage's FIRST execute wall (compile-inclusive).
        Returns True when this call was the first for `stage`.
        `feature_hash` is the costmodel jaxpr feature digest of the
        dispatched program, so scripts/fit_costmodel.py can join this
        measured wall EXACTLY to the static features it belongs to."""
        with self._lock:
            if stage in self.stages:
                return False
            row = {
                "wall_s": round(wall_s, 3),
                "via": via,
                "t": round(time.monotonic() - self.t0, 3),
            }
            if feature_hash:
                row["feature_hash"] = feature_hash
            self.stages[stage] = row
        self._flush()
        return True

    def note_refusal(self, stage: str, predicted_s: float,
                     remaining_s: float, action: str,
                     detail: str = "") -> None:
        """One pre-flight refusal: a cold first-execute whose predicted
        compile wall exceeded the remaining wall budget (the caller
        takes `action` — e.g. the per-stage split fallback — instead)."""
        with self._lock:
            self.refusals.append({
                "stage": stage,
                "predicted_s": round(predicted_s, 1),
                "remaining_s": round(remaining_s, 1),
                "action": action,
                "detail": detail[:200],
                "t": round(time.monotonic() - self.t0, 3),
            })
        self._flush()

    def note_ladder(self, kind: str, **fields) -> None:
        """One warm-ladder event, first-class in the report: kind is
        engaged | bg-compile-started | bg-compile-done | bg-compile-failed
        | swap. Fields carry the rung/target lane counts, the production
        stage label and the octwall feature_hash of the program the
        event is about, so a ladder trajectory joins the cost pins the
        same way stage first-executes do."""
        row = {"kind": kind,
               "t": round(time.monotonic() - self.t0, 3)}
        for k, v in fields.items():
            if v is not None:
                row[k] = round(v, 3) if isinstance(v, float) else v
        with self._lock:
            self.ladder.append(row)
        self._flush()

    def note_aot(self, stage: str, outcome: str, wall_s: float = 0.0,
                 detail: str = "") -> None:
        """One pk-AOT load outcome: loaded | missing | wrong_build |
        failed | rejected | marker_skip | run_failed | saved."""
        with self._lock:
            self.aot[outcome] = self.aot.get(outcome, 0) + 1
            self.aot_events.append({
                "stage": stage,
                "outcome": outcome,
                "wall_s": round(wall_s, 3),
                "detail": detail[:200],
                "t": round(time.monotonic() - self.t0, 3),
            })
        self._flush()

    def note_cache_probe(self, outcome: str, wall_s: float = 0.0,
                         detail: str = "") -> None:
        """The bench child's startup probe-deserialize of one persistent
        jax-cache entry: ok | stale | inconclusive | empty."""
        with self._lock:
            self.cache_probe = {
                "outcome": outcome,
                "wall_s": round(wall_s, 3),
                "detail": detail[:200],
            }
        self._flush()

    def note_recovery(self, action: str, window: int, attempt: int,
                      fault: str, detail: str = "",
                      ok: bool | None = None) -> None:
        """One recovery-ladder transition (obs/recovery.py): action is
        retry | restage | stage-split | xla-twin | host-reference |
        chunk-reread | recovered | exhausted."""
        row = {
            "action": action,
            "window": window,
            "attempt": attempt,
            "fault": fault,
            "detail": detail[:200],
            "t": round(time.monotonic() - self.t0, 3),
        }
        if ok is not None:
            row["ok"] = ok
        with self._lock:
            self.recovery.append(row)
        self._flush()

    def note_repair(self, action: str, chunk: int = -1, kept: int = 0,
                    dropped: int = 0, bytes_quarantined: int = 0,
                    applied: bool = True, detail: str = "") -> None:
        """One durable-store repair action (storage/repair.py): action
        is truncate-chunk | rebuild-index | drop-chunk |
        sweep-orphan-index | dirty-open-escalated; `applied=False`
        marks a dry-run scan that only computed the action."""
        with self._lock:
            self.repairs.append({
                "action": action,
                "chunk": chunk,
                "kept": kept,
                "dropped": dropped,
                "bytes_quarantined": bytes_quarantined,
                "applied": applied,
                "detail": detail[:200],
                "t": round(time.monotonic() - self.t0, 3),
            })
        self._flush()

    def note(self, msg: str) -> None:
        """Free-form forensic breadcrumb (e.g. 'warmup replay started')."""
        with self._lock:
            self.notes.append(
                f"[{time.monotonic() - self.t0:.1f}s] {msg[:200]}"
            )
        self._flush()

    # -- reporting ----------------------------------------------------------

    def report(self) -> dict:
        """The `warmup_report` block: per-stage compile wall + cache
        hit/miss/reject attribution."""
        with self._lock:
            stages = {k: dict(v) for k, v in self.stages.items()}
            compile_total = sum(v["wall_s"] for v in stages.values())
            return {
                "elapsed_s": round(time.monotonic() - self.t0, 1),
                "compile_total_s": round(compile_total, 1),
                "n_stages": len(stages),
                "stages": stages,
                "aot": dict(self.aot),
                "aot_events": list(self.aot_events),
                "refusals": [dict(r) for r in self.refusals],
                "ladder": [dict(r) for r in self.ladder],
                "cache_probe": self.cache_probe,
                "recovery": [dict(r) for r in self.recovery],
                "repairs": [dict(r) for r in self.repairs],
                "notes": list(self.notes),
            }

    def _flush(self) -> None:
        """Atomic write of the report to $OCT_WARMUP_REPORT (when set):
        a kill mid-warmup leaves the last complete note on disk, never a
        torn file. Notes are first-executes and load outcomes — dozens
        per run, so per-note writes cost nothing measurable."""
        path = os.environ.get(_REPORT_ENV)
        if not path:
            return
        try:
            with self._flush_lock:
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(self.report(), f)
                os.replace(tmp, path)
        except OSError:
            pass  # forensics are best-effort; never break the pipeline

    def reset(self) -> None:
        with self._lock:
            self.t0 = time.monotonic()
            self.stages.clear()
            self.aot.clear()
            self.aot_events.clear()
            self.refusals.clear()
            self.ladder.clear()
            self.cache_probe = None
            self.recovery.clear()
            self.repairs.clear()
            self.notes.clear()


WARMUP = WarmupRecorder()


def read_report(path: str) -> dict | None:
    """Read a (possibly mid-crash) warmup report; None when absent or
    unreadable — callers treat that as 'no forensics banked'."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
