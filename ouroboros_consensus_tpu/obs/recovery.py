"""Self-healing replay: crash-consistent checkpoints + the supervised
degradation ladder.

The reference's ChainDB is built around crash recovery — replay resumes
from the last on-disk ledger snapshot, never from genesis (SURVEY.md:
ImmutableDB + VolatileDB + LedgerDB) — while rounds r02-r05 each died
mid-replay and banked NOTHING, restarting from header zero every time.
This module is the batched pipeline's equivalent of that contract,
in two halves:

**Checkpoint/resume** — when ``OCT_CHECKPOINT=<file>`` is set,
`validate_chain`'s retire path persists a tiny progress record per
retired window (cumulative chain position, the full `PraosState` —
nonce carry + per-pool counter map — and an integrity digest) with the
same tmp+rename atomicity as the heartbeat: a SIGKILL mid-write leaves
the previous complete record. `db_analyser.revalidate(resume=...)`
reopens it, skips the retired prefix of the window stream and seeds
the fold from the host record — proven verdict-identical to an
uninterrupted replay by the differential suite (tests/test_recovery.py),
including resume across an epoch boundary and a mid-ladder-swap kill.
The record is keyed by a ``chain_tag`` (db path + params) so a resume
against a different chain silently starts fresh, and a COMPLETED
replay marks its record ``complete`` so the next invocation never
skips work that was already banked.

**RecoverySupervisor** — a window whose dispatch/materialize raises a
recoverable error (device runtime errors, the chaos taxonomy, I/O) is
not the end of the replay: the supervisor escalates through an explicit
ladder, each rung a full re-validation of JUST that window —

    retry            the same path again, after jittered backoff
                     (transient tunnel/device blips)
    stage-split      the per-lane/stage-split packed path (OCT_VRF_AGG
                     semantics forced off for the call — the
                     materialize_verdicts anomaly taxonomy path)
    xla-twin         the XLA twin of the pk pipeline (impl forced
                     "xla"; on CPU hosts this equals stage-split's
                     backend and still exercises the distinct flag)
    host-reference   the exact sequential reference fold (pure host,
                     cannot fail for device reasons) — the floor

— every transition a first-class `RecoveryEvent` through the batch
tracer (-> ``oct_recovery_total{action=}``), mirrored into the warmup
report (`WARMUP.note_recovery`) so it is banked in the round JSON and
the run ledger like every other forensic. Verdict-correct by
construction: each rung is a complete re-validation with identical
semantics (the differential suites pin all of them), so a recovered
replay's verdicts, error taxonomy and final nonce carry equal the
uninterrupted run's.

**ParentPolicy** — the bench parent's side of the same policy: it
tails the child's heartbeat classification and, when the child is
``stalled`` (its own watchdog tripped) or ``dead`` (heartbeat stopped)
past a grace window, SIGTERMs it (the child's faulthandler banks the
stacks), kills it, and relaunches with ``OCT_RESUME=1`` — the retry
resumes from the last retired window instead of burning the remaining
wall re-validating what was already banked.

Kill-switches: ``OCT_RECOVERY=0`` disables the supervisor (errors
propagate raw — the pre-PR-12 behavior); leaving ``OCT_CHECKPOINT``
unset disables checkpointing (the retire seam is one None check)."""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

_CKPT_ENV = "OCT_CHECKPOINT"
_RESUME_ENV = "OCT_RESUME"
_ENABLE_ENV = "OCT_RECOVERY"
_BACKOFF_ENV = "OCT_RECOVERY_BACKOFF_S"

SCHEMA_VERSION = 1

# the explicit escalation policy per backend — each rung re-validates
# the failing window completely, so any rung that returns IS the
# window's verdict (retry tries the SAME failed path again first)
LADDERS = {
    "device": ("retry", "stage-split", "xla-twin", "host-reference"),
    "sharded": ("retry", "xla-twin", "host-reference"),
    "native": ("retry", "host-reference"),
}


def checkpoint_path() -> str | None:
    return os.environ.get(_CKPT_ENV) or None


def resume_requested() -> bool:
    return os.environ.get(_RESUME_ENV, "0") not in ("0", "")


def enabled() -> bool:
    """OCT_RECOVERY (default on): the supervisor ladder. =0 restores
    raise-through (read per call so tests can A/B both behaviors)."""
    return os.environ.get(_ENABLE_ENV, "1") != "0"


# ---------------------------------------------------------------------------
# PraosState <-> JSON (the host progress record)
# ---------------------------------------------------------------------------


def _hx(b: bytes | None) -> str | None:
    return b.hex() if b is not None else None


def _unhx(s: str | None) -> bytes | None:
    return bytes.fromhex(s) if s is not None else None


def encode_state(st) -> dict:
    """PraosState -> a JSON-safe dict. The checkpoint is the WHOLE
    sequential fold state: nonce carry, per-pool counter map, last
    slot — everything `validate_chain` threads between windows.
    (Device-resident carry is NOT here by design: resume re-seeds the
    device nonce scan from this host record — COVERAGE.md §5.16.)"""
    return {
        "last_slot": st.last_slot,
        "ocert_counters": {k.hex(): int(v)
                          for k, v in sorted(st.ocert_counters.items())},
        "evolving_nonce": _hx(st.evolving_nonce),
        "candidate_nonce": _hx(st.candidate_nonce),
        "epoch_nonce": _hx(st.epoch_nonce),
        "lab_nonce": _hx(st.lab_nonce),
        "last_epoch_block_nonce": _hx(st.last_epoch_block_nonce),
    }


def decode_state(d: dict):
    from ..protocol.praos import PraosState

    return PraosState(
        last_slot=d.get("last_slot"),
        ocert_counters={bytes.fromhex(k): int(v)
                        for k, v in (d.get("ocert_counters") or {}).items()},
        evolving_nonce=_unhx(d.get("evolving_nonce")),
        candidate_nonce=_unhx(d.get("candidate_nonce")),
        epoch_nonce=_unhx(d.get("epoch_nonce")),
        lab_nonce=_unhx(d.get("lab_nonce")),
        last_epoch_block_nonce=_unhx(d.get("last_epoch_block_nonce")),
    )


def _digest(chain_tag: str, headers: int, windows: int, state: dict) -> str:
    """Integrity digest over everything resume trusts: a torn or
    hand-edited record fails closed (fresh start), never a silently
    wrong re-seed."""
    blob = json.dumps(
        {"chain_tag": chain_tag, "headers": headers, "windows": windows,
         "state": state},
        sort_keys=True, separators=(",", ":"),
    ).encode()
    return hashlib.blake2s(blob, digest_size=16).hexdigest()


def chain_tag(db_path: str, params) -> str:
    """Identity of the replay a checkpoint belongs to: the chain on
    disk plus the protocol parameters that shape its verdicts. A
    record tagged for another chain is ignored on resume (bench warms
    on the 100k chain, measures the 1M one — positions do not
    transfer)."""
    blob = f"{os.path.abspath(db_path)}|{params!r}".encode()
    return hashlib.blake2s(blob, digest_size=8).hexdigest()


# ---------------------------------------------------------------------------
# ProgressWriter: the per-retired-window atomic record
# ---------------------------------------------------------------------------


def _emit(ev) -> None:
    from ..protocol import batch as pbatch

    if pbatch.BATCH_TRACER is not None:
        pbatch.BATCH_TRACER(ev)


class ProgressWriter:
    """Accumulates the global chain position across `validate_chain`
    invocations (revalidate calls it once per epoch segment) and
    atomically rewrites the progress record per retired window —
    tmp+rename, the same crash contract as the heartbeat and warmup
    report. One tiny JSON write per window (~hundreds per replay), so
    the hot path is untaxed."""

    def __init__(self, path: str, chain_tag_: str,
                 headers: int = 0, windows: int = 0):
        self.path = path
        self.chain_tag = chain_tag_
        self._lock = threading.Lock()
        self.headers = headers  # guarded-by: _lock
        self.windows = windows  # guarded-by: _lock

    def note(self, state, n_new: int) -> None:
        from ..utils.trace import CheckpointEvent

        with self._lock:
            self.headers += int(n_new)
            self.windows += 1
            self._write(state, complete=False, error=None)
        _emit(CheckpointEvent("write", self.headers, self.windows))

    def finalize(self, state, error=None) -> None:
        """The replay COMPLETED (cleanly or at a validation error):
        mark the record so a later resume never skips a fresh run's
        work based on a finished one's position."""
        from ..utils.trace import CheckpointEvent

        with self._lock:
            self._write(state, complete=True,
                        error=None if error is None else repr(error)[:200])
        _emit(CheckpointEvent("complete", self.headers, self.windows))

    def _write(self, state, complete: bool, error) -> None:
        enc = encode_state(state)
        doc = {
            "schema": SCHEMA_VERSION,
            "kind": "oct-checkpoint",
            "chain_tag": self.chain_tag,
            "headers": self.headers,
            "windows": self.windows,
            "state": enc,
            "digest": _digest(self.chain_tag, self.headers, self.windows,
                              enc),
            "complete": complete,
            "error": error,
            "pid": os.getpid(),
            "ts_unix": time.time(),
        }
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # checkpoints are best-effort; never break the replay


_WRITER: ProgressWriter | None = None


def arm_writer(chain_tag_: str, resumed_headers: int = 0,
               resumed_windows: int = 0) -> ProgressWriter | None:
    """Mount the process checkpoint writer iff OCT_CHECKPOINT is set
    (called by db_analyser.revalidate; the batch loop's seam is
    `note_window`). Resuming passes the record's position so the
    cumulative count stays genesis-anchored."""
    global _WRITER
    path = checkpoint_path()
    if path is None:
        _WRITER = None
        return None
    _WRITER = ProgressWriter(path, chain_tag_, resumed_headers,
                             resumed_windows)
    return _WRITER


def disarm_writer() -> None:
    global _WRITER
    _WRITER = None


def note_window(state, n_new: int) -> None:
    """The retire seam (protocol/batch._device_loop and the non-device
    loop): one None check when checkpointing is disarmed."""
    w = _WRITER
    if w is not None:
        w.note(state, n_new)


def read_checkpoint(path: str | None = None) -> dict | None:
    """Read + integrity-check a progress record; None when absent,
    torn, schema-alien or digest-mismatched (fail closed: a fresh
    start is always correct, a wrong re-seed never is)."""
    path = path or checkpoint_path()
    if not path:
        return None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("kind") != "oct-checkpoint":
        return None
    if doc.get("schema") != SCHEMA_VERSION:
        return None
    try:
        want = _digest(doc["chain_tag"], doc["headers"], doc["windows"],
                       doc["state"])
    except (KeyError, TypeError):
        return None
    if doc.get("digest") != want:
        return None
    return doc


def note_resume(doc: dict) -> None:
    """A replay seeded itself from a progress record instead of
    genesis: bank the fact (warmup note + CheckpointEvent("resume")
    -> oct_checkpoint_events_total{kind="resume"})."""
    from ..utils.trace import CheckpointEvent
    from .warmup import WARMUP

    WARMUP.note(
        f"resumed from checkpoint: {doc['headers']} headers / "
        f"{doc['windows']} windows already retired"
    )
    _emit(CheckpointEvent("resume", int(doc["headers"]),
                          int(doc["windows"])))


def resume_record(chain_tag_: str, path: str | None = None) -> dict | None:
    """The record a replay of `chain_tag_` may resume from: valid,
    same chain, not complete, with at least one retired window."""
    doc = read_checkpoint(path)
    if doc is None or doc.get("complete"):
        return None
    if doc.get("chain_tag") != chain_tag_:
        return None
    if not doc.get("headers"):
        return None
    return doc


# ---------------------------------------------------------------------------
# RecoverySupervisor: the in-process degradation ladder
# ---------------------------------------------------------------------------


def recoverable(exc: BaseException) -> bool:
    """Failure classes the ladder may absorb. Deliberately narrow —
    the per-class policy lives in `node/exit.triage` (the
    consensusRethrowPolicy analog): only `RECOVER`-class faults
    (device/runtime errors, I/O, the chaos taxonomy) ride the ladder.
    `REFUSE` (DB locked, wrong chain magic), `REPAIR` (on-disk
    corruption — the open-with-repair scan owns it) and `PROPAGATE`
    (TypeError-class programming bugs) all surface raw: recovery must
    never mask a wrong program OR launder a refusal."""
    from ..node import exit as node_exit

    return node_exit.triage(exc) is node_exit.Disposition.RECOVER


def note_recovery_event(action: str, window: int, lanes: int,
                        attempt: int, exc: BaseException,
                        ok: bool | None = None) -> None:
    """One recovery-ladder transition, banked everywhere at once: the
    warmup report (-> round JSON + ledger) and the batch tracer
    (-> oct_recovery_total{action=}). Shared by the supervisor and the
    non-window recoveries (db_analyser's chunk reread)."""
    from ..utils.trace import RecoveryEvent
    from .warmup import WARMUP

    fault = type(exc).__name__
    detail = repr(exc)[:200]
    WARMUP.note_recovery(action=action, window=window, attempt=attempt,
                         fault=fault, detail=detail, ok=ok)
    _emit(RecoveryEvent(action=action, window=window, lanes=lanes,
                        attempt=attempt, fault=fault, detail=detail,
                        ok=ok))


class RecoverySupervisor:
    """Escalates a failing window through LADDERS[backend]; every
    transition is a RecoveryEvent + warmup note. Injectable sleep for
    stubbed-clock tests; backoff jitter rides the chaos RNG when
    armed (deterministic recovery timing under a seeded fault plan)."""

    def __init__(self, backoff_s: float | None = None, sleep=time.sleep):
        if backoff_s is None:
            try:
                backoff_s = float(os.environ.get(_BACKOFF_ENV, "0.05"))
            except ValueError:
                backoff_s = 0.05
        self.backoff_s = backoff_s
        self.sleep = sleep
        self.episodes = 0
        self.recovered = 0

    # -- event plumbing -----------------------------------------------------

    def _note(self, action: str, window: int, lanes: int, attempt: int,
              exc: BaseException, ok: bool | None = None) -> None:
        note_recovery_event(action, window, lanes, attempt, exc, ok)

    def _jitter(self) -> float:
        from ..testing import chaos

        return chaos.jitter()

    # -- the ladder ---------------------------------------------------------

    def _run_rung(self, rung: str, params, ticked, hvs, backend, mesh):
        from ..protocol import batch as pbatch

        if rung == "retry":
            return pbatch.validate_batch(params, ticked, hvs,
                                         backend=backend, mesh=mesh)
        if rung == "stage-split":
            with pbatch.recovery_overrides(agg=False):
                return pbatch.validate_batch(params, ticked, hvs,
                                             backend="device")
        if rung == "xla-twin":
            with pbatch.recovery_overrides(agg=False, impl="xla"):
                return pbatch.validate_batch(params, ticked, hvs,
                                             backend="device")
        if rung == "host-reference":
            return host_reference_fold(params, ticked, hvs)
        raise ValueError(f"unknown recovery rung {rung!r}")

    def recover_window(self, params, ticked, hvs, exc: BaseException,
                       backend: str = "device", mesh=None,
                       window: int = -1):
        """One failing window -> its BatchResult, or the original
        exception re-raised (supervisor disabled / unrecoverable fault
        class / every rung failed — 'exhausted' is itself forensics)."""
        if not enabled() or not recoverable(exc):
            raise exc
        lanes = len(hvs)
        self.episodes += 1
        last: BaseException = exc
        ladder = LADDERS.get(backend, LADDERS["device"])
        for attempt, rung in enumerate(ladder, start=1):
            self._note(rung, window, lanes, attempt, last)
            if rung == "retry" and self.backoff_s > 0:
                self.sleep(self.backoff_s * self._jitter())
            try:
                res = self._run_rung(rung, params, ticked, hvs, backend,
                                     mesh)
            except Exception as e:  # noqa: BLE001 — escalate the ladder
                last = e
                continue
            self.recovered += 1
            self._note("recovered", window, lanes, attempt, exc, ok=True)
            return res
        self._note("exhausted", window, lanes, len(ladder), last, ok=False)
        raise last


def host_reference_fold(params, ticked, hvs):
    """The ladder's floor: the exact sequential reference fold of one
    within-epoch window (tick + update per header, pure host crypto) —
    the same semantics every differential suite pins `validate_batch`
    against, with no device in the loop at all."""
    from ..protocol import praos
    from ..protocol.views import ViewColumns
    from ..protocol.batch import BatchResult

    views = hvs.views() if isinstance(hvs, ViewColumns) else hvs
    lview = ticked.ledger_view
    st = ticked.state
    t = ticked
    for i, hv in enumerate(views):
        if i:
            t = praos.tick(params, lview, hv.slot, st)
        try:
            new_st = praos.update(params, hv, hv.slot, t)
        except praos.PraosValidationError as e:
            return BatchResult(st, i, e, None)
        st = new_st
    return BatchResult(st, len(views), None, None)


_SUPERVISOR: RecoverySupervisor | None = None
_SUP_LOCK = threading.Lock()


def supervisor() -> RecoverySupervisor:
    global _SUPERVISOR
    with _SUP_LOCK:
        if _SUPERVISOR is None:
            _SUPERVISOR = RecoverySupervisor()
        return _SUPERVISOR


def reset_for_tests() -> None:
    global _SUPERVISOR, _WRITER
    with _SUP_LOCK:
        _SUPERVISOR = None
    _WRITER = None


# ---------------------------------------------------------------------------
# ParentPolicy: the bench parent's escalation
# ---------------------------------------------------------------------------


class ParentPolicy:
    """Decide when a live child has to die for its own good. Consumes
    `obs/live.classify()` states (the bench heartbeat tail's
    vocabulary): a child continuously `stalled` — its OWN watchdog has
    tripped and stayed tripped — for `stall_grace_s`, or `dead` (the
    heartbeat file stopped moving) for `dead_grace_s`, should be
    SIGTERM'd for forensics and relaunched with resume. Compiling /
    staging / running states always reset the fuse: the policy only
    ever fires on sustained no-progress evidence, never on a slow
    compile (the watchdog's own fingerprint already treats warmup
    notes as progress)."""

    def __init__(self, stall_grace_s: float = 60.0,
                 dead_grace_s: float = 30.0, clock=time.monotonic):
        self.stall_grace_s = stall_grace_s
        self.dead_grace_s = dead_grace_s
        self.clock = clock
        self._since: float | None = None
        self._state: str | None = None

    def observe(self, state: str, now: float | None = None) -> str:
        """-> "keep" | "kill". Call once per poll with the current
        classification."""
        now = self.clock() if now is None else now
        if state not in ("stalled", "dead"):
            self._since, self._state = None, None
            return "keep"
        if self._state != state:
            self._since, self._state = now, state
            return "keep"
        grace = (self.stall_grace_s if state == "stalled"
                 else self.dead_grace_s)
        if self._since is not None and now - self._since >= grace:
            return "kill"
        return "keep"


def terminate_for_forensics(proc, sigterm_wait_s: float = 10.0) -> None:
    """SIGTERM (the child's registered faulthandler banks all-thread
    stacks into the teed log), a bounded wait, then SIGKILL."""
    import subprocess

    try:
        proc.terminate()
        try:
            proc.wait(timeout=sigterm_wait_s)
            return
        except subprocess.TimeoutExpired:
            pass
        proc.kill()
        proc.wait()
    except OSError:
        pass
