"""Multi-chip SPMD fan-out of the Praos validation hot path.

The reference's hot loop is one OS thread validating one header at a time
(SURVEY.md §2.6 "Sequential hot loop"; ledgerDbPushMany fold,
LedgerDB/Update.hs:302-312). The TPU-native design replaces it with
batch × device data parallelism over a `jax.sharding.Mesh`:

  * every column of the staged `PraosBatch` has leading batch dim B and
    per-lane-independent compute, so the natural sharding is P('batch')
    on axis 0 across all chips (ICI all the way — no host hops);
  * the only cross-device communication is the verdict reduction: a
    `psum` of the per-shard valid counts and a `pmin` of the global
    index of the first failing lane (SURVEY.md §5.8: "collectives only
    appear ... as psum/all_gather over verification verdict bitmaps");
  * the per-header nonce values (eta) stay device-resident sharded and
    are gathered once per batch for the tiny sequential host fold.

This module is exercised on a virtual 8-device CPU mesh in tests and by
the driver's `dryrun_multichip`; on real hardware the same code spans a
TPU pod slice (mesh axis over all chips of the slice).
"""

from __future__ import annotations

from functools import partial

import inspect

import jax
import numpy as np
from jax import numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..protocol import batch as pbatch

# shard_map moved from jax.experimental to the jax top level (and its
# replication-check kwarg was renamed check_rep -> check_vma) across
# the jax versions this repo must run under; resolve both at import
try:
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map
_CHECK_KW = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(_shard_map).parameters
    else {"check_rep": False}
)

BATCH_AXIS = "batch"


def make_mesh(devices=None) -> Mesh:
    """1-D device mesh over the batch axis.

    The validation workload has a single parallel dimension (chain
    position), so the mesh is 1-D; on a multi-host pod slice the same
    axis simply spans all global devices (jax.devices() is global under
    multi-host jax.distributed initialization).
    """
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def pad_batch(batch: pbatch.PraosBatch, multiple: int):
    """Pad every column of `batch` to the next POWER-OF-TWO bucket that
    is divisible by `multiple`, returning (padded_batch, original_size).

    Bucketing (same rationale as pbatch.run_batch) keeps the
    jit-of-shard_map cache bounded: one compile per bucket shape, not
    one per epoch-segment length. Pad lanes replicate lane 0
    (guaranteed decodable inputs) — their verdicts are sliced off
    before the host epilogue, and the first-failure reduction masks
    them out by position.
    """
    b = batch.beta.shape[0]
    # floor of 32 lanes: small batches (tests, chain tails) all share
    # ONE compiled shard_map shape; production batches are far larger
    minimum = max(multiple, 32)
    target = pbatch.bucket_size(max(b, minimum), minimum=minimum)
    # power-of-two buckets are only divisible by power-of-two meshes;
    # round up for any other device count
    target += (-target) % multiple
    return pbatch.pad_batch_to(batch, target), b


@partial(jax.jit, static_argnames=("mesh",))
def _sharded_verify(mesh, n_real, *cols):
    """jit-of-shard_map: local fused verify + global verdict collectives.

    The valid-lane count forms on device: each shard bit-packs its ok
    lanes into u32 mask words (pbatch._pack_bits_u32, real positions
    only — `n_real` masks the bucket-pad lanes) and the `psum` of the
    per-shard mask popcounts yields n_ok, so ONE replicated scalar
    crosses the host boundary instead of the [B] ok column. (The mask
    words themselves stay shard-local — the same packed-verdict
    vocabulary as protocol/batch.verdict_reduce, reduced in place.)"""

    def local_step(n_real, *local_cols):
        v = pbatch.verify_praos_any(*local_cols)
        ok = v.ok_ocert_sig & v.ok_kes_sig & v.ok_vrf & (
            v.ok_leader | v.leader_ambiguous
        )
        # global chain positions of this shard's lanes
        shard = jax.lax.axis_index(BATCH_AXIS)
        n_local = ok.shape[0]
        pos = shard * n_local + jnp.arange(n_local, dtype=jnp.int32)
        big = jnp.iinfo(jnp.int32).max
        local_first_bad = jnp.min(jnp.where(ok, big, pos))
        first_bad = jax.lax.pmin(local_first_bad, BATCH_AXIS)
        words = pbatch._pack_bits_u32(ok & (pos < n_real))
        n_ok = jax.lax.psum(
            jnp.sum(jax.lax.population_count(words)).astype(jnp.int32),
            BATCH_AXIS,
        )
        return v, first_bad, n_ok

    spec = P(BATCH_AXIS)
    out = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(),) + tuple(spec for _ in cols),
        out_specs=(
            pbatch.Verdicts(*(spec,) * 7),
            P(),  # first_bad: replicated scalar
            P(),  # n_ok: psum over packed-mask popcounts, replicated
        ),
        **_CHECK_KW,
    )(n_real, *cols)
    return out


def sharded_stage_run(
    params, lview, eta0, hvs, pre, mesh: Mesh | None = None
):
    """The sharded entry of `protocol.batch.validate_batch`: stage the
    window — COLUMNAR when a ViewColumns window arrives (stage_columns:
    whole-matrix slices, one vectorized SHA pad per hash family, no
    per-header objects), per-view otherwise — then shard and verify over
    the mesh. Returns `sharded_run_batch`'s (Verdicts, first_bad, n_ok)."""
    batch = pbatch.stage_any(params, lview, eta0, hvs, pre)
    return sharded_run_batch(batch, mesh)


# process-wide sharded-dispatch sequence (the ShardSpan `index`); only
# advanced while a tracer is installed — same contract as the window
# sequence in protocol/batch
_SHARD_SEQ = 0


def _emit_shard_spans(n_dev: int, v: "pbatch.Verdicts", b: int,
                      wall_s: float) -> None:
    """Per-shard WindowSpan analogue through BATCH_TRACER: shard id,
    lanes carried, popcount-vocabulary ok counts, bucket-pad waste.
    Host-side numpy over the already-materialized padded verdict
    columns — emits nothing (and costs one None check) untraced, so
    the SPMD hot path stays telemetry-free by default."""
    global _SHARD_SEQ
    if pbatch.BATCH_TRACER is None:
        return
    from ..utils.trace import ShardSpan

    idx = _SHARD_SEQ
    _SHARD_SEQ += 1
    ok = (
        np.asarray(v.ok_ocert_sig) & np.asarray(v.ok_kes_sig)
        & np.asarray(v.ok_vrf)
        & (np.asarray(v.ok_leader) | np.asarray(v.leader_ambiguous))
    )
    lanes = ok.shape[0] // n_dev  # pad_batch guarantees divisibility
    for s in range(n_dev):
        start = s * lanes
        real = int(min(max(b - start, 0), lanes))
        n_ok = int(np.count_nonzero(ok[start:start + real]))
        pbatch.BATCH_TRACER(ShardSpan(
            index=idx, shard=s, lanes=lanes, lanes_real=real,
            n_ok=n_ok, pad_lanes=lanes - real, wall_s=wall_s,
        ))


def sharded_run_batch(batch: pbatch.PraosBatch, mesh: Mesh | None = None):
    """Device-parallel `protocol.batch.run_batch`: shard the staged batch
    over the mesh, verify, reduce verdicts with collectives.

    Returns (Verdicts as host numpy sliced to the true batch size,
    first_bad_index or None, n_ok) — drop-in for the sequential epilogue
    in `validate_batch`. With a batch tracer installed (OCT_TRACE /
    obs.install), each dispatch additionally emits one ShardSpan per
    mesh position — the per-shard telemetry MULTICHIP rounds bank
    through the same recorder/ledger machinery as bench."""
    import time

    from ..testing import chaos

    # chaos seam (device-error@shard:N): a shard-level device failure
    # at the N-th sharded dispatch — the supervisor's "sharded" ladder
    # (retry -> xla-twin -> host reference) absorbs it in tier-1
    chaos.fire("shard")

    if mesh is None:
        mesh = make_mesh()
    n_dev = mesh.devices.size
    padded, b = pad_batch(batch, n_dev)
    cols = [
        jax.device_put(
            np.asarray(c), NamedSharding(mesh, P(BATCH_AXIS))
        )
        for c in pbatch.flatten_batch(padded)
    ]
    t0 = time.monotonic()
    v, first_bad, n_ok = _sharded_verify(mesh, jnp.int32(b), *cols)
    vp = pbatch.Verdicts(*(np.asarray(x) for x in v))  # materialize (wait)
    wall = time.monotonic() - t0
    _emit_shard_spans(n_dev, vp, b, wall)
    v = pbatch.Verdicts(*(x[:b] for x in vp))
    fb = int(first_bad)
    return v, (fb if fb < b else None), int(n_ok)
