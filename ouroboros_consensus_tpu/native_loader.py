"""ctypes bindings for the native chunk scanner (native/headerscan.cpp).

Builds the shared library on first use with g++ (cached next to the
source; rebuilt when the source is newer). Falls back gracefully — every
caller treats `load() is None` as "use the pure-Python path", so the
framework works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native", "headerscan.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libheaderscan.so")

_lib = None
_tried = False


def load():
    """The loaded library, building if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO)
    except Exception:
        return None
    lib.ocx_scan_items.restype = ctypes.c_int
    lib.ocx_scan_items.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.ocx_extract_headers.restype = ctypes.c_int
    lib.ocx_extract_headers.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,  # buf, len
        ctypes.c_void_p, ctypes.c_int,  # offsets, n
        *([ctypes.c_void_p] * 21),
    ]
    _lib = lib
    return _lib


def scan_items(buf: bytes, max_items: int = 1 << 20):
    """(offsets, sizes, end) of the complete top-level CBOR items in
    `buf`. `end` is where the well-formed prefix stops — == len(buf)
    iff the whole buffer parses; anything past `end` is a torn tail to
    truncate. None if the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    offsets = np.zeros(max_items, np.int64)
    sizes = np.zeros(max_items, np.int64)
    bad = ctypes.c_int64(0)
    n = lib.ocx_scan_items(
        buf, len(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max_items, ctypes.byref(bad),
    )
    return offsets[:n].copy(), sizes[:n].copy(), int(bad.value)


@dataclass
class HeaderColumns:
    """SoA header columns straight from chunk bytes — the zero-object
    fast path feeding protocol/batch.stage."""

    n: int
    block_no: np.ndarray  # [n] int64
    slot: np.ndarray  # [n] int64
    prev_hash: np.ndarray  # [n, 32] uint8
    has_prev: np.ndarray  # [n] uint8
    issuer_vk: np.ndarray  # [n, 32]
    vrf_vk: np.ndarray  # [n, 32]
    vrf_output: np.ndarray  # [n, 64]
    vrf_proof: np.ndarray  # [n, 80]
    body_size: np.ndarray  # [n] int64
    body_hash: np.ndarray  # [n, 32]
    ocert_vk: np.ndarray  # [n, 32]
    ocert_counter: np.ndarray  # [n] int64
    ocert_kes_period: np.ndarray  # [n] int64
    ocert_sigma: list  # [n] bytes
    pv_major: np.ndarray
    pv_minor: np.ndarray
    kes_sig: list  # [n] bytes
    signed_bytes: list  # [n] bytes — the KES-signed body span
    header_end: np.ndarray  # [n] int64 — buf offset just past the header item


def extract_headers(buf: bytes, offsets: np.ndarray) -> HeaderColumns | None:
    """Parse the blocks at `offsets` into columns. None if the native
    library is unavailable. Raises ValueError on malformed blocks."""
    lib = load()
    if lib is None:
        return None
    n = len(offsets)
    offs = np.ascontiguousarray(offsets, np.int64)
    i64 = lambda: np.zeros(n, np.int64)
    u8 = lambda w: np.zeros((n, w), np.uint8)
    cols = dict(
        block_no=i64(), slot=i64(), prev_hash=u8(32),
        has_prev=np.zeros(n, np.uint8), issuer_vk=u8(32), vrf_vk=u8(32),
        vrf_output=u8(64), vrf_proof=u8(80), body_size=i64(),
        body_hash=u8(32), ocert_vk=u8(32), ocert_counter=i64(),
        ocert_kes_period=i64(),
    )
    sig_off, sig_len = i64(), i64()
    pv_major, pv_minor = i64(), i64()
    kes_off, kes_len = i64(), i64()
    sgn_off, sgn_len = i64(), i64()

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    rc = lib.ocx_extract_headers(
        buf, len(buf), ptr(offs), n,
        ptr(cols["block_no"]), ptr(cols["slot"]),
        ptr(cols["prev_hash"]), ptr(cols["has_prev"]),
        ptr(cols["issuer_vk"]), ptr(cols["vrf_vk"]),
        ptr(cols["vrf_output"]), ptr(cols["vrf_proof"]),
        ptr(cols["body_size"]), ptr(cols["body_hash"]),
        ptr(cols["ocert_vk"]), ptr(cols["ocert_counter"]),
        ptr(cols["ocert_kes_period"]), ptr(sig_off), ptr(sig_len),
        ptr(pv_major), ptr(pv_minor),
        ptr(kes_off), ptr(kes_len), ptr(sgn_off), ptr(sgn_len),
    )
    if rc != 0:
        raise ValueError(f"malformed block at index {rc - 1}")
    return HeaderColumns(
        n=n,
        ocert_sigma=[buf[sig_off[i] : sig_off[i] + sig_len[i]] for i in range(n)],
        pv_major=pv_major,
        pv_minor=pv_minor,
        kes_sig=[buf[kes_off[i] : kes_off[i] + kes_len[i]] for i in range(n)],
        signed_bytes=[buf[sgn_off[i] : sgn_off[i] + sgn_len[i]] for i in range(n)],
        header_end=kes_off + kes_len,
        **cols,
    )
