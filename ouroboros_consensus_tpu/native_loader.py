"""ctypes bindings for the native chunk scanner (native/headerscan.cpp).

Builds the shared library on first use with g++ (cached next to the
source; rebuilt when the source is newer). Falls back gracefully — every
caller treats `load() is None` as "use the pure-Python path", so the
framework works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass
from functools import cached_property

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native", "headerscan.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libheaderscan.so")
_CSRC = os.path.join(os.path.dirname(_SRC), "hostcrypto.cpp")
_CSO = os.path.join(os.path.dirname(_SRC), "libhostcrypto.so")

_lib = None
_tried = False
_clib = None
_ctried = False


def load():
    """The loaded library, building if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO)
    except Exception:
        return None
    lib.ocx_scan_items.restype = ctypes.c_int
    lib.ocx_scan_items.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.ocx_extract_headers.restype = ctypes.c_int
    lib.ocx_extract_headers.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,  # buf, len
        ctypes.c_void_p, ctypes.c_int,  # offsets, n
        *([ctypes.c_void_p] * 22),
    ]
    lib.ocx_crc32_first_bad.restype = ctypes.c_int64
    lib.ocx_crc32_first_bad.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.ocx_parse_index.restype = ctypes.c_int64
    lib.ocx_parse_index.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int64,
        *([ctypes.c_void_p] * 6),
    ]
    _lib = lib
    return _lib


def parse_index(buf: bytes):
    """Columnar parse of a concatenated-CBOR ImmutableDB index:
    (slots, block_nos, hashes[n,32], offsets, sizes, crcs) up to the
    first torn/malformed entry; None when the library is unavailable
    (callers fall back to the per-entry Python decode)."""
    lib = load()
    if lib is None:
        return None
    # true CBOR minimum is 40 bytes/entry (1-byte heads + 34-byte hash
    # item + four 1-byte uints + 1-5 byte crc); capacity at that bound
    # can never be hit by a well-formed index
    cap = max(1, len(buf) // 40 + 1)
    slots = np.zeros(cap, np.int64)
    block_nos = np.zeros(cap, np.int64)
    hashes = np.zeros((cap, 32), np.uint8)
    offsets = np.zeros(cap, np.int64)
    sizes = np.zeros(cap, np.int64)
    crcs = np.zeros(cap, np.int64)

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    n = int(lib.ocx_parse_index(
        buf, len(buf), cap, ptr(slots), ptr(block_nos), ptr(hashes),
        ptr(offsets), ptr(sizes), ptr(crcs),
    ))
    if n >= cap:
        # capacity hit (cannot distinguish from a torn entry): let the
        # Python decode loop decide rather than silently truncating
        return None
    return (slots[:n], block_nos[:n], hashes[:n], offsets[:n], sizes[:n],
            crcs[:n])


def crc32_first_bad(buf: bytes, offsets, sizes, expected) -> int | None:
    """0-based index of the first span whose zlib.crc32 mismatches
    `expected`, -1 if all match; None when the library is unavailable
    (callers fall back to the per-span Python loop)."""
    lib = load()
    if lib is None:
        return None
    offs = np.ascontiguousarray(offsets, np.int64)
    szs = np.ascontiguousarray(sizes, np.int64)
    exp = np.ascontiguousarray(expected, np.int64)

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    return int(
        lib.ocx_crc32_first_bad(buf, len(buf), ptr(offs), ptr(szs), ptr(exp), len(offs))
    )


def scan_items(buf: bytes, max_items: int = 1 << 20):
    """(offsets, sizes, end) of the complete top-level CBOR items in
    `buf`. `end` is where the well-formed prefix stops — == len(buf)
    iff the whole buffer parses; anything past `end` is a torn tail to
    truncate. None if the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    offsets = np.zeros(max_items, np.int64)
    sizes = np.zeros(max_items, np.int64)
    bad = ctypes.c_int64(0)
    n = lib.ocx_scan_items(
        buf, len(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max_items, ctypes.byref(bad),
    )
    return offsets[:n].copy(), sizes[:n].copy(), int(bad.value)


def load_crypto():
    """The native host-crypto library (native/hostcrypto.cpp), building
    on first use; None if unavailable. This is the libsodium-class
    single-core verification path — the measured CPU baseline of
    bench.py and db_analyser --backend native."""
    global _clib, _ctried
    if _clib is not None or _ctried:
        return _clib
    _ctried = True
    try:
        if not os.path.exists(_CSO) or os.path.getmtime(_CSO) < os.path.getmtime(_CSRC):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", _CSO, _CSRC],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_CSO)
    except Exception:
        return None
    lib.oc_ed25519_verify.restype = ctypes.c_int
    lib.oc_ed25519_verify.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.oc_ecvrf_verify.restype = ctypes.c_int
    lib.oc_ecvrf_verify.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p,
    ]
    lib.oc_kes_verify.restype = ctypes.c_int
    lib.oc_kes_verify.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.oc_sha512.restype = None
    lib.oc_sha512.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
    lib.oc_blake2b.restype = None
    lib.oc_blake2b.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.oc_crc32.restype = ctypes.c_uint32
    lib.oc_crc32.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32]
    lib.oc_blake2b_spans.restype = None
    lib.oc_blake2b_spans.argtypes = [
        ctypes.c_void_p, ctypes.c_long, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.oc_validate_praos.restype = ctypes.c_long
    lib.oc_validate_praos.argtypes = (
        [ctypes.c_long] + [ctypes.c_void_p] * 6 + [ctypes.c_long]
        + [ctypes.c_void_p] * 8 + [ctypes.POINTER(ctypes.c_long)]
    )
    lib.oc_validate_praos2.restype = ctypes.c_long
    lib.oc_validate_praos2.argtypes = (
        [ctypes.c_long] + [ctypes.c_void_p] * 6 + [ctypes.c_long]
        + [ctypes.c_void_p] * 4 + [ctypes.c_long]
        + [ctypes.c_void_p] * 4 + [ctypes.POINTER(ctypes.c_long)]
    )
    lib.oc_ecvrf_verify_bc.restype = ctypes.c_int
    lib.oc_ecvrf_verify_bc.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p,
    ]
    lib.oc_ecvrf_prove_bc.restype = None
    lib.oc_ecvrf_prove_bc.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.oc_ed25519_public.restype = None
    lib.oc_ed25519_public.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.oc_ed25519_sign.restype = None
    lib.oc_ed25519_sign.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.oc_ecvrf_prove.restype = None
    lib.oc_ecvrf_prove.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    _clib = lib
    return _clib


def native_crc32(data, value: int = 0):
    """CRC32 (zlib polynomial) via the native library — PCLMULQDQ
    folding on CPUs that have it, bit-identical to ``zlib.crc32``.
    None when the library is unavailable (callers fall back to zlib)."""
    lib = load_crypto()
    if lib is None or not hasattr(lib, "oc_crc32"):
        return None
    buf = np.frombuffer(data, np.uint8)
    return int(lib.oc_crc32(buf.ctypes.data, buf.size, value & 0xFFFFFFFF))


def native_blake2b_spans(data, starts, ends, digest_size: int = 32):
    """Batch blake2b over ``data[starts[i]:ends[i])`` via one C call →
    [n, digest_size] uint8, or None when the library is unavailable
    (callers fall back to the hashlib loop). `data` may be bytes, a
    memoryview, or an mmap — anything the buffer protocol exposes
    contiguously."""
    lib = load_crypto()
    if lib is None or not hasattr(lib, "oc_blake2b_spans"):
        return None
    buf = np.frombuffer(data, np.uint8)
    s = np.ascontiguousarray(starts, np.int64)
    e = np.ascontiguousarray(ends, np.int64)
    n = len(s)
    out = np.empty((n, digest_size), np.uint8)
    if n:
        lib.oc_blake2b_spans(
            buf.ctypes.data, n, s.ctypes.data, e.ctypes.data,
            out.ctypes.data, digest_size,
        )
    return out


def native_ed25519_sign(seed: bytes, msg: bytes) -> bytes | None:
    """Deterministic RFC 8032 signature via the C library, or None when
    the library is unavailable (callers fall back to pure Python)."""
    lib = load_crypto()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(64)
    lib.oc_ed25519_sign(seed, msg, len(msg), out)
    return out.raw


def native_ed25519_public(seed: bytes) -> bytes | None:
    lib = load_crypto()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(32)
    lib.oc_ed25519_public(seed, out)
    return out.raw


def native_ecvrf_prove(seed: bytes, alpha: bytes) -> bytes | None:
    """Deterministic draft-03 ECVRF proof via the C library, or None."""
    lib = load_crypto()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(80)
    lib.oc_ecvrf_prove(seed, alpha, len(alpha), out)
    return out.raw


def native_ecvrf_prove_bc(seed: bytes, alpha: bytes) -> bytes | None:
    """128-byte batch-compatible proof (Gamma ‖ U ‖ V ‖ s), or None."""
    lib = load_crypto()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(128)
    lib.oc_ecvrf_prove_bc(seed, alpha, len(alpha), out)
    return out.raw


def native_ed25519_verify(pk: bytes, sig: bytes, msg: bytes) -> bool:
    lib = load_crypto()
    assert lib is not None
    return bool(lib.oc_ed25519_verify(pk, sig, msg, len(msg)))


def native_ecvrf_verify(pk: bytes, pi: bytes, alpha: bytes):
    """beta bytes or None; proof format discriminated by length."""
    lib = load_crypto()
    assert lib is not None
    beta = ctypes.create_string_buffer(64)
    if len(pi) == 128:
        ok = lib.oc_ecvrf_verify_bc(pk, pi, alpha, len(alpha), beta)
    else:
        ok = lib.oc_ecvrf_verify(pk, pi, alpha, len(alpha), beta)
    return beta.raw if ok else None


def native_kes_verify(vk: bytes, depth: int, period: int, msg: bytes, sig: bytes) -> bool:
    lib = load_crypto()
    assert lib is not None
    return bool(lib.oc_kes_verify(vk, depth, period, msg, len(msg), sig, len(sig)))


def native_validate_praos(
    cold_vk: np.ndarray,    # [n, 32] uint8
    ocert_sig: np.ndarray,  # [n, 64]
    ocert_msg: np.ndarray,  # [n, 48]
    kes_vk: np.ndarray,     # [n, 32]
    kes_t: np.ndarray,      # [n] int64
    kes_sig: np.ndarray,    # [n, 96+32*depth]
    kes_depth: int,
    body: bytes,            # flattened signed_bytes
    body_off: np.ndarray,   # [n+1] int64
    vrf_vk: np.ndarray,     # [n, 32]
    vrf_proof: np.ndarray,  # [n, 80] draft-03 or [n, 128] batch-compatible
    vrf_alpha: np.ndarray,  # [n, 32]
    vrf_output: np.ndarray, # [n, 64]
    want_leader_values: bool = True,
):
    """(first_bad_index or -1, fail_kind 0|1:ocert|2:kes|3:vrf,
    leader_values [n, 32] or None, etas [n, 32] or None). The VRF proof
    format is discriminated by the column width."""
    lib = load_crypto()
    assert lib is not None
    n = len(cold_vk)
    lv = np.zeros((n, 32), np.uint8) if want_leader_values else None
    eta = np.zeros((n, 32), np.uint8) if want_leader_values else None

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p) if a is not None else None

    arrs = [
        np.ascontiguousarray(cold_vk, np.uint8),
        np.ascontiguousarray(ocert_sig, np.uint8),
        np.ascontiguousarray(ocert_msg, np.uint8),
        np.ascontiguousarray(kes_vk, np.uint8),
        np.ascontiguousarray(kes_t, np.int64),
        np.ascontiguousarray(kes_sig, np.uint8),
    ]
    proof = np.ascontiguousarray(vrf_proof, np.uint8)
    proof_len = int(proof.shape[-1]) if proof.ndim == 2 else 80
    tail = [
        np.ascontiguousarray(vrf_vk, np.uint8),
        proof,
    ]
    tail2 = [
        np.ascontiguousarray(vrf_alpha, np.uint8),
        np.ascontiguousarray(vrf_output, np.uint8),
    ]
    boff = np.ascontiguousarray(body_off, np.int64)
    body_arr = np.frombuffer(body, np.uint8) if body else np.zeros(1, np.uint8)
    kind = ctypes.c_long(0)
    rc = lib.oc_validate_praos2(
        n, *[ptr(a) for a in arrs], kes_depth,
        ptr(body_arr), ptr(boff), *[ptr(a) for a in tail], proof_len,
        *[ptr(a) for a in tail2], ptr(lv), ptr(eta),
        ctypes.byref(kind),
    )
    return int(rc), int(kind.value), lv, eta


class MalformedBlock(ValueError):
    """extract_headers hit an unparseable block; `.index` is its
    position in the offsets array (blocks before it parsed clean)."""

    def __init__(self, index: int):
        super().__init__(f"malformed block at index {index}")
        self.index = index


def _span_matrix(buf_u8: np.ndarray, off: np.ndarray, ln: np.ndarray):
    """[n, w] uint8 matrix over the (offset, length) spans of the chunk
    buffer, or None when the spans are not uniform width (the columnar
    pipeline requires row-major rectangular columns; callers fall back
    to the per-row bytes list).

    Uniform-STRIDE spans (the common case: a chunk of equal-size
    blocks) come back as a ZERO-COPY strided view into the buffer;
    anything else is one vectorized fancy-index gather (int32 indices —
    chunk files are far under 2 GiB)."""
    n = len(off)
    if n == 0:
        return np.zeros((0, 0), np.uint8)
    w = int(ln[0])
    if not (ln == w).all():
        return None
    if n > 1:
        d = np.diff(off)
        d0 = int(d[0])
        if d0 > 0 and (d == d0).all():
            return np.lib.stride_tricks.as_strided(
                buf_u8[int(off[0]) :], shape=(n, w), strides=(d0, 1),
            )
    idx = off.astype(np.int32)[:, None] + np.arange(w, dtype=np.int32)
    return buf_u8[idx]


@dataclass
class HeaderColumns:
    """SoA header columns straight from chunk bytes — the zero-object
    fast path feeding protocol/batch.stage.

    The three variable-width fields (`ocert_sigma` / `kes_sig` /
    `signed_bytes`) are stored as (offset, length) spans into the chunk
    buffer: the per-row `bytes`-list views are built LAZILY on first
    access (the per-row slicing loop is exactly the object tax the
    columnar pipeline avoids), and the `*_mat` properties expose them as
    row-major uint8 matrices via one vectorized gather when the spans
    are uniform width (always, on real chains)."""

    n: int
    block_no: np.ndarray  # [n] int64
    slot: np.ndarray  # [n] int64
    prev_hash: np.ndarray  # [n, 32] uint8
    has_prev: np.ndarray  # [n] uint8
    issuer_vk: np.ndarray  # [n, 32]
    vrf_vk: np.ndarray  # [n, 32]
    vrf_output: np.ndarray  # [n, 64]
    vrf_proof: np.ndarray  # [n, 128] zero-padded to the widest format
    vrf_proof_len: np.ndarray  # [n] int64 — 80 (draft-03) or 128 (bc)
    body_size: np.ndarray  # [n] int64
    body_hash: np.ndarray  # [n, 32]
    ocert_vk: np.ndarray  # [n, 32]
    ocert_counter: np.ndarray  # [n] int64
    ocert_kes_period: np.ndarray  # [n] int64
    pv_major: np.ndarray
    pv_minor: np.ndarray
    header_end: np.ndarray  # [n] int64 — buf offset just past the header item
    raw: bytes  # the chunk buffer the spans point into
    sig_off: np.ndarray  # [n] int64 — OCert sigma span
    sig_len: np.ndarray  # [n] int64
    kes_off: np.ndarray  # [n] int64 — KES signature span
    kes_len: np.ndarray  # [n] int64
    sgn_off: np.ndarray  # [n] int64 — KES-signed body span
    sgn_len: np.ndarray  # [n] int64

    def _span_list(self, off, ln) -> list:
        buf = self.raw
        return [
            buf[o : o + l]
            for o, l in zip(off.tolist(), ln.tolist())
        ]

    @cached_property
    def _buf_u8(self) -> np.ndarray:
        return np.frombuffer(self.raw, np.uint8)

    @cached_property
    def ocert_sigma(self) -> list:  # [n] bytes
        return self._span_list(self.sig_off, self.sig_len)

    @cached_property
    def kes_sig(self) -> list:  # [n] bytes
        return self._span_list(self.kes_off, self.kes_len)

    @cached_property
    def signed_bytes(self) -> list:  # [n] bytes — the KES-signed body span
        return self._span_list(self.sgn_off, self.sgn_len)

    @cached_property
    def ocert_sigma_mat(self):  # [n, 64] uint8 | None
        return _span_matrix(self._buf_u8, self.sig_off, self.sig_len)

    @cached_property
    def kes_sig_mat(self):  # [n, 96 + 32*depth] uint8 | None
        return _span_matrix(self._buf_u8, self.kes_off, self.kes_len)

    @cached_property
    def signed_bytes_mat(self):  # [n, body_len] uint8 | None
        return _span_matrix(self._buf_u8, self.sgn_off, self.sgn_len)


def extract_headers(buf: bytes, offsets: np.ndarray) -> HeaderColumns | None:
    """Parse the blocks at `offsets` into columns. None if the native
    library is unavailable. Raises ValueError on malformed blocks."""
    lib = load()
    if lib is None:
        return None
    n = len(offsets)
    offs = np.ascontiguousarray(offsets, np.int64)
    i64 = lambda: np.zeros(n, np.int64)
    u8 = lambda w: np.zeros((n, w), np.uint8)
    cols = dict(
        block_no=i64(), slot=i64(), prev_hash=u8(32),
        has_prev=np.zeros(n, np.uint8), issuer_vk=u8(32), vrf_vk=u8(32),
        vrf_output=u8(64), vrf_proof=u8(128), vrf_proof_len=i64(),
        body_size=i64(),
        body_hash=u8(32), ocert_vk=u8(32), ocert_counter=i64(),
        ocert_kes_period=i64(),
    )
    sig_off, sig_len = i64(), i64()
    pv_major, pv_minor = i64(), i64()
    kes_off, kes_len = i64(), i64()
    sgn_off, sgn_len = i64(), i64()

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    rc = lib.ocx_extract_headers(
        buf, len(buf), ptr(offs), n,
        ptr(cols["block_no"]), ptr(cols["slot"]),
        ptr(cols["prev_hash"]), ptr(cols["has_prev"]),
        ptr(cols["issuer_vk"]), ptr(cols["vrf_vk"]),
        ptr(cols["vrf_output"]), ptr(cols["vrf_proof"]),
        ptr(cols["vrf_proof_len"]),
        ptr(cols["body_size"]), ptr(cols["body_hash"]),
        ptr(cols["ocert_vk"]), ptr(cols["ocert_counter"]),
        ptr(cols["ocert_kes_period"]), ptr(sig_off), ptr(sig_len),
        ptr(pv_major), ptr(pv_minor),
        ptr(kes_off), ptr(kes_len), ptr(sgn_off), ptr(sgn_len),
    )
    if rc != 0:
        raise MalformedBlock(rc - 1)
    return HeaderColumns(
        n=n,
        pv_major=pv_major,
        pv_minor=pv_minor,
        header_end=kes_off + kes_len,
        raw=buf,
        sig_off=sig_off, sig_len=sig_len,
        kes_off=kes_off, kes_len=kes_len,
        sgn_off=sgn_off, sgn_len=sgn_len,
        **cols,
    )
