"""Pass 2 — jaxpr pathology analyzer.

Traces every registered kernel with ABSTRACT inputs (no compile, no
device) and computes the graph-shape metrics that predict the XLA
compile-time pathologies this repo has actually hit (the algebraic
simplifier's circular-simplification loop on the fused
`verify_praos_core` graph — VERDICT r5 weak #3/#4, the round-5
eager-only composed smoke):

  mul_chain_depth   longest path of multiply-class primitives
                    (mul / dot_general) through any SINGLE XLA
                    computation. Control-flow bodies (while / scan /
                    cond / pallas_call) are separate computations — the
                    simplifier rewrites one computation at a time, so a
                    `fori_loop` FENCES a chain: only the unrolled
                    segment feeds the rewrite loop. This is the metric
                    the squaring-chain family trips.
  op_fanout         max number of consumer equations of one value —
                    wide fan-out multiplies the simplifier's rewrite
                    candidates per pass.
  remat_width       peak number of simultaneously live values over the
                    jaxpr's own schedule — a proxy for the
                    rematerialization pressure XLA's scheduler faces.
  eqns              recursive primitive count (graph size).
  mul_count         recursive multiply-class primitive count.

`budgets.json` pins a ceiling per registered graph; `check_budgets`
fails any graph over its ceiling, fencing regressions of the
simplifier-circular pattern family in CI (tests/test_analysis.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable

# multiply-class primitives: the algebraic simplifier's worst rewrite
# families (reassociation/distribution) chew on these
_MUL_PRIMS = {"mul", "dot_general"}
# call-like primitives whose subjaxprs are separate XLA computations
_FENCE_PRIMS = {
    "while", "scan", "cond", "pjit", "closed_call", "core_call",
    "custom_jvp_call", "custom_vjp_call", "remat", "checkpoint",
    "pallas_call", "shard_map", "custom_partitioning",
}


@dataclasses.dataclass
class GraphReport:
    name: str
    eqns: int
    mul_count: int
    mul_chain_depth: int
    op_fanout: int
    remat_width: int
    computations: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for x in vs:
            while hasattr(x, "jaxpr"):  # ClosedJaxpr (possibly nested)
                x = x.jaxpr
            if hasattr(x, "eqns"):
                yield x


def _analyze(jaxpr, acc: dict) -> int:
    """One computation: returns its internal max mul-chain depth and
    folds every metric into `acc`. Recurses into subcomputations, which
    contribute to the global max but NOT to this computation's chain
    (they are fences)."""
    depth: dict[int, int] = {}  # id(var) -> mul-chain depth at that value
    uses: dict[int, int] = {}
    last_use: dict[int, int] = {}
    acc["computations"] += 1

    for i, eqn in enumerate(jaxpr.eqns):
        acc["eqns"] += 1
        prim = eqn.primitive.name
        is_mul = prim in _MUL_PRIMS
        if is_mul:
            acc["mul_count"] += 1
        in_depth = 0
        for v in eqn.invars:
            if hasattr(v, "val"):  # Literal
                continue
            uses[id(v)] = uses.get(id(v), 0) + 1
            last_use[id(v)] = i
            in_depth = max(in_depth, depth.get(id(v), 0))
        if prim in _FENCE_PRIMS:
            for sub in _sub_jaxprs(eqn):
                _analyze(sub, acc)
            out_depth = 0  # separate computation: the chain is fenced
        else:
            out_depth = in_depth + (1 if is_mul else 0)
        for v in eqn.outvars:
            depth[id(v)] = out_depth
        acc["chain"] = max(acc["chain"], out_depth)
    for v in jaxpr.outvars:
        if not hasattr(v, "val"):
            uses[id(v)] = uses.get(id(v), 0) + 1
            last_use[id(v)] = len(jaxpr.eqns)
    if uses:
        acc["fanout"] = max(acc["fanout"], max(uses.values()))

    # remat_width: live-interval sweep over the jaxpr's own order
    born: dict[int, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            born[id(v)] = i
    events: list[tuple[int, int]] = []
    for vid, b in born.items():
        d = last_use.get(vid, b)
        events.append((b, 1))
        events.append((d + 1, -1))
    live = peak = 0
    for _, delta in sorted(events):
        live += delta
        peak = max(peak, live)
    acc["width"] = max(acc["width"], peak)
    return acc["chain"]


def analyze_jaxpr(closed_jaxpr, name: str = "graph") -> GraphReport:
    """Compute the pathology metrics of one traced jaxpr."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    acc = {"eqns": 0, "mul_count": 0, "chain": 0, "fanout": 0,
           "width": 0, "computations": 0}
    _analyze(jaxpr, acc)
    return GraphReport(
        name=name,
        eqns=acc["eqns"],
        mul_count=acc["mul_count"],
        mul_chain_depth=acc["chain"],
        op_fanout=acc["fanout"],
        remat_width=acc["width"],
        computations=acc["computations"],
    )


# ---------------------------------------------------------------------------
# Kernel registry: every graph the repo dispatches, with the abstract
# input shapes it is traced at. T (the batch tile) only scales array
# widths, never graph structure, so a tiny T keeps tracing fast while
# the metrics match production shapes exactly. Every builder takes an
# optional lane-count override `t`: the octrange interval certification
# (analysis/absint.py) re-traces the lane-SENSITIVE graphs (msm,
# aggregate, verdict_reduce — anything that reduces over the lane axis)
# at production lane counts, while budgets and the lane-INVARIANT
# certificates share the default small-tile trace through trace_graph's
# cache.
# ---------------------------------------------------------------------------

_T = 2
_NB = 2
_DEPTH = 2


def _s(*shape):
    import jax
    from jax import numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _pk_core_args(t):
    return (
        _s(32, t), _s(32, t), _s(32, t), _s(_NB, 128, t), _s(t),
        _s(32, t), _s(t), _s(32, t), _s(32, t), _s(32, t),
        _s(_DEPTH, 32, t), _s(_NB, 128, t), _s(t),
        _s(32, t), _s(32, t), _s(16, t), _s(32, t), _s(32, t),
        _s(64, t), _s(32, t), _s(32, t),
    )


def _graph_ed_core(t=None):
    from ..ops.pk import verify as pv

    t = t or _T
    return pv.ed_core, (_s(32, t), _s(32, t), _s(_NB, 128, t), _s(t))


def _graph_kes_core(t=None):
    import functools

    from ..ops.pk import verify as pv

    t = t or _T
    fn = functools.partial(pv.kes_core, depth=_DEPTH)
    return fn, (
        _s(32, t), _s(t), _s(32, t), _s(32, t), _s(_DEPTH, 32, t),
        _s(_NB, 128, t), _s(t),
    )


def _graph_vrf_core(t=None):
    from ..ops.pk import verify as pv

    t = t or _T
    return pv.vrf_core, (
        _s(32, t), _s(32, t), _s(16, t), _s(32, t), _s(32, t)
    )


def _graph_finish_core(t=None):
    from ..ops.pk import verify as pv

    t = t or _T

    def fn(ed_ok, ed_pt, ed_r, kes_ok, kes_pt, kes_r, vrf_ok, vrf_flat,
           c, beta, tlo, thi):
        from ..ops.pk import curve as pc

        def pt(flat):
            return pc.Point(flat[0:20], flat[20:40], flat[40:60], flat[60:80])

        pts = [pt(vrf_flat[80 * i: 80 * (i + 1)]) for i in range(5)]
        return pv.finish_core(
            ed_ok != 0, pt(ed_pt), ed_r, kes_ok != 0, pt(kes_pt), kes_r,
            vrf_ok != 0, pts, c, beta, tlo, thi,
        )

    return fn, (
        _s(t), _s(80, t), _s(32, t), _s(t), _s(80, t), _s(32, t),
        _s(t), _s(400, t), _s(16, t), _s(64, t), _s(32, t), _s(32, t),
    )


def _graph_verify_praos_core(t=None):
    import functools

    from ..ops.pk import verify as pv

    fn = functools.partial(pv.verify_praos_core, kes_depth=_DEPTH)
    return fn, _pk_core_args(t or _T)


def _pk_core_args_bc(t):
    # batch-compatible composed shapes: vrf_c [16, T] is replaced by the
    # announced u, v [32, T] columns
    return (
        _s(32, t), _s(32, t), _s(32, t), _s(_NB, 128, t), _s(t),
        _s(32, t), _s(t), _s(32, t), _s(32, t), _s(32, t),
        _s(_DEPTH, 32, t), _s(_NB, 128, t), _s(t),
        _s(32, t), _s(32, t), _s(32, t), _s(32, t), _s(32, t),
        _s(32, t),
        _s(64, t), _s(32, t), _s(32, t),
    )


def _graph_vrf_bc_core(t=None):
    from ..ops.pk import verify as pv

    t = t or _T
    return pv.vrf_core_bc, (
        _s(32, t), _s(32, t), _s(32, t), _s(32, t), _s(32, t),
        _s(32, t),
    )


def _graph_verify_praos_core_bc(t=None):
    import functools

    from ..ops.pk import verify as pv

    fn = functools.partial(pv.verify_praos_core_bc, kes_depth=_DEPTH)
    return fn, _pk_core_args_bc(t or _T)


def _graph_msm(t=None):
    """One Pippenger MSM (ops/pk/msm.py) at a tiny lane count: the
    fori-fenced scans keep the chain depth flat in N, so tiny shapes pin
    the same structure the bench-scale aggregate dispatches. (The
    interval certification re-traces at production N — the bucket-count
    accumulators are the lane-sensitive part.)"""
    from ..ops.pk import curve as pc
    from ..ops.pk import msm as pk_msm

    n = t or 4

    def fn(scalars, x, y, z, t):
        return pk_msm.msm(scalars, pc.Point(x, y, z, t), 256)

    return fn, (_s(20, n), _s(20, n), _s(20, n), _s(20, n), _s(20, n))


def _graph_aggregate_core(t=None):
    """The full aggregated window program (ops/pk/aggregate.py): cheap
    per-lane work + Fiat–Shamir coefficients + the two-group MSM."""
    import functools

    from ..ops.pk import aggregate as pk_aggregate

    t = t or _T
    fn = functools.partial(pk_aggregate.aggregate_window, kes_depth=_DEPTH)
    return fn, (
        _s(32, t), _s(32, t), _s(32, t), _s(_NB, 128, t), _s(1, t),
        _s(32, t), _s(1, t), _s(32, t), _s(32, t), _s(32, t),
        _s(_DEPTH, 32, t), _s(_NB, 128, t), _s(1, t),
        _s(32, t), _s(32, t), _s(32, t), _s(32, t), _s(32, t),
        _s(32, t),
        _s(64, t), _s(32, t), _s(32, t),
    )


def _graph_aggregate_vrf_core(t=None):
    """The kill-switch (OCT_RLC_ALL=0) aggregated window program
    (ops/pk/aggregate.aggregate_window_vrf): exact per-lane ed/KES
    checks + the vrf-only RLC on the unsigned per-group MSM engine.
    Same 22-column signature as the unified program."""
    import functools

    from ..ops.pk import aggregate as pk_aggregate

    t = t or _T
    fn = functools.partial(pk_aggregate.aggregate_window_vrf,
                           kes_depth=_DEPTH)
    return fn, (
        _s(32, t), _s(32, t), _s(32, t), _s(_NB, 128, t), _s(1, t),
        _s(32, t), _s(1, t), _s(32, t), _s(32, t), _s(32, t),
        _s(_DEPTH, 32, t), _s(_NB, 128, t), _s(1, t),
        _s(32, t), _s(32, t), _s(32, t), _s(32, t), _s(32, t),
        _s(32, t),
        _s(64, t), _s(32, t), _s(32, t),
    )


def _graph_spmd_local(t=None):
    """The per-shard body of parallel/spmd._sharded_verify: the XLA-twin
    `protocol.batch.verify_praos` plus the verdict collectives, traced
    under a single-device mesh (collective structure is device-count
    independent)."""
    import jax
    import numpy as np
    from jax import numpy as jnp
    from jax.sharding import Mesh

    from ..parallel import spmd

    b = t or 8

    def u8(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint8)

    def u32(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint32)

    # flatten_batch order, staged dtypes (protocol/batch.PraosBatch)
    cols = (
        u8(b, 32), u8(b, 32), u8(b, 32), u32(b, _NB, 16, 2), _s(b),
        u8(b, 32), _s(b), u8(b, 32), u8(b, 32), u8(b, 32),
        u8(b, _DEPTH, 32), u32(b, _NB, 16, 2), _s(b),
        u8(b, 32), u8(b, 32), u8(b, 16), u8(b, 32), u8(b, 32),
        u8(b, 64), u8(b, 32), u8(b, 32),
    )
    mesh = Mesh(np.asarray(jax.devices("cpu")[:1]), (spmd.BATCH_AXIS,))

    def fn(*cs):
        return spmd._sharded_verify(mesh, jnp.int32(b), *cs)

    return fn, cols


def _graph_packed_unpack(t=None):
    """The PRODUCTION packed `unpack` stage
    (ops/pk/kernels._mk_packed_unpack): protocol/batch.unpack_packed —
    body-sourced u8 columns -> the 21 staged columns, including the
    on-device SHA-512 padding, VRF alpha hash and table gathers —
    CHAINED into staged_to_limb_first, exactly the graph the per-stage
    jit/AOT executable compiles and dispatches. Traced at a synthetic
    (non-overlapping-offset) layout — offsets only slide slices, never
    change graph structure."""
    import jax
    from jax import numpy as jnp

    from ..ops.pk import kernels as pk_kernels
    from ..protocol import batch as pbatch

    b = t or 4
    layout = pbatch.PraosPackedLayout(
        body_len=304, o_issuer=0, o_vrf_vk=32, o_vrf_out=64,
        o_vrf_proof=128, o_vk_hot=208, o_sigma=240,
        kes_depth=_DEPTH, slots_per_kes=100, has_nonce=True,
    )

    def u8(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint8)

    args = (
        u8(b, 304), u8(b, 64), _s(b), u8(8, 32 + 32 * _DEPTH),
        _s(b), _s(b), _s(b), _s(b), u8(8, 64), u8(32),
    )
    return pk_kernels._mk_packed_unpack(layout), args


def _graph_verdict_reduce(t=None):
    """The packed D2H reduction (protocol/batch.verdict_reduce,
    scan=True): verdict-bit packing + the sequential Blake2b nonce scan
    (ops/blake2b.nonce_fold_scan). The scan body is a separate
    computation (lax.scan fences the chain)."""
    import functools

    import jax
    from jax import numpy as jnp

    from ..protocol import batch as pbatch

    b = t or 8

    def bl(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.bool_)

    args = (
        _s(5, b), _s(b, 32), _s(b), _s(),
        _s(32), bl(), _s(32), bl(),
    )
    return functools.partial(pbatch.verdict_reduce, scan=True), args


def _graph_forge_sweep(t=None):
    """The leader-election sweep (protocol/forge.forge_sweep): device
    alpha derivation, the full VRF prove (both proof serializations),
    the Blake2b leader-value tail and the threshold bracket — exactly
    the program the batched synthesizer dispatches per election window.
    Lane-invariant (everything is per-(slot, pool) pair), so the tiny
    registry tile pins the production FORGE_BUCKET structure."""
    import jax
    from jax import numpy as jnp

    from ..protocol import forge as pforge

    b = t or _T

    def u8(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint8)

    args = (
        u8(b, 32), u8(b, 32), u8(b, 32), _s(b), u8(32),
        u8(b, 32), u8(b, 32),
    )
    return pforge.forge_sweep, args


def _graph_forge_sign(t=None):
    """The packed OCert-issue signer (protocol/forge.forge_sign — the
    certified ed25519 sign kernel under its forge-lane registry name):
    the sign direction of the forging pipeline carries its own pins at
    the shape the synthesizer dispatches (deduped OCert signables)."""
    import jax
    from jax import numpy as jnp

    from ..protocol import forge as pforge

    b = t or 4

    def u8(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint8)

    def u32(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint32)

    args = (
        u8(b, 32), u8(b, 32), u32(b, _NB, 16, 2), _s(b),
        u32(b, _NB, 16, 2), _s(b),
    )
    return pforge.forge_sign, args


REGISTRY: dict[str, Callable] = {
    "ed_core": _graph_ed_core,
    "kes_core": _graph_kes_core,
    "vrf_core": _graph_vrf_core,
    "vrf_bc_core": _graph_vrf_bc_core,
    "finish_core": _graph_finish_core,
    "verify_praos_core": _graph_verify_praos_core,
    "verify_praos_core_bc": _graph_verify_praos_core_bc,
    "msm": _graph_msm,
    "aggregate_core": _graph_aggregate_core,
    "aggregate_vrf_core": _graph_aggregate_vrf_core,
    "spmd_sharded_verify": _graph_spmd_local,
    "packed_unpack": _graph_packed_unpack,
    "verdict_reduce": _graph_verdict_reduce,
    "forge_sweep": _graph_forge_sweep,
    "forge_sign": _graph_forge_sign,
}


# Source modules (repo-relative) each graph's trace actually executes —
# the `scripts/lint.py --changed` fast path re-analyzes only graphs
# whose module set intersects the git diff. Shared leaves (limbs, curve,
# hashes, field) appear in every pk graph by construction.
_PK_COMMON = [
    "ouroboros_consensus_tpu/ops/pk/limbs.py",
    "ouroboros_consensus_tpu/ops/pk/curve.py",
    "ouroboros_consensus_tpu/ops/pk/hashes.py",
    "ouroboros_consensus_tpu/ops/pk/verify.py",
    "ouroboros_consensus_tpu/ops/field.py",
    "ouroboros_consensus_tpu/ops/bigint.py",
    "ouroboros_consensus_tpu/ops/sha512.py",
    "ouroboros_consensus_tpu/ops/blake2b.py",
    "ouroboros_consensus_tpu/ops/u64.py",
]
_XLA_TWIN = [
    "ouroboros_consensus_tpu/ops/curve.py",
    "ouroboros_consensus_tpu/ops/scalar.py",
    "ouroboros_consensus_tpu/ops/ed25519_batch.py",
    "ouroboros_consensus_tpu/ops/kes_batch.py",
    "ouroboros_consensus_tpu/ops/ecvrf_batch.py",
    "ouroboros_consensus_tpu/protocol/batch.py",
]
GRAPH_SOURCES: dict[str, list[str]] = {
    "ed_core": _PK_COMMON,
    "kes_core": _PK_COMMON,
    "vrf_core": _PK_COMMON,
    "vrf_bc_core": _PK_COMMON,
    "finish_core": _PK_COMMON,
    "verify_praos_core": _PK_COMMON,
    "verify_praos_core_bc": _PK_COMMON,
    "msm": _PK_COMMON + ["ouroboros_consensus_tpu/ops/pk/msm.py"],
    "aggregate_core": _PK_COMMON + [
        "ouroboros_consensus_tpu/ops/pk/msm.py",
        "ouroboros_consensus_tpu/ops/pk/aggregate.py",
    ],
    "aggregate_vrf_core": _PK_COMMON + [
        "ouroboros_consensus_tpu/ops/pk/msm.py",
        "ouroboros_consensus_tpu/ops/pk/aggregate.py",
    ],
    "spmd_sharded_verify": _XLA_TWIN + [
        "ouroboros_consensus_tpu/parallel/spmd.py",
        "ouroboros_consensus_tpu/ops/field.py",
        "ouroboros_consensus_tpu/ops/bigint.py",
        "ouroboros_consensus_tpu/ops/sha512.py",
        "ouroboros_consensus_tpu/ops/blake2b.py",
        "ouroboros_consensus_tpu/ops/u64.py",
    ],
    "packed_unpack": _PK_COMMON + [
        "ouroboros_consensus_tpu/ops/pk/kernels.py",
        "ouroboros_consensus_tpu/protocol/batch.py",
    ],
    "verdict_reduce": [
        "ouroboros_consensus_tpu/protocol/batch.py",
        "ouroboros_consensus_tpu/ops/blake2b.py",
        "ouroboros_consensus_tpu/ops/u64.py",
    ],
    # the forge graphs trace through the XLA-twin ops (ecvrf_batch /
    # ed25519_batch), not the ops/pk ladder cores
    "forge_sweep": _XLA_TWIN + [
        "ouroboros_consensus_tpu/protocol/forge.py",
        "ouroboros_consensus_tpu/ops/field.py",
        "ouroboros_consensus_tpu/ops/bigint.py",
        "ouroboros_consensus_tpu/ops/sha512.py",
        "ouroboros_consensus_tpu/ops/blake2b.py",
        "ouroboros_consensus_tpu/ops/u64.py",
    ],
    "forge_sign": [
        "ouroboros_consensus_tpu/protocol/forge.py",
        "ouroboros_consensus_tpu/ops/ed25519_batch.py",
        "ouroboros_consensus_tpu/ops/curve.py",
        "ouroboros_consensus_tpu/ops/scalar.py",
        "ouroboros_consensus_tpu/ops/bigint.py",
        "ouroboros_consensus_tpu/ops/field.py",
        "ouroboros_consensus_tpu/ops/sha512.py",
        "ouroboros_consensus_tpu/ops/u64.py",
    ],
}


# the tile each builder bakes when called with t=None — trace_graph
# normalizes an explicit t equal to the builder default onto the (name,
# None) cache key so the budget, point-op and certification passes share
# one trace per graph
DEFAULT_TILES: dict[str, int] = {
    "ed_core": _T, "kes_core": _T, "vrf_core": _T, "vrf_bc_core": _T,
    "finish_core": _T, "verify_praos_core": _T, "verify_praos_core_bc": _T,
    "aggregate_core": _T, "aggregate_vrf_core": _T, "msm": 4,
    "spmd_sharded_verify": 8,
    "packed_unpack": 4, "verdict_reduce": 8,
    "forge_sweep": _T, "forge_sign": 4,
}


def registered_graphs() -> list[str]:
    return sorted(REGISTRY)


# trace cache: (name, t) -> ClosedJaxpr. One tier-1 pytest process
# traces each composed graph ONCE no matter how many passes (budgets,
# golden pin, interval, taint, point-ops) consume it — the traces are
# the expensive part (30-60 s each for the composed cores). Capped LRU:
# a composed jaxpr holds ~200k eqn objects, so an unbounded cache would
# pin gigabytes across a full slow-tier sweep; consumers that want
# sharing run their passes per graph before moving on.
_TRACE_CACHE_MAX = 3
_TRACE_CACHE: dict[tuple[str, int | None], object] = {}
# trace-time point-op capture (ops/pk/curve.py op_counter), recorded as
# a free by-product of every cached trace: (name, t) -> dict (kept for
# all keys — counts are tiny)
_POINT_OPS: dict[tuple[str, int | None], dict] = {}


def trace_graph(name: str, t: int | None = None):
    import jax

    if t is not None and t == DEFAULT_TILES.get(name):
        t = None
    key = (name, t)
    if key in _TRACE_CACHE:
        _TRACE_CACHE[key] = _TRACE_CACHE.pop(key)  # LRU touch
        return _TRACE_CACHE[key]
    from ..ops.pk import curve as pc

    fn, args = REGISTRY[name](t)
    with pc.op_counter() as stats:
        traced = jax.make_jaxpr(fn)(*args)
    _POINT_OPS[key] = {"ops": stats["ops"], "lane_ops": stats["lane_ops"]}
    _TRACE_CACHE[key] = traced
    while len(_TRACE_CACHE) > _TRACE_CACHE_MAX:
        _TRACE_CACHE.pop(next(iter(_TRACE_CACHE)))
    return traced


def point_ops(name: str, t: int | None = None) -> dict:
    """Point-op counts captured while tracing (name, t); traces on
    first use. Only the ops/pk graphs route through the counted
    add/double helpers — other graphs report zeros."""
    if t is not None and t == DEFAULT_TILES.get(name):
        t = None
    trace_graph(name, t)
    return dict(_POINT_OPS[(name, t)])


def analyze_registered(names: list[str] | None = None) -> list[GraphReport]:
    reports = []
    for name in names or registered_graphs():
        reports.append(analyze_jaxpr(trace_graph(name), name))
    return reports


# ---------------------------------------------------------------------------
# Budgets
# ---------------------------------------------------------------------------

_BUDGET_PATH = os.path.join(os.path.dirname(__file__), "budgets.json")


def load_budgets(path: str | None = None) -> dict:
    with open(path or _BUDGET_PATH, encoding="utf-8") as f:
        return json.load(f)


def check_point_ops(budgets: dict | None = None,
                    names: list[str] | None = None) -> list[str]:
    """Third ratcheted metric (promoted from scripts/count_point_ops.py):
    per-lane point-op ceilings per graph, pinned in budgets.json under
    "point_ops" as {"at_lanes": T, "lane_ops_per_lane": ceiling}.
    Counts come free with the (name, at_lanes) trace (the op_counter
    capture in trace_graph), so a gate that already traced the graph for
    budgets/certification pays nothing extra. A perf regression in the
    MSM/aggregate path — more adds per bucket pass, a lost shared
    doubling chain — fails here statically, without a device."""
    budgets = budgets if budgets is not None else load_budgets()
    sec = budgets.get("point_ops", {})
    violations = []
    for name in sorted(sec):
        cfg = sec[name]
        if name == "all_stage_total":
            # Composite pin (round 15): the SUM of per-lane point ops
            # across every stage executable the unified dispatch path
            # runs per window (cfg["graphs"]). This is the number the
            # one-RLC fold is accountable for — before the fold the
            # per-window total was agg(vrf) + ed + kes ladders
            # (~1018/lane); folding all four equations into one
            # shared-bucket MSM takes the whole pipeline under 100.
            members = list(cfg["graphs"])
            if names is not None and not set(members) & set(names):
                continue
            lanes = int(cfg["at_lanes"])
            ceiling = float(cfg["lane_ops_per_lane"])
            total = sum(point_ops(g, lanes)["lane_ops"] / lanes
                        for g in members)
            if total > ceiling:
                violations.append(
                    f"all_stage_total: {total:.1f} point lane-ops/lane "
                    f"summed over {'+'.join(members)} at {lanes} lanes "
                    f"exceeds budget {ceiling:g}"
                )
            continue
        if names is not None and name not in names:
            continue
        lanes = int(cfg["at_lanes"])
        ceiling = float(cfg["lane_ops_per_lane"])
        stats = point_ops(name, lanes)
        per_lane = stats["lane_ops"] / lanes
        if per_lane > ceiling:
            violations.append(
                f"{name}: {per_lane:.1f} point lane-ops/lane at "
                f"{lanes} lanes exceeds budget {ceiling:g}"
            )
    return violations


def check_instrumentation_purity(budgets: dict | None = None,
                                 names: list[str] | None = None) -> list[str]:
    """Observability is HOST-side only: re-trace each graph listed under
    budgets.json "instrumentation_purity" with the obs flight recorder
    installed and OCT_TRACE forced on, and fail on ANY equation-count
    delta against the baseline trace. Telemetry that leaks into a traced
    program (an io_callback, a debug print, a traced counter) would grow
    the jaxpr — this differential pins the growth at exactly zero.

    The configured set is the graphs built FROM the instrumented host
    modules (protocol/batch.py, ops/pk/kernels.py): those are the only
    programs whose trace even executes telemetry-adjacent code, so the
    differential is cheap (small tiles) while fencing the real hazard."""
    budgets = budgets if budgets is not None else load_budgets()
    cfg = budgets.get("instrumentation_purity", {})
    todo = [n for n in cfg.get("graphs", [])
            if names is None or n in names]
    if not todo:
        return []
    import jax

    from .. import obs

    violations = []
    for name in todo:
        if name not in REGISTRY:
            violations.append(
                f"{name}: instrumentation_purity names an unregistered graph"
            )
            continue
        base = analyze_jaxpr(trace_graph(name), name).eqns
        old = os.environ.get("OCT_TRACE")
        os.environ["OCT_TRACE"] = "1"
        obs.install()
        try:
            fn, args = REGISTRY[name](None)
            with_obs = analyze_jaxpr(jax.make_jaxpr(fn)(*args), name).eqns
        finally:
            obs.uninstall()
            if old is None:
                os.environ.pop("OCT_TRACE", None)
            else:
                os.environ["OCT_TRACE"] = old
        if with_obs != base:
            violations.append(
                f"{name}: {with_obs - base:+d} equation(s) from telemetry "
                f"({base} -> {with_obs}); observability must stay host-side"
            )
    return violations


def check_budgets(reports: list[GraphReport],
                  budgets: dict | None = None) -> list[str]:
    """-> list of violation strings (empty = all graphs under budget).
    A graph missing from the budget file is itself a violation: every
    registered kernel must carry a pinned ceiling."""
    budgets = budgets if budgets is not None else load_budgets()
    per_graph = budgets.get("graphs", {})
    violations = []
    for r in reports:
        limits = per_graph.get(r.name)
        if limits is None:
            violations.append(
                f"{r.name}: no budget entry in budgets.json "
                "(add one to pin this graph)"
            )
            continue
        for metric, ceiling in limits.items():
            actual = getattr(r, metric, None)
            if actual is None:
                violations.append(f"{r.name}: unknown metric {metric!r}")
            elif actual > ceiling:
                violations.append(
                    f"{r.name}: {metric} = {actual} exceeds budget "
                    f"{ceiling}"
                )
    return violations
