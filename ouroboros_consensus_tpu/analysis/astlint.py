"""Pass 1 — AST jit-safety linter.

Finds the host/device hazards that are statically visible in Python
source long before XLA (or a TPU runtime) ever sees the program. Rules:

  OCT101 host-sync-in-jit     `.item()` / `.tolist()` /
                              `.block_until_ready()` / `np.asarray` /
                              `np.array` / `jax.device_get` — and
                              `float()`/`int()`/`bool()` applied to a
                              locally traced value — inside a function
                              reachable from a `@jax.jit` /
                              `shard_map` / `pallas_call` root. Each of
                              these forces a device→host transfer (or a
                              trace error) in the middle of a traced
                              graph.
  OCT102 traced-branch        Python `if`/`while` whose condition
                              references a traced value inside jit
                              code: data-dependent Python control flow
                              either fails to trace or silently bakes
                              in one branch.
  OCT103 mutable-global-capture
                              a jit-reachable function reads a
                              module-level mutable object (dict/list/
                              set). jit traces capture the CONTENTS at
                              trace time; later mutation desyncs the
                              compiled executable from the Python
                              state.
  OCT104 wide-int-literal     an integer literal that does not fit in
                              int32 inside jit code: jax weak types
                              promote the lane to 64-bit (or overflow
                              at lowering on 32-bit TPU lanes) —
                              the u32-lane widening pitfall.
  OCT105 await-holding-lock   `await` while holding a RAWLock /
                              ResourceRegistry resource in async
                              runtime code: the awaited IO can block
                              arbitrarily, starving every sim/async
                              task queued on the lock.

Suppression syntax (documented in analysis/README.md):

  x = thing.item()   # octlint: disable=OCT101  <why it is safe here>
  # a trailing `# octlint: disable` (no rule list) suppresses all rules
  # on that line; the def-line of a function suppresses its whole body;
  # `# octlint: disable-file=OCT103` anywhere suppresses the file.

The linter is best-effort by design: reachability is a static
over-approximation (name-resolved calls across package modules), so a
finding is "this pattern is hostile to jit if this code ever traces",
not a proof of breakage — the suppression comment is the reviewed
assertion that it does not.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable

RULES = {
    "OCT101": "host-sync-in-jit",
    "OCT102": "traced-branch",
    "OCT103": "mutable-global-capture",
    "OCT104": "wide-int-literal",
    "OCT105": "await-holding-lock",
    # a suppression comment that suppresses nothing on the current tree
    # is itself a finding: as files get rewritten, stale `# octlint:
    # disable=…` comments would otherwise silently pre-authorize the
    # next real hazard on that line (suppression rot)
    "OCT106": "stale-suppression",
}

# rule tokens are letters-then-digits (OCT101); matching them strictly
# keeps a trailing justification ("… disable=OCT101 TPU sync is fine
# here") out of the captured rule list
_RULE_LIST = r"[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*"
_SUPPRESS_RE = re.compile(
    rf"#\s*octlint:\s*disable(?:=({_RULE_LIST}))?(?=[\s,]|$)"
)
_SUPPRESS_FILE_RE = re.compile(
    rf"#\s*octlint:\s*disable-file=({_RULE_LIST})"
)

# host-sync method names (attribute calls on any object)
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# host-sync module functions, resolved through import aliases; only
# flagged when the argument is locally traced — np.asarray over host
# constants at trace time is the normal way to build jit constants
_SYNC_NUMPY_FNS = {"asarray", "array", "copy"}
_SYNC_JAX_FNS = {"device_get"}
# builtins that force a concrete value out of a tracer
_SYNC_BUILTINS = {"float", "int", "bool"}

# attribute reads that are static at trace time: referencing a traced
# array through these does NOT taint the result
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "weak_type"}

# explicit dtype constructors: a wide literal wrapped in one of these is
# a deliberate 64/unsigned-width value, not an accidental widening
_DTYPE_CTORS = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bfloat16",
}

_JAXY_MODULES = {"jax", "jax.numpy", "jax.lax", "numpy"}  # numpy NOT traced
_TRACED_MODULES = {"jax", "jax.numpy", "jax.lax"}

_LOCK_ACQUIRE = {"acquire_read", "acquire_append", "acquire_write", "allocate"}
_LOCK_RELEASE = {"release_read", "release_append", "release_write", "close"}


def _comment_lines(source: str):
    """(line_no, text) for every REAL comment in the source — tokenized
    so a suppression example quoted inside a docstring neither
    suppresses anything nor trips the OCT106 stale audit. Falls back to
    a plain line scan if the file does not tokenize (the AST parse will
    report the syntax error through its own path)."""
    import io
    import tokenize

    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return list(enumerate(source.splitlines(), start=1))
    return [
        (t.start[0], t.string) for t in toks
        if t.type == tokenize.COMMENT
    ]


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    # ordinal among same-keyed findings in one lint run (assigned by
    # lint_paths): a SECOND occurrence of a grandfathered hazard gets a
    # distinct key, so the baseline ratchet cannot be widened silently
    seq: int = 0

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"[{RULES[self.rule]}] {self.message}{tag}"

    def key(self) -> str:
        """Line-number-free identity for baseline matching: findings
        survive unrelated edits above them."""
        base = f"{self.rule}::{self.path}::{self.message}"
        return base if self.seq == 0 else f"{base}::#{self.seq}"


# ---------------------------------------------------------------------------
# Per-module model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _FuncInfo:
    module: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_root: bool = False
    reachable: bool = False
    calls: set = dataclasses.field(default_factory=set)  # (module, name)
    callable_args: set = dataclasses.field(default_factory=set)
    children: list = dataclasses.field(default_factory=list)


class _ModuleModel:
    def __init__(self, modname: str, path: str, tree: ast.Module,
                 source: str):
        self.modname = modname
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        # alias -> dotted module name ("np" -> "numpy", "pc" -> pkg mod)
        self.mod_aliases: dict[str, str] = {}
        # name -> (module, symbol) for `from m import f`
        self.sym_imports: dict[str, tuple[str, str]] = {}
        self.mutable_globals: set[str] = set()
        self.functions: dict[str, _FuncInfo] = {}
        self.suppress_file: set[str] = set()
        self.suppress_line: dict[int, set[str] | None] = {}
        # declaration sites, in source order, for the OCT106 stale-
        # suppression audit: each entry is [line, rules|None, file_level,
        # used] and `used` flips the first time is_suppressed matches it
        self.suppress_decls: list[list] = []
        self._scan_suppressions(source)
        self._scan()

    # -- suppression comments ------------------------------------------------

    def _scan_suppressions(self, source: str) -> None:
        for i, line in _comment_lines(source):
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                rules = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
                self.suppress_file |= rules
                self.suppress_decls.append([i, rules, True, False])
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = m.group(1)
                if rules is None:
                    self.suppress_line[i] = None  # all rules
                    self.suppress_decls.append([i, None, False, False])
                else:
                    rs = {r.strip() for r in rules.split(",") if r.strip()}
                    self.suppress_line[i] = rs
                    self.suppress_decls.append([i, rs, False, False])

    def _mark_used(self, line: int | None, rule: str, file_level: bool):
        """Credit the FIRST declaration that justified this suppression
        (a redundant second declaration of the same rule stays unused
        and the audit flags it)."""
        for d in self.suppress_decls:
            if d[2] != file_level:
                continue
            if file_level:
                if d[1] is not None and rule in d[1]:
                    d[3] = True
                    return
            elif d[0] == line and (d[1] is None or rule in d[1]):
                d[3] = True
                return

    def is_suppressed(self, rule: str, line: int, def_line: int | None) -> bool:
        if rule in self.suppress_file:
            self._mark_used(None, rule, True)
            return True
        for ln in (line, def_line):
            if ln is None:
                continue
            rules = self.suppress_line.get(ln, "missing")
            if rules is None or (rules != "missing" and rule in rules):
                self._mark_used(ln, rule, False)
                return True
        return False

    def stale_suppressions(self) -> list[Finding]:
        """OCT106: declarations that suppressed nothing during this
        lint run. Called AFTER every rule has visited the module. A
        stale comment that itself lists OCT106 suppresses its own
        finding (and thereby stops being stale) — the reviewed way to
        keep a deliberately-pre-emptive suppression."""
        out = []
        for d in self.suppress_decls:
            if d[3]:
                continue
            line, rules, file_level, _ = d
            what = "all rules" if rules is None else ",".join(sorted(rules))
            kind = "disable-file" if file_level else "disable"
            sup = self.is_suppressed("OCT106", line, None)
            out.append(Finding(
                "OCT106", self.path, line, 0,
                f"`# octlint: {kind}={what}` suppresses nothing on the "
                "current tree — remove the stale comment",
                sup,
            ))
        return out

    # -- imports / globals / functions --------------------------------------

    def _resolve_relative(self, node: ast.ImportFrom) -> str:
        base = self.modname.split(".")
        if node.level:
            base = base[: len(base) - node.level]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    def _scan(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                src = (
                    self._resolve_relative(node)
                    if node.level
                    else (node.module or "")
                )
                for a in node.names:
                    name = a.asname or a.name
                    # `from jax import numpy as jnp` style: the imported
                    # symbol may itself be a module
                    self.mod_aliases[name] = f"{src}.{a.name}"
                    self.sym_imports[name] = (src, a.name)
        candidates: set[str] = set()
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                value = stmt.value
                if value is not None and _is_mutable_literal(value):
                    for t in targets:
                        if isinstance(t, ast.Name):
                            candidates.add(t.id)
        # only globals the module actually MUTATES are a capture hazard;
        # a module-level constant table that happens to be a list is not
        self.mutable_globals = candidates & _mutated_names(self.tree)
        self._collect_functions(self.tree, prefix="")

    def _collect_functions(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                info = _FuncInfo(self.modname, qn, child)
                self.functions[qn] = info
                self._collect_functions(child, prefix=f"{qn}.")
                for sub in self.functions.values():
                    if sub.qualname.startswith(f"{qn}."):
                        info.children.append(sub.qualname)
            elif isinstance(child, ast.ClassDef):
                # class bodies: collect methods but never treat them as
                # call-graph targets (attribute dispatch is unresolved)
                self._collect_functions(child, prefix=f"{prefix}{child.name}.")
            elif not isinstance(child, (ast.Lambda,)):
                self._collect_functions(child, prefix=prefix)

    def module_of_alias(self, name: str) -> str | None:
        return self.mod_aliases.get(name)


def _is_mutable_literal(node: ast.expr) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in {"dict", "list", "set"}
    return False


_MUTATOR_METHODS = {
    "append", "add", "update", "setdefault", "pop", "clear", "extend",
    "insert", "remove", "popitem", "discard",
}


def _mutated_names(tree: ast.Module) -> set[str]:
    """Names that are mutated anywhere in the module: `x[...] = v`,
    `x.append(v)`, `del x[...]`, `x |= ...`, or rebound via `global`."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AugAssign)
                else node.targets
            )
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)) and \
                        isinstance(t.value, ast.Name):
                    out.add(t.value.id)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in _MUTATOR_METHODS and \
                    isinstance(f.value, ast.Name):
                out.add(f.value.id)
        elif isinstance(node, ast.Global):
            out.update(node.names)
    return out


# ---------------------------------------------------------------------------
# Jit-root detection + call graph
# ---------------------------------------------------------------------------


def _callable_ref(node: ast.expr) -> str | tuple[str, str] | None:
    """Reference to a callable expression: a bare local name (str), an
    `alias.func` pair (tuple), or the same through functools.partial."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        chain = _attr_chain(node)
        if len(chain) == 2:
            return (chain[0], chain[1])
        return None
    if isinstance(node, ast.Call):
        f = node.func
        fname = None
        if isinstance(f, ast.Name):
            fname = f.id
        elif isinstance(f, ast.Attribute):
            fname = f.attr
        if fname == "partial" and node.args:
            return _callable_ref(node.args[0])
    return None


def _attr_chain(node: ast.expr) -> list[str]:
    """a.b.c -> ["a", "b", "c"]; [] when not a plain chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _is_jit_wrapper(node: ast.expr) -> bool:
    """jax.jit / jit / pjit / shard_map / pl.pallas_call expression?"""
    chain = _attr_chain(node)
    if not chain:
        # partial(jax.jit, ...) used as a decorator factory
        if isinstance(node, ast.Call):
            cn = _attr_chain(node.func)
            if cn and cn[-1] == "partial" and node.args:
                return _is_jit_wrapper(node.args[0])
        return False
    return chain[-1] in {"jit", "pjit", "shard_map", "pallas_call"}


class _CallCollector(ast.NodeVisitor):
    """Collects resolvable call targets + jit-wrapped callables inside
    one function body (without descending into nested defs)."""

    def __init__(self, model: _ModuleModel):
        self.model = model
        self.calls: set[tuple[str | None, str]] = set()
        self.jit_wrapped: set[str] = set()  # local callable names
        # functions passed by name as arguments (higher-order): if the
        # enclosing function traces, these are traced too (the Pallas
        # `_call(kernel, ...)` indirection pattern)
        self.callable_args: set[tuple[str | None, str]] = set()
        self._depth = 0

    def visit_FunctionDef(self, node):  # noqa: N802
        if self._depth == 0:
            self._depth += 1
            self.generic_visit(node)
            self._depth -= 1
        # nested defs handled via _FuncInfo.children

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):  # noqa: N802
        f = node.func
        if isinstance(f, ast.Name):
            self.calls.add((None, f.id))
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            mod = self.model.module_of_alias(f.value.id)
            if mod is not None:
                self.calls.add((mod, f.attr))
        for arg in node.args:
            ref = _callable_ref(arg)
            if isinstance(ref, str):
                self.callable_args.add((None, ref))
            elif ref is not None:
                mod = self.model.module_of_alias(ref[0])
                if mod is not None:
                    self.callable_args.add((mod, ref[1]))
        if _is_jit_wrapper(f):
            for arg in node.args[:1]:
                ref = _callable_ref(arg)
                if isinstance(ref, str):
                    self.jit_wrapped.add(ref)
                elif ref is not None:
                    mod = self.model.module_of_alias(ref[0])
                    if mod is not None:
                        self.calls.add((mod, ref[1]))
                        self.jit_wrapped.add(f"{ref[0]}.{ref[1]}")
        self.generic_visit(node)


class Package:
    """All modules of one package subtree, with the cross-module
    jit-reachability closure computed."""

    def __init__(self, root: str, package_name: str | None = None,
                 rel_to: str | None = None):
        self.root = root
        self.rel_to = rel_to or os.path.dirname(os.path.abspath(root))
        self.package_name = package_name or os.path.basename(
            os.path.abspath(root)
        )
        self.modules: dict[str, _ModuleModel] = {}
        self._load()
        self._mark_roots()
        self._close_reachability()

    # -- loading -------------------------------------------------------------

    def _iter_sources(self) -> Iterable[tuple[str, str]]:
        if os.path.isfile(self.root):
            modname = os.path.splitext(os.path.basename(self.root))[0]
            yield modname, self.root
            return
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, os.path.dirname(self.root))
                mod = rel[:-3].replace(os.sep, ".")
                if mod.endswith(".__init__"):
                    mod = mod[: -len(".__init__")]
                yield mod, full

    def _load(self) -> None:
        for modname, path in self._iter_sources():
            with open(path, encoding="utf-8") as f:
                source = f.read()
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError:
                continue
            rel = os.path.relpath(path, self.rel_to)
            self.modules[modname] = _ModuleModel(modname, rel, tree, source)

    # -- roots + reachability ------------------------------------------------

    def _mark_roots(self) -> None:
        for model in self.modules.values():
            for info in model.functions.values():
                for dec in info.node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _is_jit_wrapper(target) or _is_jit_wrapper(dec):
                        info.is_root = True
            # call-site wrapping: jax.jit(f), pl.pallas_call(kernel,...)
            for info in model.functions.values():
                cc = _CallCollector(model)
                cc.visit(info.node)
                info.calls = cc.calls
                info.callable_args = cc.callable_args
                for name in cc.jit_wrapped:
                    self._mark_callable(model, info, name)
            # module-level wrapping (e.g. FN = jax.jit(fn))
            cc = _CallCollector(model)
            for stmt in model.tree.body:
                if not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    cc.visit(stmt)
            for name in cc.jit_wrapped:
                self._mark_callable(model, None, name)

    def _mark_callable(self, model: _ModuleModel, info: _FuncInfo | None,
                       name: str) -> None:
        # local function in the enclosing scope chain?
        if info is not None:
            prefix = info.qualname
            while True:
                qn = f"{prefix}.{name}" if prefix else name
                if qn in model.functions:
                    model.functions[qn].is_root = True
                    return
                if "." not in prefix:
                    break
                prefix = prefix.rsplit(".", 1)[0]
        if name in model.functions:
            model.functions[name].is_root = True
            return
        if "." in name:  # "alias.func" recorded by _CallCollector
            alias, fname = name.split(".", 1)
            mod = model.module_of_alias(alias)
            target = self._lookup(mod, fname)
            if target is not None:
                target.is_root = True
            return
        if name in model.sym_imports:
            src, sym = model.sym_imports[name]
            target = self._lookup(src, sym)
            if target is not None:
                target.is_root = True

    def _lookup(self, modname: str | None, fname: str) -> _FuncInfo | None:
        if modname is None:
            return None
        model = self.modules.get(modname)
        if model is None:
            return None
        if fname in model.functions:
            return model.functions[fname]
        # re-export through the module's own symbol imports
        if fname in model.sym_imports:
            src, sym = model.sym_imports[fname]
            if src != modname:
                return self._lookup(src, sym)
        return None

    def _resolve_call(self, model: _ModuleModel, info: _FuncInfo,
                      call: tuple[str | None, str]) -> _FuncInfo | None:
        mod, name = call
        if mod is not None:
            return self._lookup(mod, name)
        # bare name: enclosing scopes, then module scope, then imports
        prefix = info.qualname
        while "." in prefix:
            prefix = prefix.rsplit(".", 1)[0]
            qn = f"{prefix}.{name}"
            if qn in model.functions:
                return model.functions[qn]
        if name in model.functions:
            return model.functions[name]
        if name in model.sym_imports:
            src, sym = model.sym_imports[name]
            return self._lookup(src, sym)
        return None

    def _close_reachability(self) -> None:
        work: list[_FuncInfo] = []
        for model in self.modules.values():
            for info in model.functions.values():
                if info.is_root:
                    info.reachable = True
                    work.append(info)
        while work:
            info = work.pop()
            model = self.modules[info.module]
            nxt: list[_FuncInfo] = []
            for qn in info.children:
                nxt.append(model.functions[qn])
            for call in info.calls | info.callable_args:
                target = self._resolve_call(model, info, call)
                if target is not None:
                    nxt.append(target)
            for t in nxt:
                if not t.reachable:
                    t.reachable = True
                    work.append(t)


# ---------------------------------------------------------------------------
# Rule visitors
# ---------------------------------------------------------------------------


class _TracedNames(ast.NodeVisitor):
    """Local flow-insensitive dataflow: names assigned from jax/jnp/lax
    expressions, or from expressions that reference an already-traced
    name (iterated to a fixed point). Reads through static attributes
    (`x.shape`, `x.dtype`, ...) do not taint."""

    def __init__(self, model: _ModuleModel, params_traced: set[str]):
        self.model = model
        self.traced: set[str] = set(params_traced)
        self.changed = False

    def _expr_traced(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if len(chain) >= 2:
                mod = self.model.module_of_alias(chain[0])
                if mod in _TRACED_MODULES:
                    return True
        return any(
            self._expr_traced(c)
            for c in ast.iter_child_nodes(node)
            if isinstance(c, ast.expr)
        )

    def visit_Assign(self, node):  # noqa: N802
        if self._expr_traced(node.value):
            for t in node.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name) and \
                            sub.id not in self.traced:
                        self.traced.add(sub.id)
                        self.changed = True
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # noqa: N802
        pass  # nested defs analyzed on their own

    visit_AsyncFunctionDef = visit_FunctionDef


def _check_function(pkg: Package, model: _ModuleModel,
                    info: _FuncInfo) -> list[Finding]:
    out: list[Finding] = []
    node = info.node
    def_line = node.lineno

    def emit(rule: str, where: ast.AST, message: str) -> None:
        sup = model.is_suppressed(rule, where.lineno, def_line)
        out.append(Finding(rule, model.path, where.lineno,
                           getattr(where, "col_offset", 0), message, sup))

    # in a jit ROOT the parameters are the traced operands; in a merely
    # reachable helper they may be host values, so only roots taint them
    params_traced: set[str] = set()
    if info.is_root and isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef)
    ):
        a = node.args
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            params_traced.add(p.arg)
        for va in (a.vararg, a.kwarg):
            if va is not None:
                params_traced.add(va.arg)
    tn = _TracedNames(model, params_traced)
    for _ in range(4):  # fixed point over chained assignments
        tn.changed = False
        for stmt in node.body:
            tn.visit(stmt)
        if not tn.changed:
            break

    def expr_traced(e: ast.expr) -> bool:
        return tn._expr_traced(e)

    # literals wrapped in an explicit dtype constructor are deliberate
    dtype_wrapped: set[int] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            chain = _attr_chain(sub.func)
            if chain and chain[-1] in _DTYPE_CTORS:
                for arg in sub.args:
                    if isinstance(arg, ast.Constant):
                        dtype_wrapped.add(id(arg))

    def own_nodes(n: ast.AST):
        """Walk this function's own body, excluding nested defs (they
        are separate _FuncInfos and inherit reachability)."""
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not n:
                continue
            yield from own_nodes(child)

    for sub in own_nodes(node):
        # OCT101 — host syncs
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute):
                if f.attr in _SYNC_METHODS:
                    emit("OCT101", sub,
                         f"host-sync `.{f.attr}()` in jit-reachable "
                         f"`{info.qualname}`")
                elif isinstance(f.value, ast.Name):
                    mod = model.module_of_alias(f.value.id)
                    if mod == "numpy" and f.attr in _SYNC_NUMPY_FNS \
                            and sub.args and expr_traced(sub.args[0]):
                        emit("OCT101", sub,
                             f"`{f.value.id}.{f.attr}` on a traced value "
                             f"in jit-reachable `{info.qualname}` forces "
                             "a device->host transfer")
                    elif mod == "jax" and f.attr in _SYNC_JAX_FNS:
                        emit("OCT101", sub,
                             f"`jax.{f.attr}` inside jit-reachable "
                             f"`{info.qualname}`")
            elif isinstance(f, ast.Name) and f.id in _SYNC_BUILTINS:
                if sub.args and expr_traced(sub.args[0]):
                    emit("OCT101", sub,
                         f"`{f.id}()` on a traced value in "
                         f"`{info.qualname}` concretizes the tracer")
        # OCT102 — Python control flow on traced values. `x is None`
        # sentinel checks are static at trace time (a tracer is never
        # None), so identity comparisons are exempt.
        if isinstance(sub, (ast.If, ast.While)):
            is_sentinel = isinstance(sub.test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in sub.test.ops
            )
            if not is_sentinel and expr_traced(sub.test):
                kind = "if" if isinstance(sub, ast.If) else "while"
                emit("OCT102", sub,
                     f"Python `{kind}` on a traced value in "
                     f"`{info.qualname}`")
        # OCT103 — mutable-global reads
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                and sub.id in model.mutable_globals:
            emit("OCT103", sub,
                 f"jit-reachable `{info.qualname}` reads mutable "
                 f"module global `{sub.id}`")
        # OCT104 — wide int literals
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int) \
                and not isinstance(sub.value, bool) \
                and id(sub) not in dtype_wrapped:
            if not (-(2 ** 31) <= sub.value < 2 ** 31):
                emit("OCT104", sub,
                     f"int literal {sub.value} exceeds int32 in "
                     f"jit-reachable `{info.qualname}` (widens the lane "
                     "to 64-bit weak type)")
    return out


def _check_async_locks(model: _ModuleModel, info: _FuncInfo) -> list[Finding]:
    """OCT105: linear statement-order scan of an `async def` body; a
    held-lock set is updated on acquire/release calls, and every await
    with a non-empty set is a finding."""
    node = info.node
    if not isinstance(node, ast.AsyncFunctionDef):
        return []
    out: list[Finding] = []
    held: list[str] = []

    def describe(call: ast.Call) -> str | None:
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            return f"{f.value.id}.{f.attr}"
        return None

    class V(ast.NodeVisitor):
        def visit_Call(self, sub):  # noqa: N802
            f = sub.func
            if isinstance(f, ast.Attribute):
                if f.attr in _LOCK_ACQUIRE:
                    held.append(describe(sub) or f.attr)
                elif f.attr in _LOCK_RELEASE and held:
                    held.pop()
            self.generic_visit(sub)

        def visit_Await(self, sub):  # noqa: N802
            # the awaited expression may itself BE the acquire —
            # process the inner call first, then judge the await
            inner = sub.value
            acquiring = (
                isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Attribute)
                and inner.func.attr in _LOCK_ACQUIRE
            )
            if held and not acquiring:
                sup = model.is_suppressed("OCT105", sub.lineno, node.lineno)
                out.append(Finding(
                    "OCT105", model.path, sub.lineno, sub.col_offset,
                    f"`await` while holding {held[-1]} in "
                    f"`{info.qualname}` can starve the runtime",
                    sup,
                ))
            self.generic_visit(sub)

        def visit_AsyncFunctionDef(self, sub):  # noqa: N802
            if sub is node:
                self.generic_visit(sub)

        def visit_FunctionDef(self, sub):  # noqa: N802
            pass

    V().visit(node)
    return out


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def lint_paths(paths: list[str], rel_to: str | None = None) -> list[Finding]:
    """Lint every package / file in `paths`; returns ALL findings
    (suppressed ones carry suppressed=True)."""
    findings: list[Finding] = []
    for path in paths:
        pkg = Package(path, rel_to=rel_to)
        for model in pkg.modules.values():
            for info in model.functions.values():
                if info.reachable:
                    findings.extend(_check_function(pkg, model, info))
                findings.extend(_check_async_locks(model, info))
            # OCT106 runs last: it audits which declarations the rules
            # above actually consumed
            findings.extend(model.stale_suppressions())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # disambiguate duplicate keys in source order (see Finding.seq)
    counts: dict[str, int] = {}
    out: list[Finding] = []
    for f in findings:
        base = f"{f.rule}::{f.path}::{f.message}"
        n = counts.get(base, 0)
        counts[base] = n + 1
        out.append(dataclasses.replace(f, seq=n) if n else f)
    return out


def lint_source(source: str, name: str = "<memory>") -> list[Finding]:
    """Lint a single source string (fixture tests)."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, f"{name}.py")
        with open(p, "w", encoding="utf-8") as f:
            f.write(source)
        found = lint_paths([p], rel_to=d)
    return [dataclasses.replace(f, path=name) for f in found]
