"""octwall — Pass 4: static compile-cost certification of the crypto
jaxprs, calibrated by the flight recorder.

BENCH r02-r05 banked no device number because first-execute compile
walls (~410 s on the composed programs) ate the bench budget — and the
repo already proved compile time is *steerable* from jaxpr structure
(PR 1: fencing the ladders cut the composed graph 355k -> 171k eqns,
chain depth 900 -> 114). PR 6's warmup recorder measures per-stage
first-execute walls after the fact; this pass predicts them BEFORE
anything compiles, so a doomed dispatch is refused pre-flight instead
of discovered at the wall.

Three cooperating pieces:

  features  `extract_features` walks a traced jaxpr (reusing the
            Pass-2 trace cache — no XLA compile, no device) and
            extracts the structural features PR 1 showed drive the
            algebraic simplifier's 50-run-cap blowup: total/maximum
            per-computation equation counts, unfenced multiply-chain
            depth, fence (scan/while/pjit) counts and body sizes,
            fan-out, remat width, dot/gather counts, constant bytes.
            A `feature_hash` (blake2s of the canonical feature vector)
            identifies the exact graph structure, so a measured wall
            recorded by obs/warmup.py joins its static features
            EXACTLY — a stale measurement from an older code state
            simply fails to join.

  model     a small feature-weighted model: predicted cold-compile
            wall = exp(b0 + sum b_i * log1p(feature_i)), coefficients
            constrained NON-NEGATIVE (more structure can never predict
            a cheaper compile — the ratchet depends on monotonicity).
            Fitted by `scripts/fit_costmodel.py` from the per-stage
            first-execute walls the warmup recorder banks into BENCH
            round JSONs plus local calibration runs; pinned with the
            per-graph features/predictions in analysis/costmodel.json.

  consumers `check_compile_wall` ratchets each registered graph's
            prediction against budgets.json's "compile_wall" section
            (scripts/lint.py exit 5, the `cost` CLI subcommand);
            `advisories` flags monolith computations and unfenced
            chains over budget, naming the source fence to split;
            `preflight` is the bench attempt gate — a COLD monolithic
            program whose predicted wall exceeds the remaining wall
            budget (bench.py exports OCT_WALL_DEADLINE to the device
            child) is refused, the refusal recorded in the warmup
            report, and protocol/batch falls back to the per-stage
            split path whose programs are individually smaller.

What the model does NOT predict: Pallas/Mosaic lowering walls (kernel
bodies are opaque to the jaxpr), device-side autotuning, persistent-
cache deserialization time, or the XLA version drift between the
calibration backend and the deployment runtime — predictions are a
structural estimate for the admission gate and the ratchet, not a
profiler (see analysis/README.md, Pass 4)."""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import time

from . import graphs

_COST_PATH = os.path.join(os.path.dirname(__file__), "costmodel.json")

# primitives whose operand gather/scatter indexing the simplifier's
# rewrite families interact with (cheap to count, cheap to fit)
_GATHER_PRIMS = {"gather", "dynamic_slice", "scatter", "scatter-add",
                 "dynamic_update_slice"}

# canonical feature order — the hash and the model read this tuple, so
# APPEND new features, never reorder (a reorder would silently unjoin
# every banked calibration row)
FEATURE_NAMES = (
    "eqns", "computations", "max_comp_eqns", "mul_chain_depth",
    "mul_count", "op_fanout", "remat_width", "fence_count",
    "max_body_eqns", "dot_count", "gather_count", "const_bytes",
)

# the subset the fitted model consumes (the rest are extracted for the
# advisories and for future re-fits without re-measuring). Chosen by
# subset search over the calibration rows: graph SIZE (eqns) carries
# most of the signal, with per-op premiums for the expensive families
# (multiplies feeding the simplifier's rewrite loop, MXU dots,
# fence subcomputations each compiled separately, gathers).
MODEL_FEATURES = (
    "eqns", "mul_count", "dot_count", "fence_count", "gather_count",
)

# a fitted prediction never goes below this (dispatch + tiny-program
# compile floor) — keeps log-space extrapolation honest on small graphs
MIN_PREDICTED_S = 0.05

_DEADLINE_ENV = "OCT_WALL_DEADLINE"
_GATE_ENV = "OCT_COMPILE_GATE"
# seconds a first-execute must fit under the deadline WITH room to
# spare for the replay itself
PREFLIGHT_MARGIN_S = 30.0


def _src_of(eqn) -> str:
    from .absint import _src_of as src

    return src(eqn)


@dataclasses.dataclass
class CostFeatures:
    """Compile-cost features of one traced graph (one recursive walk,
    same fence/multiply vocabulary as the Pass-2 analyzer)."""

    name: str
    eqns: int = 0
    computations: int = 0
    max_comp_eqns: int = 0
    mul_chain_depth: int = 0
    mul_count: int = 0
    op_fanout: int = 0
    remat_width: int = 0
    fence_count: int = 0
    max_body_eqns: int = 0
    dot_count: int = 0
    gather_count: int = 0
    const_bytes: int = 0
    # pathology provenance (advisories name these)
    chain_src: str = ""
    monolith_src: str = "<top-level>"

    def to_dict(self) -> dict:
        return {k: int(getattr(self, k)) for k in FEATURE_NAMES}

    def hash(self) -> str:
        return feature_hash(self.to_dict())


def feature_hash(features: dict) -> str:
    """Stable digest of the canonical feature vector: the join key
    between a warmup-report stage note and the static features it was
    measured against."""
    vec = ",".join(f"{k}={int(features.get(k, 0))}" for k in FEATURE_NAMES)
    return hashlib.blake2s(vec.encode(), digest_size=8).hexdigest()


def _sub_closed(eqn):
    """(jaxpr, consts) pairs for every sub-computation of a fence eqn
    (graphs._sub_jaxprs strips ClosedJaxpr consts; the cost walk wants
    them for const_bytes)."""
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for x in vs:
            consts = ()
            while hasattr(x, "jaxpr"):
                consts = getattr(x, "consts", ()) or consts
                x = x.jaxpr
            if hasattr(x, "eqns"):
                yield x, consts


def _const_nbytes(consts) -> int:
    import numpy as np

    total = 0
    for c in consts:
        try:
            total += int(np.asarray(c).nbytes)
        except Exception:
            pass
    return total


def _walk(jaxpr, f: CostFeatures, provenance: str) -> None:
    """One computation: mirrors graphs._analyze (fences separate
    computations, multiply chains reset at fences) plus the cost-only
    features and the source attribution the advisories need."""
    depth: dict[int, int] = {}
    uses: dict[int, int] = {}
    last_use: dict[int, int] = {}
    f.computations += 1
    comp_eqns = 0
    for i, eqn in enumerate(jaxpr.eqns):
        comp_eqns += 1
        f.eqns += 1
        prim = eqn.primitive.name
        is_mul = prim in graphs._MUL_PRIMS
        if is_mul:
            f.mul_count += 1
        if prim == "dot_general":
            f.dot_count += 1
        if prim in _GATHER_PRIMS:
            f.gather_count += 1
        in_depth = 0
        for v in eqn.invars:
            if hasattr(v, "val"):
                continue
            uses[id(v)] = uses.get(id(v), 0) + 1
            last_use[id(v)] = i
            in_depth = max(in_depth, depth.get(id(v), 0))
        if prim in graphs._FENCE_PRIMS:
            f.fence_count += 1
            before = f.eqns
            for sub, consts in _sub_closed(eqn):
                f.const_bytes += _const_nbytes(consts)
                _walk(sub, f, f"{prim}@{_src_of(eqn)}")
            f.max_body_eqns = max(f.max_body_eqns, f.eqns - before)
            out_depth = 0  # separate computation: the chain is fenced
        else:
            out_depth = in_depth + (1 if is_mul else 0)
            if out_depth > f.mul_chain_depth:
                f.mul_chain_depth = out_depth
                f.chain_src = _src_of(eqn)
        for v in eqn.outvars:
            depth[id(v)] = out_depth
    for v in jaxpr.outvars:
        if not hasattr(v, "val"):
            uses[id(v)] = uses.get(id(v), 0) + 1
            last_use[id(v)] = len(jaxpr.eqns)
    if uses:
        f.op_fanout = max(f.op_fanout, max(uses.values()))
    # live-interval sweep (remat pressure), same as Pass 2
    born: dict[int, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            born[id(v)] = i
    events: list[tuple[int, int]] = []
    for vid, b in born.items():
        events.append((b, 1))
        events.append((last_use.get(vid, b) + 1, -1))
    live = peak = 0
    for _, delta in sorted(events):
        live += delta
        peak = max(peak, live)
    f.remat_width = max(f.remat_width, peak)
    if comp_eqns > f.max_comp_eqns:
        f.max_comp_eqns = comp_eqns
        f.monolith_src = provenance


def extract_features(closed_jaxpr, name: str = "graph") -> CostFeatures:
    """Walk one traced jaxpr (no compile) into its cost features."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    f = CostFeatures(name=name)
    f.const_bytes += _const_nbytes(getattr(closed_jaxpr, "consts", ()))
    _walk(jaxpr, f, "<top-level>")
    return f


def graph_features(name: str, t: int | None = None) -> CostFeatures:
    """Features of a registered graph via the shared Pass-2 trace cache
    (one trace serves budgets, certification, point-ops AND cost)."""
    return extract_features(graphs.trace_graph(name, t), name)


# ---------------------------------------------------------------------------
# The fitted model (analysis/costmodel.json)
# ---------------------------------------------------------------------------


def load_cost(path: str | None = None) -> dict:
    with open(path or _COST_PATH, encoding="utf-8") as fh:
        return json.load(fh)


_CACHED: dict | None = None


def _cached_cost() -> dict | None:
    """costmodel.json, read once per process (the runtime consumers —
    stage-note hashes, the preflight gate — must stay dict-lookup
    cheap). Missing/invalid file -> None, never an exception."""
    global _CACHED
    if _CACHED is None:
        try:
            _CACHED = load_cost()
        except (OSError, json.JSONDecodeError, ValueError):
            _CACHED = {}
    return _CACHED or None


def predict(features: CostFeatures | dict,
            model: dict | None = None) -> float | None:
    """Predicted cold-compile wall (seconds) for a feature vector;
    None when no fitted model is available."""
    if model is None:
        cost = _cached_cost()
        model = (cost or {}).get("model")
    if not model or "coeffs" not in model:
        return None
    feats = features.to_dict() if isinstance(features, CostFeatures) \
        else features
    z = float(model.get("intercept", 0.0))
    for k, c in model["coeffs"].items():
        z += float(c) * math.log1p(max(0, int(feats.get(k, 0))))
    return max(MIN_PREDICTED_S, math.exp(z))


def fit_model(rows: list[tuple[dict, float]], ridge: float = 1e-2,
              backend: str = "") -> dict:
    """Non-negative log-log least squares over MODEL_FEATURES.
    `rows` = [(features_dict, measured_first_execute_s), ...].
    Coefficients are clipped to >= 0 and re-solved on the surviving
    support (more structure must never predict a cheaper compile)."""
    import numpy as np

    if len(rows) < 3:
        raise ValueError(f"need >= 3 calibration rows, got {len(rows)}")
    names = list(MODEL_FEATURES)
    X = np.array([
        [math.log1p(max(0, int(f.get(k, 0)))) for k in names]
        for f, _ in rows
    ])
    y = np.array([math.log(max(1e-3, float(w))) for _, w in rows])
    active = list(range(len(names)))
    for _ in range(len(names) + 1):
        A = np.hstack([np.ones((len(rows), 1)), X[:, active]])
        # ridge keeps the collinear size features stable on small
        # calibration sets; the intercept is not penalized
        reg = np.eye(A.shape[1]) * ridge
        reg[0, 0] = 0.0
        beta = np.linalg.solve(A.T @ A + reg, A.T @ y)
        neg = [active[j] for j in range(len(active)) if beta[1 + j] < 0]
        if not neg:
            break
        active = [j for j in active if j not in neg]
        if not active:
            beta = np.array([float(np.mean(y))])
            break
    coeffs = {names[j]: 0.0 for j in range(len(names))}
    for pos, j in enumerate(active):
        coeffs[names[j]] = round(float(beta[1 + pos]), 6)
    return {
        "intercept": round(float(beta[0]), 6),
        "coeffs": coeffs,
        "backend": backend,
        "rows": len(rows),
    }


def pin_payload(features: list[CostFeatures],
                model: dict | None) -> dict:
    """The costmodel.json "graphs" section: per graph the feature
    vector, its hash (the calibration join key) and the model's
    prediction — sorted-keys stable for CI diffing."""
    out: dict = {}
    for f in features:
        pred = predict(f, model) if model else None
        out[f.name] = {
            "features": f.to_dict(),
            "feature_hash": f.hash(),
            "predicted_s": None if pred is None else round(pred, 1),
        }
    return out


def write_cost(graphs_section: dict | None = None,
               model: dict | None = None,
               calibration: list | None = None,
               path: str | None = None) -> dict:
    """Rewrite costmodel.json, preserving whichever sections are not
    being replaced (lint --update-costs refreshes `graphs`;
    fit_costmodel refreshes `model` + `calibration`)."""
    global _CACHED
    path = path or _COST_PATH
    try:
        payload = load_cost(path)
    except (OSError, json.JSONDecodeError, ValueError):
        payload = {}
    payload["comment"] = (
        "octwall compile-cost model (analysis/costmodel.py). `model` = "
        "non-negative log-log coefficients fitted by "
        "scripts/fit_costmodel.py from warmup-recorder first-execute "
        "walls; `graphs` = per-graph feature vectors + hashes (the "
        "calibration join keys, regenerated by scripts/lint.py "
        "--update-costs) + predicted cold-compile walls; `calibration` "
        "= the measured rows the fit used. budgets.json's compile_wall "
        "section ratchets the predictions (lint exit 5)."
    )
    if model is not None:
        now = time.time()
        model = dict(model)
        model.setdefault("fitted_at", time.strftime(
            "%Y-%m-%d", time.gmtime(now)))
        payload["model"] = model
    if calibration is not None:
        payload["calibration"] = calibration
    if graphs_section is not None:
        payload["graphs"] = graphs_section
    elif model is not None and "graphs" in payload:
        # a re-fit invalidates every pinned prediction: recompute from
        # the STORED features (no re-tracing)
        for name, pin in payload["graphs"].items():
            pred = predict(pin["features"], payload["model"])
            pin["predicted_s"] = None if pred is None else round(pred, 1)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    _CACHED = None
    return payload


def pinned(name: str) -> dict | None:
    """The costmodel.json pin for one graph (features/hash/predicted),
    or None."""
    cost = _cached_cost()
    return (cost or {}).get("graphs", {}).get(name)


def predicted_wall(name: str) -> float | None:
    """Pinned predicted cold-compile wall for a registered graph —
    a dict lookup, NO tracing (safe on every hot path)."""
    pin = pinned(name)
    if not pin:
        return None
    v = pin.get("predicted_s")
    return None if v is None else float(v)


# ---------------------------------------------------------------------------
# Stage-name resolution (the warmup recorder's vocabulary)
# ---------------------------------------------------------------------------

# dispatch stage name (ops/pk/kernels._stage_call, protocol/batch
# _warm_timed) -> the registered graph that is its closest structural
# twin. The per-stage pk jits wrap exactly the *_core programs plus
# relayout glue; the packed/fused monoliths map to the composed
# registry graphs. `unpack_<digest>` stage names (layout-keyed) all
# resolve to packed_unpack.
STAGE_GRAPHS: dict[str, str] = {
    "ed": "ed_core",
    "kes": "kes_core",
    "vrf": "vrf_core",
    "vrf_bc": "vrf_bc_core",
    "finish": "finish_core",
    "relayout": "packed_unpack",
    "relayout_bc": "packed_unpack",
    "unpack": "packed_unpack",
    "reduce": "verdict_reduce",
    "reduce_noscan": "verdict_reduce",
    "agg-packed": "aggregate_core",
    "agg-vrf": "aggregate_vrf_core",
    "xla-packed": "verify_praos_core_bc",
    "xla-fused": "verify_praos_core",
    "xla-fused-bc": "verify_praos_core_bc",
    "msm": "msm",
}


def stage_graph(stage: str) -> str | None:
    """Registered-graph twin of a warmup stage label (strips the
    `@b<bucket>`, `:<lanes>l` and `:<layout>` qualifiers). The
    xla-packed label embeds the staged proof length (`:p80` draft-03 /
    `:p128` batch-compatible — protocol/batch._jitted_packed_xla),
    which selects between the two composed twins."""
    base = stage.split("@", 1)[0].split(":", 1)[0]
    if base.startswith("unpack_"):
        base = "unpack"
    if base == "xla-packed":
        return ("verify_praos_core" if ":p80" in stage
                else "verify_praos_core_bc")
    return STAGE_GRAPHS.get(base)


# ---------------------------------------------------------------------------
# Warm-while-serving compile ladder (protocol/batch.WarmLadder)
# ---------------------------------------------------------------------------

# the lane rungs the ladder may start a cold replay at while the
# production-bucket programs compile in a background thread. Every rung
# program is PINNED in costmodel.json (`<graph>@<rung>` entries, written
# by scripts/lint.py --update-costs) so lint exit 5 fences each one: on
# the current kernels the composed graphs are lane-INVARIANT (the
# fenced MSM chunk scans keep eqn counts flat in N — verified by the
# identical feature hashes), which means a rung compile costs what the
# production compile costs and the ladder's win is OVERLAP (replay
# serves on the small, individually-cheap split-stage programs while
# the monolith compiles in the background), not a cheaper rung compile.
# If a future kernel change makes the structure lane-sensitive, these
# pins are where it shows up — and choose_rung starts discriminating.
LADDER_RUNGS = (1024, 2048)
LADDER_GRAPHS = ("aggregate_core", "aggregate_vrf_core",
                 "verify_praos_core_bc")


def ladder_pin_name(graph: str, lanes: int) -> str:
    return f"{graph}@{lanes}"


def ladder_pins() -> list[tuple[str, str, int]]:
    """[(pin_name, base_graph, lanes)] for every rung program the
    ladder may compile — the lint cost pass extracts features for each
    and ratchets them exactly like the registry graphs (compile_wall
    ceilings + pin freshness; they carry no device_resources pins)."""
    return [
        (ladder_pin_name(g, r), g, r)
        for g in LADDER_GRAPHS for r in LADDER_RUNGS
    ]


def stage_pin_graph(stage: str, lanes: int | None = None) -> str | None:
    """Like stage_graph, but resolves to the rung pin when the dispatch
    runs at a ladder rung lane count and that rung is pinned — so the
    pre-flight gate prices a rung window by its own pin instead of the
    production graph's."""
    g = stage_graph(stage)
    if g is None or lanes is None:
        return g
    pin = ladder_pin_name(g, lanes)
    return pin if pinned(pin) is not None else g


def choose_rung(graph: str, *, now: float | None = None,
                margin_s: float | None = None,
                rungs: tuple = None) -> int | None:
    """Starting rung for a cold replay, chosen against the exported
    $OCT_WALL_DEADLINE: the LARGEST pinned rung whose predicted compile
    wall fits the remaining budget with margin, else the smallest rung
    (serve on the smallest windows and let the background compile eat
    the wall). No deadline -> the largest rung (no pressure, minimize
    re-tiling overhead). None when no rungs are configured."""
    rungs = LADDER_RUNGS if rungs is None else rungs
    if not rungs:
        return None
    deadline = wall_deadline()
    if deadline is None:
        return max(rungs)
    now = time.time() if now is None else now
    margin = PREFLIGHT_MARGIN_S if margin_s is None else margin_s
    remaining = deadline - now
    best = None
    for r in sorted(rungs):
        pred = predicted_wall(ladder_pin_name(graph, r))
        if pred is None:
            # an UNPINNED rung never outranks a pinned one under a
            # deadline: its wall is unknown, and choosing it risks
            # exactly the blow-through the ladder exists to avoid
            continue
        if pred + margin <= remaining:
            best = r
    if best is not None:
        return best
    # no pinned rung fits (or none are pinned at all): serve on the
    # smallest windows and let the background compile eat the wall
    return min(rungs)


def stage_feature_hash(stage: str) -> str | None:
    """Pinned feature hash for a dispatch stage — recorded on every
    warmup stage note so fit_costmodel's calibration join is exact
    (a wall banked by an OLD bench round fails to join once the pins
    move). Dict lookups only.

    Known one-sidedness: this is the PINNED hash, not one derived from
    the dispatched program (re-tracing a 300k-eqn graph at note time is
    the cost this pass exists to avoid), so a kernel edit that outruns
    its pins would stamp new-structure walls with the old hash. The
    lint gate closes that window: `check_pins` fails CI whenever the
    freshly-extracted features drift from costmodel.json, so a bench
    round on a green tree always stamps current structure."""
    g = stage_graph(stage)
    if g is None:
        return None
    pin = pinned(g)
    return pin.get("feature_hash") if pin else None


def check_pins(features: list[CostFeatures]) -> list[str]:
    """Pin-freshness gate (scripts/lint.py, rides the cost pass): each
    graph's freshly-extracted feature hash must match its
    costmodel.json pin. A stale pin would make stage notes stamp
    measured walls with the hash of an OLD structure — exactly the
    mis-join the note-time hash cannot defend against on its own."""
    out: list[str] = []
    for f in features:
        pin = pinned(f.name)
        if pin is None:
            out.append(
                f"{f.name}: no costmodel.json pin "
                "(run scripts/lint.py --update-costs)"
            )
        elif pin.get("feature_hash") != f.hash():
            out.append(
                f"{f.name}: jaxpr features drifted from the "
                "costmodel.json pin — stage notes would stamp walls "
                "with a stale hash (run scripts/lint.py --update-costs)"
            )
    return out


# ---------------------------------------------------------------------------
# Ratchet + pathology advisories (budgets.json "compile_wall")
# ---------------------------------------------------------------------------


def check_compile_wall(features: list[CostFeatures],
                       budgets: dict | None = None) -> list[str]:
    """Fifth ratcheted metric: per-graph predicted cold-compile walls
    vs budgets.json's "compile_wall" ceilings (scripts/lint.py exit 5).
    A registered graph missing from the section is itself a violation;
    the pathology advisories ride along so a violation names WHAT to
    split, not just that the prediction grew."""
    budgets = budgets if budgets is not None else graphs.load_budgets()
    sec = budgets.get("compile_wall", {})
    per_graph = sec.get("graphs", {})
    violations: list[str] = []
    for f in features:
        cfg = per_graph.get(f.name)
        if cfg is None:
            violations.append(
                f"{f.name}: no compile_wall entry in budgets.json "
                "(run scripts/lint.py --update-costs to pin it)"
            )
            continue
        pred = predict(f)
        if pred is None:
            violations.append(
                f"{f.name}: no fitted cost model "
                "(run scripts/fit_costmodel.py)"
            )
            continue
        ceiling = float(cfg["predicted_s_max"])
        adv = advisories(f, budgets)
        if pred > ceiling:
            msg = (f"{f.name}: predicted cold-compile wall {pred:.1f}s "
                   f"exceeds budget {ceiling:g}s")
            if adv:
                msg += " — " + "; ".join(adv)
            violations.append(msg)
        else:
            # the pathology detector fires on its own: a monolith or an
            # unfenced chain over the advisory budget is a violation
            # even while the wall prediction still fits its ceiling
            violations.extend(f"{f.name}: {a}" for a in adv)
    return violations


def advisories(f: CostFeatures, budgets: dict | None = None) -> list[str]:
    """Pathology detector: monolith computations and unfenced multiply
    chains over the advisory budget, each naming the source fence to
    split (the remediation PR 1 already proved works)."""
    budgets = budgets if budgets is not None else graphs.load_budgets()
    adv = budgets.get("compile_wall", {}).get("advisory", {})
    out: list[str] = []
    monolith = adv.get("monolith_eqns")
    if monolith and f.max_comp_eqns > int(monolith):
        out.append(
            f"monolith computation of {f.max_comp_eqns} eqns "
            f"({f.monolith_src}) exceeds the {monolith}-eqn advisory: "
            "split it behind a fori_loop/scan fence"
        )
    chain = adv.get("unfenced_chain")
    if chain and f.mul_chain_depth > int(chain):
        out.append(
            f"unfenced multiply chain of depth {f.mul_chain_depth} "
            f"(deepest at {f.chain_src}) exceeds the {chain}-deep "
            "advisory: fence the chain (fori_loop/scan) before the "
            "algebraic simplifier chews on it"
        )
    return out


# ---------------------------------------------------------------------------
# Pre-flight admission gate (the bench attempt gate)
# ---------------------------------------------------------------------------


def wall_deadline() -> float | None:
    """Absolute wall deadline (epoch seconds) exported by bench.py to
    its device child as $OCT_WALL_DEADLINE; None = no budget set (the
    gate admits everything)."""
    v = os.environ.get(_DEADLINE_ENV)
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def preflight(stage: str, graph: str | None = None, *,
              now: float | None = None,
              margin_s: float | None = None,
              action: str = "stage-split-fallback",
              fallback_graph: str | None = None,
              lanes: int | None = None) -> bool:
    """Admission gate for a COLD program's first execute: True = go.

    Refuses when a wall deadline is set, the stage has not yet recorded
    a first execute (so its compile is still owed), and the pinned
    predicted cold-compile wall does not fit the remaining budget with
    `margin_s` to spare. A refusal is recorded in the warmup report
    (the round JSON banks the decision either way) and the caller takes
    `action` — the fallback path it will dispatch instead.

    `fallback_graph` names the registered twin of that fallback when it
    is itself ONE monolithic program (the per-lane xla-packed twin): a
    refusal only helps if the fallback is predicted CHEAPER, so the
    gate admits rather than trade one doomed compile for another. When
    the fallback is the per-stage split path (fallback_graph=None) the
    refusal always stands — split programs are individually small and
    the persistent cache banks each one across retries. No prediction
    or no deadline -> admit: the gate never blocks on ignorance."""
    if os.environ.get(_GATE_ENV, "1") == "0":
        return True
    deadline = wall_deadline()
    if deadline is None:
        return True
    from ..obs.warmup import WARMUP

    if stage in WARMUP.stages:
        return True  # already compiled this process: warm dispatch
    g = graph if graph is not None else stage_pin_graph(stage, lanes)
    pred = predicted_wall(g) if g else None
    if pred is None:
        return True
    now = time.time() if now is None else now
    margin = PREFLIGHT_MARGIN_S if margin_s is None else margin_s
    remaining = deadline - now
    if pred + margin <= remaining:
        return True
    if fallback_graph is not None:
        fb = predicted_wall(fallback_graph)
        if fb is None or fb >= pred:
            return True  # the fallback is no cheaper: refusing gains nothing
    WARMUP.note_refusal(
        stage, pred, remaining, action=action,
        detail=f"graph={g} margin={margin:g}s",
    )
    return False
