"""Abstract domains for the octrange jaxpr interpreter (analysis/absint.py).

Two pluggable domains:

  Interval  — value bounds as exact Python ints (arbitrary precision,
              so 20 * B_MAX^2-style products never lose bits), at
              PER-ROW granularity along the limb axis: an abstract
              value is one (lo, hi) covering the whole tensor, a `Rows`
              tuple with one (lo, hi) per index along axis 0 (the
              limb-FIRST ops/pk convention), or a `LastRows` tuple per
              index along the MINOR axis (the XLA-twin ops/field.py
              [..., 20] convention).  The limb kernels' safety story is
              inherently per-row — `mul`'s rows 39-40 hold only carry
              residues, SUBC's top limb is 12287 while the others reach
              2^15.5, and the FOLD/FOLD^2 wraps multiply exactly those
              rows — so a whole-tensor bound provably cannot certify
              them (it flags the `top * FOLD^2` fold at limbs.py:183 /
              field.py:166 that is in fact bounded by ~21 * FOLD^2).
              The interpreter checks every SIGNED integer eqn against
              its dtype range; UNSIGNED arithmetic wraps to the full
              dtype range silently (two's-complement wrap is defined
              XLA semantics and the SHA-512/Blake2b lanes rely on it),
              and bitwise ops never overflow by construction.
  Taint     — a frozenset of `level:label` marks with two levels:
              `wire`  — untrusted but PUBLIC wire data (signatures,
                        keys, proofs: everything a verifier sees is
                        public, so wire taint may steer memory access),
              `secret`— sign-path secrets (scalars, nonces) that must
                        never reach control flow or an access pattern.

Widening (for scan/while fixpoints) jumps each growing bound to the
next rung of a power-ladder so the fixpoint terminates in a handful of
iterations; _WIDEN_TOP is the ladder's top and doubles as the domain's
"unbounded" sentinel (any bound at or past it means the interpreter
could not prove a finite bound).
"""

from __future__ import annotations

from typing import FrozenSet, Tuple

Interval = Tuple[int, int]  # (lo, hi), inclusive, exact Python ints
Taint = FrozenSet[str]  # {"secret:a", "wire:ed_s", ...}

# the widening ladder top: far above any real 64-bit-dtype range, so a
# bound that climbs here is genuinely unprovable, not merely large
_WIDEN_TOP = 1 << 200

# rungs chosen around the representation constants this repo actually
# uses (13-bit limbs, the B_MAX=9500 nearly-normalized bound, bytes,
# 2^16 packing, u32/u64 hash words) so the first widening usually lands
# exactly on the invariant bound.  9500 is load-bearing: a field-element
# loop carry that widened past it to 2^14 would make the very next
# `mul` bound 20 * (2^14)^2 > 2^31 and the fixpoint could never prove
# the B_MAX invariant the kernels actually maintain.
_LADDER = [
    0, 1, 2, 255, 256, 8191, 8192, 9500, (1 << 14), (1 << 16), (1 << 17),
    (1 << 20), (1 << 26), (1 << 31) - 1, (1 << 32) - 1, (1 << 40),
    (1 << 63) - 1, (1 << 64) - 1, (1 << 80), (1 << 128), _WIDEN_TOP,
]

NO_TAINT: Taint = frozenset()


class Rows(tuple):
    """Per-row (axis-0) intervals: a tuple of (lo, hi) pairs, one per
    index along the tensor's leading axis. Always build through
    `rows()` so an all-equal tuple canonicalizes to a plain uniform
    interval — canonical forms make fixpoint equality checks and memo
    keys stable. This is the limb-first (ops/pk) convention: limbs
    occupy axis 0, lanes the tail."""

    __slots__ = ()


class LastRows(tuple):
    """Per-row intervals along the LAST axis — the XLA-twin convention
    (ops/field.py, ops/bigint.py: shape [..., 20] with limbs minor).
    Same canonical forms as Rows; build through `last_rows()`. A value
    is never both: mixing conventions in one op collapses the less
    structured side (sound, just less precise)."""

    __slots__ = ()


def _canon(cls, ivs):
    ivs = tuple(ivs)
    if not ivs:
        return (0, 0)  # zero-extent axis: any bound holds vacuously
    first = ivs[0]
    for v in ivs[1:]:
        if v != first:
            return cls(ivs)
    return first


def rows(ivs) -> "Rows | Interval":
    return _canon(Rows, ivs)


def last_rows(ivs) -> "LastRows | Interval":
    return _canon(LastRows, ivs)


def rows_of(a, n: int) -> list:
    """Expand an abstract value to n per-axis-0-row intervals (LastRows
    structure lives on a different axis: collapse it)."""
    if isinstance(a, Rows):
        assert len(a) == n, (len(a), n)
        return list(a)
    return [collapse(a)] * n


def last_rows_of(a, n: int) -> list:
    if isinstance(a, LastRows):
        assert len(a) == n, (len(a), n)
        return list(a)
    return [collapse(a)] * n


def collapse(a) -> Interval:
    """Whole-tensor bound: the join of all rows."""
    if isinstance(a, (Rows, LastRows)):
        return (min(v[0] for v in a), max(v[1] for v in a))
    return a


def _zip_any(a, b, f):
    """Apply f pairwise, preserving whichever row structure the two
    sides share (same class, same length); collapse otherwise."""
    for cls, build in ((Rows, rows), (LastRows, last_rows)):
        ar, br = isinstance(a, cls), isinstance(b, cls)
        if not (ar or br):
            continue
        other = b if ar else a
        if isinstance(other, (Rows, LastRows)) and not isinstance(
            other, cls
        ):
            break  # mixed conventions: collapse both
        n = len(a) if ar else len(b)
        if ar and br and len(a) != len(b):
            break  # defensive; same-var joins match
        ex = last_rows_of if cls is LastRows else rows_of
        return build(f(x, y) for x, y in zip(ex(a, n), ex(b, n)))
    return f(collapse(a), collapse(b))


def iv_join_any(a, b):
    """Join that preserves row structure when either side has it."""
    if not isinstance(a, (Rows, LastRows)) and not isinstance(
        b, (Rows, LastRows)
    ):
        return iv_join(a, b)
    return _zip_any(a, b, iv_join)


def iv_widen_any(old, new):
    if not isinstance(old, (Rows, LastRows)) and not isinstance(
        new, (Rows, LastRows)
    ):
        return iv_widen(old, new)
    return _zip_any(old, new, iv_widen)


def iv(lo: int, hi: int) -> Interval:
    assert lo <= hi, (lo, hi)
    return (int(lo), int(hi))


def iv_const(v) -> Interval:
    v = int(v)
    return (v, v)


def iv_join(a: Interval, b: Interval) -> Interval:
    return (min(a[0], b[0]), max(a[1], b[1]))


def iv_add(a: Interval, b: Interval) -> Interval:
    return (a[0] + b[0], a[1] + b[1])


def iv_sub(a: Interval, b: Interval) -> Interval:
    return (a[0] - b[1], a[1] - b[0])


def iv_mul(a: Interval, b: Interval) -> Interval:
    cands = (a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1])
    return (min(cands), max(cands))


def iv_scale(a: Interval, n: int) -> Interval:
    """n non-negative copies summed (reduce_sum / dot contraction)."""
    assert n >= 0
    return (a[0] * n, a[1] * n)


def _tdiv(a: int, b: int) -> int:
    """C-style truncated division (XLA integer `div` semantics)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def iv_div(a: Interval, b: Interval) -> Interval:
    """Integer division; divisor interval must exclude 0 for precision,
    otherwise falls back to the symmetric magnitude bound."""
    if b[0] <= 0 <= b[1]:
        m = max(abs(a[0]), abs(a[1]))  # |a / b| <= |a| for |b| >= 1
        return (-m, m)
    cands = [_tdiv(x, y) for x in a for y in b]
    return (min(cands), max(cands))


def iv_rem(a: Interval, b: Interval) -> Interval:
    """XLA `rem` takes the dividend's sign; |rem| < |divisor|."""
    m = max(abs(b[0]), abs(b[1]))
    if m == 0:
        return (0, 0)
    lo = -(m - 1) if a[0] < 0 else 0
    hi = (m - 1) if a[1] > 0 else 0
    return (min(lo, 0), max(hi, 0))


def iv_shr(a: Interval, s: Interval) -> Interval:
    """Arithmetic shift right == floor division by a power of two.
    Python's >> on negative ints is arithmetic, matching XLA."""
    slo, shi = max(0, s[0]), min(128, max(0, s[1]))
    cands = [x >> y for x in a for y in (slo, shi)]
    return (min(cands), max(cands))


def iv_shl(a: Interval, s: Interval) -> Interval:
    slo, shi = max(0, s[0]), min(128, max(0, s[1]))
    cands = [x << y for x in a for y in (slo, shi)]
    return (min(cands), max(cands))


def _bits_cover(hi: int) -> int:
    """Smallest all-ones value covering hi (>= 0)."""
    return (1 << max(hi, 0).bit_length()) - 1


def iv_and(a: Interval, b: Interval, dtype_range: Interval) -> Interval:
    """Bitwise AND. With one non-negative operand the result is bounded
    by it (the `v & MASK` idiom works on negative v too); with both
    possibly negative fall back to the dtype range (never an overflow —
    bitwise results always fit the dtype)."""
    if a[0] >= 0 and b[0] >= 0:
        return (0, min(_bits_cover(a[1]), _bits_cover(b[1])))
    if a[0] >= 0:
        return (0, a[1])
    if b[0] >= 0:
        return (0, b[1])
    return dtype_range


def iv_or(a: Interval, b: Interval, dtype_range: Interval) -> Interval:
    if a[0] >= 0 and b[0] >= 0:
        return (max(a[0], b[0]), max(_bits_cover(a[1]), _bits_cover(b[1])))
    return dtype_range


def iv_xor(a: Interval, b: Interval, dtype_range: Interval) -> Interval:
    if a[0] >= 0 and b[0] >= 0:
        return (0, max(_bits_cover(a[1]), _bits_cover(b[1])))
    return dtype_range


def iv_widen(old: Interval, new: Interval) -> Interval:
    """Widen `old` toward `new` along the threshold ladder: any bound
    that moved jumps straight to the next rung, so a scan fixpoint
    stabilizes in O(len(ladder)) iterations worst case."""
    lo, hi = old
    if new[0] < lo:
        lo = -_WIDEN_TOP
        for r in _LADDER:
            if -r <= new[0]:
                lo = -r
                break
    if new[1] > hi:
        hi = _WIDEN_TOP
        for r in _LADDER:
            if r >= new[1]:
                hi = r
                break
    return (lo, hi)


def iv_contains(outer: Interval, inner: Interval) -> bool:
    return outer[0] <= inner[0] and inner[1] <= outer[1]


def iv_is_top(a: Interval) -> bool:
    return a[0] <= -_WIDEN_TOP or a[1] >= _WIDEN_TOP


# ---------------------------------------------------------------------------
# Taint
# ---------------------------------------------------------------------------


def taint(level: str, label: str) -> Taint:
    assert level in ("wire", "secret"), level
    return frozenset((f"{level}:{label}",))


def taint_join(*ts: Taint) -> Taint:
    out: Taint = NO_TAINT
    for t in ts:
        if t:
            out = out | t if out else t
    return out


def taint_secret(t: Taint) -> Taint:
    return frozenset(m for m in t if m.startswith("secret:"))


def taint_wire(t: Taint) -> Taint:
    return frozenset(m for m in t if m.startswith("wire:"))
