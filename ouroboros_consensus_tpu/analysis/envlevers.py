"""Env-lever drift gate: the `OCT_*` / `BENCH_*` switchboard vs its doc.

Every observability / recovery / bench lever in this tree is an
environment variable, and obs/README.md's "## Levers" table is the one
place operators are told they exist. Tables rot in both directions:

  * a new `os.environ.get("OCT_FOO")` lands without a row — the lever
    works but nobody can discover it;
  * a lever is deleted from the code but its row lingers — operators
    set it and silently get nothing.

This pass closes the loop statically. It walks the same roots as the
octsync sweep (package + scripts/ + bench.py), collects every env name
actually READ through the stdlib seams —

    os.environ.get("OCT_X") / os.environ["OCT_X"] / os.getenv("OCT_X")
    "OCT_X" in os.environ / os.environ.pop("OCT_X")
    _ENV = "OCT_X" ... os.environ.get(_ENV)      (constant-aware)

— filters to the `OCT_*` / `BENCH_*` namespaces, and diffs the set
against the backticked lever names parsed out of the README table.
Both directions are violations. Writes (`os.environ["OCT_X"] = v`,
`env={**os.environ, "OCT_X": v}`) are deliberately NOT reads: bench.py
sets many levers for its device child; setting is not a discoverable
switch, reading is.

Pure AST + text. Never imports the modules it scans, never imports jax.
"""

from __future__ import annotations

import ast
import os
import re

_PREFIX_RE = re.compile(r"^(?:OCT|BENCH)_[A-Z0-9_]+$")
_DOC_NAME_RE = re.compile(r"\b((?:OCT|BENCH)_[A-Z0-9_]+)\b")

_README_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "obs", "README.md",
)

# namespace-prefix probes (ledger env capture: k.startswith("OCT_"))
# surface as bare prefixes — they are sweeps, not individual levers
_BARE_PREFIXES = {"OCT_", "BENCH_"}


def _is_lever(name: str) -> bool:
    return bool(_PREFIX_RE.match(name)) and name not in _BARE_PREFIXES


# ---------------------------------------------------------------------------
# Source side: env names the tree actually reads
# ---------------------------------------------------------------------------


def _env_attr(node: ast.AST) -> bool:
    """`os.environ` (or a bare `environ` from `from os import environ`)."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id == "environ"


class _ReadScanner(ast.NodeVisitor):
    def __init__(self) -> None:
        self.reads: set[str] = set()
        self.consts: dict[str, str] = {}

    def _resolve(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.consts.get(node.id)
        return None

    def _note(self, node: ast.AST) -> None:
        name = self._resolve(node)
        if name and _is_lever(name):
            self.reads.add(name)

    def visit_Assign(self, node: ast.Assign) -> None:
        # constant-aware: _ENV = "OCT_X" later fed to environ.get(_ENV)
        if isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.consts[tgt.id] = node.value.value
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in ("get", "pop") and _env_attr(fn.value) \
                    and node.args:
                self._note(node.args[0])
            elif fn.attr == "getenv" and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "os" and node.args:
                self._note(node.args[0])
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # environ["OCT_X"] reads; environ["OCT_X"] = v (Store) does not
        if _env_attr(node.value) and isinstance(node.ctx, ast.Load):
            self._note(node.slice)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # "OCT_X" in os.environ — membership probe is a read
        if len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and _env_attr(node.comparators[0]):
            self._note(node.left)
        self.generic_visit(node)


def _iter_py(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def scan_reads(paths: list[str]) -> set[str]:
    """Every OCT_*/BENCH_* env name read anywhere under `paths`."""
    reads: set[str] = set()
    for path in _iter_py(paths):
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        sc = _ReadScanner()
        sc.visit(tree)
        reads |= sc.reads
    return reads


def default_roots(repo_root: str | None = None) -> list[str]:
    repo = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    pkg = os.path.join(repo, "ouroboros_consensus_tpu")
    return [pkg, os.path.join(repo, "scripts"),
            os.path.join(repo, "bench.py")]


# ---------------------------------------------------------------------------
# Doc side: lever names in the README "## Levers" table
# ---------------------------------------------------------------------------


def documented_levers(readme_path: str | None = None) -> set[str]:
    """Lever names from every backticked token in the "## Levers"
    table's first column (a row may document variant spellings —
    `OCT_LEDGER=<dir>` / `OCT_LEDGER=0` — they collapse to one name)."""
    with open(readme_path or _README_PATH, encoding="utf-8") as f:
        text = f.read()
    m = re.search(r"^## Levers\s*$", text, flags=re.MULTILINE)
    if not m:
        return set()
    section = text[m.end():]
    nxt = re.search(r"^## ", section, flags=re.MULTILINE)
    if nxt:
        section = section[:nxt.start()]
    names: set[str] = set()
    for line in section.splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        first_cell = line.split("|")[1]
        for tick in re.findall(r"`([^`]+)`", first_cell):
            names.update(
                n for n in _DOC_NAME_RE.findall(tick) if _is_lever(n)
            )
    return names


def kill_switch_levers(readme_path: str | None = None) -> set[str]:
    """The kill-switch SUBSET of the documented levers: rows whose
    first cell documents an `=0` spelling (`OCT_RECOVERY=0`,
    `OCT_FORGE_DEVICE=1` / `=0`, …). These are the levers octflow's
    FLOW305 holds to guard-a-branch integrity — value levers
    (`OCT_CHECKPOINT=<file>`) are documented but not kill-switches."""
    with open(readme_path or _README_PATH, encoding="utf-8") as f:
        text = f.read()
    m = re.search(r"^## Levers\s*$", text, flags=re.MULTILINE)
    if not m:
        return set()
    section = text[m.end():]
    nxt = re.search(r"^## ", section, flags=re.MULTILINE)
    if nxt:
        section = section[:nxt.start()]
    names: set[str] = set()
    for line in section.splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        first_cell = line.split("|")[1]
        if "=0" not in first_cell:
            continue
        for tick in re.findall(r"`([^`]+)`", first_cell):
            names.update(
                n for n in _DOC_NAME_RE.findall(tick) if _is_lever(n)
            )
    return names


# ---------------------------------------------------------------------------
# The gates
# ---------------------------------------------------------------------------


def check_kill_switches(
    readme_path: str | None = None,
    flow_baseline: dict | None = None,
) -> list[str]:
    """Cross-link the README kill-switch rows with octflow's ratcheted
    FLOW305 lever inventory (analysis/flow.json `inventory.levers`,
    entries `NAME:guards=N`). Both drift directions are violations, so
    a new `=0` row lands only together with a --update-flow re-pin
    (which re-runs the guard analysis on it) and a deleted row retires
    its inventory entry."""
    from . import flow

    rows = kill_switch_levers(readme_path)
    base = flow_baseline if flow_baseline is not None \
        else flow.load_baseline()
    entries = base.get("inventory", {}).get("levers", [])
    pinned = {e.split(":", 1)[0] for e in entries}
    out = []
    for name in sorted(rows - pinned):
        out.append(
            f"obs/README.md documents kill-switch `{name}=0` but "
            f"analysis/flow.json has no FLOW305 lever inventory entry "
            "for it — run scripts/lint.py --update-flow"
        )
    for name in sorted(pinned - rows):
        out.append(
            f"analysis/flow.json pins FLOW305 lever inventory for "
            f"`{name}` but obs/README.md no longer documents it as a "
            "kill-switch row — stale pin, run scripts/lint.py "
            "--update-flow"
        )
    return out


def check_env_levers(
    paths: list[str] | None = None,
    readme_path: str | None = None,
) -> list[str]:
    """Both drift directions as violation strings; empty = in sync."""
    reads = scan_reads(paths or default_roots())
    documented = documented_levers(readme_path)
    out = []
    for name in sorted(reads - documented):
        out.append(
            f"env lever `{name}` is read by the tree but has no row in "
            f"the obs/README.md \"## Levers\" table"
        )
    for name in sorted(documented - reads):
        out.append(
            f"obs/README.md documents env lever `{name}` but nothing "
            f"under the swept roots reads it — stale row or dead lever"
        )
    return out
