"""Pass 5 — octsync: concurrency & durability-protocol analyzer.

The four existing passes certify the *device graphs*; octsync checks
the host-side thread/lock/rename fabric those graphs run inside — the
threaded staging producer, the warm-ladder background compiler, the
heartbeat/watchdog/metrics-server threads, the flock'd AOT store and
the guard/marker tmp+rename durability protocol. Three checkers:

Lock discipline
  SYNC201 lock-order-inversion   the interprocedural lock-acquisition
                                 graph (every `with <lock>` scope plus
                                 the locks acquired by every function
                                 called inside it) contains a cycle —
                                 two code paths can take the same pair
                                 of locks in opposite orders, or a
                                 non-reentrant lock can be re-acquired
                                 under itself through a call chain.
  SYNC202 acquire-without-release
                                 a bare `.acquire()` (or an exclusive
                                 `fcntl.flock`) with no `.release()` /
                                 `LOCK_UN` anywhere in the same
                                 function. Lock-manager methods whose
                                 CONTRACT is to hold (`acquire`,
                                 `open`, `__enter__`) are exempt.
  SYNC203 unguarded-attribute    an attribute annotated
                                 `# guarded-by: <lock>` on its
                                 assignment line is touched by a
                                 thread-entry-reachable function
                                 outside a `with <lock>` scope.

Thread lifecycle
  SYNC204 unjoined-thread        a non-daemon `threading.Thread` with
                                 no `.join()` anywhere in its module —
                                 interpreter shutdown blocks on it
                                 with no shutdown path of its own.
  SYNC205 escaping-thread-exception
                                 a thread target either has no broad
                                 (bare / Exception / BaseException)
                                 handler at all — the exception kills
                                 the thread silently on stderr — or
                                 has a broad handler whose body is
                                 only `pass`/`continue`: swallowed
                                 without feeding any recorder seam.
  SYNC206 unbalanced-recorder-install
                                 a function pairs a recorder install
                                 (`install`/`maybe_arm`) with its
                                 uninstall (`uninstall`/`disarm`) but
                                 the uninstall only sits on the
                                 straight-line path — an exception
                                 between the two leaks an armed
                                 recorder (the partial-arm bug class).

Durability protocol
  SYNC207 bare-write-to-protected-path
                                 `open(path, "w")` where `path` taints
                                 from a protected root (the env levers
                                 and path-producing functions declared
                                 in analysis/sync_roots.json): every
                                 write under a guarded store path must
                                 ride write-tmp -> fsync -> rename
                                 (`write_atomic`, `fs.replace`, the
                                 guard's marker writer). A `+ ".tmp"`
                                 target is blessed only when the same
                                 function also calls a `replace`.
  SYNC208 stale-suppression      an `# octsync: disable=...` comment
                                 that suppresses nothing on the
                                 current tree (suppression rot).

Suppression grammar (same shape as octlint's):

  self._x = 0   # octsync: disable=SYNC203  <why it is safe here>
  # `# octsync: disable` (no rule list) suppresses all rules on that
  # line; the def-line suppresses the whole body;
  # `# octsync: disable-file=SYNC207` suppresses the file.

Annotation grammar:

  self.stages = {}   # guarded-by: _lock

ties the attribute to the lock *name*; holding is credited leniently
by trailing name (`with self._lock:`, `with WARMUP._lock:` both hold
`_lock`), so a shared-lock handoff (`self._lock = lock`) still counts.

octsync is a static over-approximation and proves nothing about the
C++ scanner threads, OS-level flock semantics across filesystems, or
GIL-dependent atomicity of single bytecode ops — see
analysis/README.md for the full caveat list.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable

from .astlint import _attr_chain, _comment_lines

RULES = {
    "SYNC201": "lock-order-inversion",
    "SYNC202": "acquire-without-release",
    "SYNC203": "unguarded-attribute",
    "SYNC204": "unjoined-thread",
    "SYNC205": "escaping-thread-exception",
    "SYNC206": "unbalanced-recorder-install",
    "SYNC207": "bare-write-to-protected-path",
    "SYNC208": "stale-suppression",
}

_RULE_LIST = r"[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*"
_SUPPRESS_RE = re.compile(
    rf"#\s*octsync:\s*disable(?:=({_RULE_LIST}))?(?=[\s,]|$)"
)
_SUPPRESS_FILE_RE = re.compile(
    rf"#\s*octsync:\s*disable-file=({_RULE_LIST})"
)
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_REENTRANT = {"RLock", "Condition"}  # Condition wraps an RLock by default
_INSTALLERS = {"install", "maybe_arm"}
_UNINSTALLERS = {"uninstall", "disarm"}
# lock-manager methods whose contract is to hold across return
_HOLDER_NAMES = {"acquire", "open", "__enter__", "promote_writer"}
_WRITE_MODES = ("w", "a", "x", "+")

_ROOTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "sync_roots.json")
_BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "concurrency.json")


def load_roots(path: str | None = None) -> dict:
    with open(path or _ROOTS_PATH, encoding="utf-8") as f:
        return json.load(f)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    seq: int = 0  # ordinal among same-keyed findings (see astlint)

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"[{RULES[self.rule]}] {self.message}{tag}"

    def key(self) -> str:
        base = f"{self.rule}::{self.path}::{self.message}"
        return base if self.seq == 0 else f"{base}::#{self.seq}"


# ---------------------------------------------------------------------------
# Per-module model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Func:
    module: str
    qualname: str  # "Class.method" for methods
    node: ast.AST
    cls: str | None = None
    thread_entry: bool = False
    thread_reachable: bool = False
    # locks this function acquires directly (strict identities)
    acquires: set = dataclasses.field(default_factory=set)
    # strict identities acquired here or in any resolvable callee
    trans_acquires: set = dataclasses.field(default_factory=set)
    calls: list = dataclasses.field(default_factory=list)  # resolved later
    children: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class _Guarded:
    module: str
    cls: str | None  # None = module-level name
    attr: str
    lock: str  # lock NAME from the annotation (lenient matching)
    line: int

    def ident(self) -> str:
        owner = f"{self.module}.{self.cls}" if self.cls else self.module
        return f"{owner}.{self.attr} -> {self.lock}"


class _Module:
    def __init__(self, modname: str, path: str, tree: ast.Module,
                 source: str):
        self.modname = modname
        self.path = path
        self.tree = tree
        self.mod_aliases: dict[str, str] = {}
        self.sym_imports: dict[str, tuple[str, str]] = {}
        self.functions: dict[str, _Func] = {}
        # lock identity -> ctor name ("Lock"/"RLock"/"Condition")
        self.locks: dict[str, str] = {}
        # module-level `NAME = ClassName(...)` -> (module, ClassName)
        self.instances: dict[str, tuple[str, str]] = {}
        # module-level `NAME = "literal"` (the `_ENV = "OCT_X"` idiom)
        self.str_consts: dict[str, str] = {}
        self.classes: set[str] = set()
        self.guarded: list[_Guarded] = []
        self.suppress_file: set[str] = set()
        self.suppress_line: dict[int, set[str] | None] = {}
        self.suppress_decls: list[list] = []
        self._guard_comments: dict[int, str] = {}
        # tokenized once, shared with octflow's suppression scan
        self.comment_lines: list[tuple[int, str]] = list(
            _comment_lines(source))
        self._scan_comments()
        self._scan()

    # -- comments: suppressions + guarded-by annotations --------------------

    def _scan_comments(self) -> None:
        for i, line in self.comment_lines:
            g = _GUARDED_BY_RE.search(line)
            if g:
                self._guard_comments[i] = g.group(1)
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.suppress_file |= rules
                self.suppress_decls.append([i, rules, True, False])
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = m.group(1)
                if rules is None:
                    self.suppress_line[i] = None
                    self.suppress_decls.append([i, None, False, False])
                else:
                    rs = {r.strip() for r in rules.split(",") if r.strip()}
                    self.suppress_line[i] = rs
                    self.suppress_decls.append([i, rs, False, False])

    def _mark_used(self, line: int | None, rule: str, file_level: bool):
        for d in self.suppress_decls:
            if d[2] != file_level:
                continue
            if file_level:
                if d[1] is not None and rule in d[1]:
                    d[3] = True
                    return
            elif d[0] == line and (d[1] is None or rule in d[1]):
                d[3] = True
                return

    def is_suppressed(self, rule: str, line: int,
                      def_line: int | None) -> bool:
        if rule in self.suppress_file:
            self._mark_used(None, rule, True)
            return True
        for ln in (line, def_line):
            if ln is None:
                continue
            rules = self.suppress_line.get(ln, "missing")
            if rules is None or (rules != "missing" and rule in rules):
                self._mark_used(ln, rule, False)
                return True
        return False

    def stale_suppressions(self) -> list[Finding]:
        out = []
        for d in self.suppress_decls:
            if d[3]:
                continue
            line, rules, file_level, _ = d
            what = "all rules" if rules is None else ",".join(sorted(rules))
            kind = "disable-file" if file_level else "disable"
            sup = self.is_suppressed("SYNC208", line, None)
            out.append(Finding(
                "SYNC208", self.path, line, 0,
                f"`# octsync: {kind}={what}` suppresses nothing on the "
                "current tree — remove the stale comment",
                sup,
            ))
        return out

    # -- structure -----------------------------------------------------------

    def _resolve_relative(self, node: ast.ImportFrom) -> str:
        base = self.modname.split(".")
        if node.level:
            base = base[: len(base) - node.level]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    def _scan(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_aliases[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom):
                src = (self._resolve_relative(node) if node.level
                       else (node.module or ""))
                for a in node.names:
                    name = a.asname or a.name
                    self.mod_aliases[name] = f"{src}.{a.name}"
                    self.sym_imports[name] = (src, a.name)
        for stmt in self.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self.classes.add(stmt.name)
            elif isinstance(stmt, ast.Assign):
                if isinstance(stmt.value, ast.Constant) and \
                        isinstance(stmt.value.value, str):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.str_consts[t.id] = stmt.value.value
                    continue
                if not isinstance(stmt.value, ast.Call):
                    continue
                ctor = _lock_ctor(stmt.value, self)
                cls = _instance_class(stmt.value, self)
                for t in stmt.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if ctor:
                        self.locks[f"{self.modname}.{t.id}"] = ctor
                    elif cls:
                        self.instances[t.id] = cls
        self._collect(self.tree, prefix="", cls=None)
        # guarded-by annotations attach to the assignment on their line
        self._collect_guarded()

    def _collect(self, node: ast.AST, prefix: str, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                info = _Func(self.modname, qn, child, cls=cls)
                self.functions[qn] = info
                self._collect(child, prefix=f"{qn}.", cls=cls)
                for sub in self.functions.values():
                    if sub.qualname.startswith(f"{qn}."):
                        info.children.append(sub.qualname)
                # instance locks: `self.X = threading.Lock()` in a body
                if cls is not None:
                    for sub in ast.walk(child):
                        if isinstance(sub, ast.Assign) and \
                                isinstance(sub.value, ast.Call):
                            ctor = _lock_ctor(sub.value, self)
                            if not ctor:
                                continue
                            for t in sub.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) and \
                                        t.value.id in ("self", "cls"):
                                    lid = f"{self.modname}.{cls}.{t.attr}"
                                    self.locks[lid] = ctor
            elif isinstance(child, ast.ClassDef):
                self._collect(child, prefix=f"{prefix}{child.name}.",
                              cls=child.name)
            elif not isinstance(child, ast.Lambda):
                self._collect(child, prefix=prefix, cls=cls)

    def _collect_guarded(self) -> None:
        if not self._guard_comments:
            return

        def visit(node: ast.AST, cls: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                    continue
                if isinstance(child, (ast.Assign, ast.AnnAssign)):
                    lock = self._guard_comments.get(child.lineno)
                    if lock:
                        targets = (child.targets
                                   if isinstance(child, ast.Assign)
                                   else [child.target])
                        for t in targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id in ("self", "cls"):
                                self.guarded.append(_Guarded(
                                    self.modname, cls, t.attr, lock,
                                    child.lineno))
                            elif isinstance(t, ast.Name) and cls is None:
                                self.guarded.append(_Guarded(
                                    self.modname, None, t.id, lock,
                                    child.lineno))
                visit(child, cls)

        # class context for a method body's assignments comes from the
        # enclosing ClassDef chain, which visit() threads through
        visit(self.tree, None)


def _lock_ctor(call: ast.Call, model: _Module) -> str | None:
    """threading.Lock()/RLock()/Condition() (alias-aware) -> ctor name."""
    chain = _attr_chain(call.func)
    if not chain or chain[-1] not in _LOCK_CTORS:
        return None
    if len(chain) == 1:
        src = model.sym_imports.get(chain[0], ("", ""))[0]
        return chain[0] if src == "threading" else None
    return chain[-1] if model.mod_aliases.get(chain[0]) == "threading" \
        else None


def _instance_class(call: ast.Call, model: _Module) \
        -> tuple[str, str] | None:
    """`NAME = ClassName(...)` -> (defining module, ClassName)."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in model.classes:
            return (model.modname, f.id)
        if f.id in model.sym_imports:
            return model.sym_imports[f.id]
    return None


# ---------------------------------------------------------------------------
# The package: cross-module call graph + thread reachability
# ---------------------------------------------------------------------------


class SyncPackage:
    def __init__(self, roots: list[str], rel_to: str,
                 roots_table: dict | None = None,
                 threads: bool = True):
        self.rel_to = rel_to
        self.roots_table = roots_table or load_roots()
        self.modules: dict[str, _Module] = {}
        for root in roots:
            self._load(root)
        self._resolve_all_calls()
        # octflow reuses the package for its call graph only — thread
        # reachability and transitive lock closure are octsync-specific
        if threads:
            self._mark_threads()
            self._close_acquires()

    # -- loading -------------------------------------------------------------

    def _iter_sources(self, root: str) -> Iterable[tuple[str, str]]:
        if os.path.isfile(root):
            yield os.path.splitext(os.path.basename(root))[0], root
            return
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, os.path.dirname(root))
                mod = rel[:-3].replace(os.sep, ".")
                if mod.endswith(".__init__"):
                    mod = mod[: -len(".__init__")]
                yield mod, full

    def _load(self, root: str) -> None:
        for modname, path in self._iter_sources(root):
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (SyntaxError, OSError):
                continue
            rel = os.path.relpath(path, self.rel_to)
            self.modules[modname] = _Module(modname, rel, tree, source)

    # -- call resolution -----------------------------------------------------

    def _lookup(self, modname: str | None, fname: str) -> _Func | None:
        if modname is None:
            return None
        model = self.modules.get(modname)
        if model is None:
            return None
        if fname in model.functions:
            return model.functions[fname]
        if fname in model.sym_imports:
            src, sym = model.sym_imports[fname]
            if src != modname:
                return self._lookup(src, sym)
        return None

    def _instance_of(self, model: _Module, name: str) \
            -> tuple[str, str] | None:
        """Resolve a bare name to a (module, Class) instance, through
        `from m import NAME` re-exports."""
        if name in model.instances:
            return model.instances[name]
        if name in model.sym_imports:
            src, sym = model.sym_imports[name]
            srcm = self.modules.get(src)
            if srcm is not None and src != model.modname:
                return self._instance_of(srcm, sym)
        return None

    def resolve_call(self, model: _Module, info: _Func | None,
                     func: ast.expr) -> _Func | None:
        if isinstance(func, ast.Name):
            name = func.id
            if info is not None:
                prefix = info.qualname
                while "." in prefix:
                    prefix = prefix.rsplit(".", 1)[0]
                    qn = f"{prefix}.{name}"
                    if qn in model.functions:
                        return model.functions[qn]
            if name in model.functions:
                return model.functions[name]
            if name in model.sym_imports:
                src, sym = model.sym_imports[name]
                return self._lookup(src, sym)
            return None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            base, meth = func.value.id, func.attr
            if base in ("self", "cls") and info is not None and \
                    info.cls is not None:
                return model.functions.get(f"{info.cls}.{meth}")
            inst = self._instance_of(model, base)
            if inst is not None:
                src, cls = inst
                srcm = self.modules.get(src)
                if srcm is not None:
                    return srcm.functions.get(f"{cls}.{meth}")
            mod = model.mod_aliases.get(base)
            if mod is not None:
                return self._lookup(mod, meth)
        return None

    def _resolve_all_calls(self) -> None:
        for model in self.modules.values():
            for info in model.functions.values():
                for sub in _own_nodes(info.node):
                    if isinstance(sub, ast.Call):
                        target = self.resolve_call(model, info, sub.func)
                        if target is not None and target is not info:
                            info.calls.append(target)

    # -- thread entries + reachability ---------------------------------------

    def thread_sites(self) -> list[tuple[_Module, _Func | None, ast.Call]]:
        """Every `threading.Thread(...)` construction site."""
        out = []
        for model in self.modules.values():
            seen: set[int] = set()
            for info in model.functions.values():
                for sub in _own_nodes(info.node):
                    if isinstance(sub, ast.Call) and \
                            _is_thread_ctor(sub, model):
                        out.append((model, info, sub))
                        seen.add(id(sub))
            for sub in ast.walk(model.tree):
                if isinstance(sub, ast.Call) and id(sub) not in seen \
                        and _is_thread_ctor(sub, model):
                    out.append((model, None, sub))
        return out

    def thread_target(self, model: _Module, info: _Func | None,
                      call: ast.Call) -> _Func | None:
        expr = None
        for kw in call.keywords:
            if kw.arg == "target":
                expr = kw.value
        if expr is None and call.args:
            expr = call.args[0]
        if expr is None or isinstance(expr, ast.Lambda):
            return None
        return self.resolve_call(model, info, expr)

    def _mark_threads(self) -> None:
        work: list[_Func] = []
        for model, info, call in self.thread_sites():
            target = self.thread_target(model, info, call)
            if target is not None and not target.thread_entry:
                target.thread_entry = True
                if not target.thread_reachable:
                    target.thread_reachable = True
                    work.append(target)
        while work:
            info = work.pop()
            model = self.modules[info.module]
            nxt = list(info.calls)
            nxt.extend(model.functions[qn] for qn in info.children)
            for t in nxt:
                if not t.thread_reachable:
                    t.thread_reachable = True
                    work.append(t)

    # -- lock acquisition closure --------------------------------------------

    def resolve_lock(self, model: _Module, info: _Func | None,
                     expr: ast.expr) -> str | None:
        """Strict lock identity of a `with` item / acquire receiver:
        must resolve to a declared Lock/RLock/Condition."""
        if isinstance(expr, ast.Name):
            lid = f"{model.modname}.{expr.id}"
            if lid in model.locks:
                return lid
            if expr.id in model.sym_imports:
                src, sym = model.sym_imports[expr.id]
                srcm = self.modules.get(src)
                if srcm is not None and f"{src}.{sym}" in srcm.locks:
                    return f"{src}.{sym}"
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base in ("self", "cls") and info is not None and \
                    info.cls is not None:
                lid = f"{model.modname}.{info.cls}.{attr}"
                return lid if lid in model.locks else None
            inst = self._instance_of(model, base)
            if inst is not None:
                src, cls = inst
                srcm = self.modules.get(src)
                if srcm is not None:
                    lid = f"{src}.{cls}.{attr}"
                    return lid if lid in srcm.locks else None
        return None

    def lock_kind(self, lid: str) -> str:
        for model in self.modules.values():
            if lid in model.locks:
                return model.locks[lid]
        return "Lock"

    def _direct_acquires(self, model: _Module, info: _Func) -> set:
        out = set()
        for sub in _own_nodes(info.node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    lid = self.resolve_lock(model, info,
                                            item.context_expr)
                    if lid:
                        out.add(lid)
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "acquire":
                lid = self.resolve_lock(model, info, sub.func.value)
                if lid:
                    out.add(lid)
        return out

    def _close_acquires(self) -> None:
        for model in self.modules.values():
            for info in model.functions.values():
                info.acquires = self._direct_acquires(model, info)
                info.trans_acquires = set(info.acquires)
        changed = True
        while changed:
            changed = False
            for model in self.modules.values():
                for info in model.functions.values():
                    for callee in info.calls:
                        extra = callee.trans_acquires - info.trans_acquires
                        if extra:
                            info.trans_acquires |= extra
                            changed = True


def _own_nodes(n: ast.AST):
    """Walk a function body excluding nested def/class bodies."""
    yield n
    for child in ast.iter_child_nodes(n):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)) and child is not n:
            continue
        yield from _own_nodes(child)


def _is_thread_ctor(call: ast.Call, model: _Module) -> bool:
    chain = _attr_chain(call.func)
    if not chain or chain[-1] != "Thread":
        return False
    if len(chain) == 1:
        return model.sym_imports.get("Thread", ("", ""))[0] == "threading"
    return model.mod_aliases.get(chain[0]) == "threading"


# ---------------------------------------------------------------------------
# Checker 1 — lock discipline
# ---------------------------------------------------------------------------


def _lock_order_edges(pkg: SyncPackage):
    """(held, acquired, model, node) for every acquisition performed —
    directly or through a resolvable call — inside a `with <lock>`."""
    edges = []

    def scan(model: _Module, info: _Func, node: ast.AST,
             held: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            now = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    lid = pkg.resolve_lock(model, info, item.context_expr)
                    if lid:
                        for h in now:
                            edges.append((h, lid, model, child))
                        now = now + (lid,)
            elif isinstance(child, ast.Call) and held:
                target = pkg.resolve_call(model, info, child.func)
                acq = set()
                if target is not None:
                    acq = target.trans_acquires
                elif isinstance(child.func, ast.Attribute) and \
                        child.func.attr == "acquire":
                    lid = pkg.resolve_lock(model, info, child.func.value)
                    if lid:
                        acq = {lid}
                for lid in acq:
                    for h in now:
                        edges.append((h, lid, model, child))
            scan(model, info, child, now)

    for model in pkg.modules.values():
        for info in model.functions.values():
            scan(model, info, info.node, ())
    return edges


def _check_lock_order(pkg: SyncPackage) -> list[Finding]:
    edges = _lock_order_edges(pkg)
    graph: dict[str, set[str]] = {}
    site: dict[tuple[str, str], tuple[_Module, ast.AST]] = {}
    for a, b, model, node in edges:
        if a == b and pkg.lock_kind(a) in _REENTRANT:
            continue
        graph.setdefault(a, set()).add(b)
        key = (a, b)
        prev = site.get(key)
        if prev is None or (model.path, node.lineno) < \
                (prev[0].path, prev[1].lineno):
            site[key] = (model, node)
    out = []
    reported: set[frozenset] = set()
    for a, b in sorted(site):
        # a cycle through this edge: b can (transitively) lead back to a
        if a == b:
            cyc = {a}
        else:
            seen, stack, cyc = {b}, [b], None
            while stack:
                n = stack.pop()
                if a in graph.get(n, ()):
                    cyc = seen | {a}
                    break
                for m in graph.get(n, ()):
                    if m not in seen:
                        seen.add(m)
                        stack.append(m)
            if cyc is None:
                continue
        fz = frozenset(cyc)
        if fz in reported:
            continue
        reported.add(fz)
        model, node = site[(a, b)]
        names = " -> ".join(sorted(cyc)) + f" -> {sorted(cyc)[0]}"
        info_fn = next(
            (i.qualname for i in model.functions.values()
             if i.node.lineno <= node.lineno <=
             max(i.node.lineno, getattr(i.node, "end_lineno", 0) or 0)),
            "<module>",
        )
        sup = model.is_suppressed("SYNC201", node.lineno, None)
        out.append(Finding(
            "SYNC201", model.path, node.lineno, node.col_offset,
            f"lock-order inversion cycle {{{names}}} (one edge acquired "
            f"in `{info_fn}`)", sup,
        ))
    return out


def _check_acquire_release(pkg: SyncPackage) -> list[Finding]:
    out = []
    for model in pkg.modules.values():
        for info in model.functions.values():
            name = info.qualname.rsplit(".", 1)[-1]
            if name in _HOLDER_NAMES:
                continue
            acquires, releases = [], 0
            flock_ex, flock_un = [], 0
            for sub in _own_nodes(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if isinstance(f, ast.Attribute):
                    if f.attr == "acquire":
                        lid = pkg.resolve_lock(model, info, f.value)
                        if lid:
                            acquires.append((sub, lid))
                    elif f.attr == "release":
                        releases += 1
                    elif f.attr == "flock":
                        flags = {c[-1] for a in sub.args[1:]
                                 for c in [_attr_chain(a)] if c}
                        for a in sub.args[1:]:
                            for n in ast.walk(a):
                                c = _attr_chain(n) if isinstance(
                                    n, (ast.Attribute, ast.Name)) else []
                                if c:
                                    flags.add(c[-1])
                        if "LOCK_UN" in flags:
                            flock_un += 1
                        elif {"LOCK_EX", "LOCK_SH"} & flags:
                            flock_ex.append(sub)
            if acquires and not releases:
                sub, lid = acquires[0]
                sup = model.is_suppressed("SYNC202", sub.lineno,
                                          info.node.lineno)
                out.append(Finding(
                    "SYNC202", model.path, sub.lineno, sub.col_offset,
                    f"`{lid}.acquire()` in `{info.qualname}` has no "
                    "release on any path in this function", sup,
                ))
            if flock_ex and not flock_un:
                sub = flock_ex[0]
                sup = model.is_suppressed("SYNC202", sub.lineno,
                                          info.node.lineno)
                out.append(Finding(
                    "SYNC202", model.path, sub.lineno, sub.col_offset,
                    f"exclusive `fcntl.flock` in `{info.qualname}` has "
                    "no LOCK_UN on any path in this function", sup,
                ))
    return out


def _check_guarded(pkg: SyncPackage) -> list[Finding]:
    guarded = [(g, m) for m in pkg.modules.values() for g in m.guarded]
    if not guarded:
        return []
    by_class: dict[tuple[str, str], dict[str, _Guarded]] = {}
    by_module: dict[tuple[str, str], _Guarded] = {}
    for g, _ in guarded:
        if g.cls:
            by_class.setdefault((g.module, g.cls), {})[g.attr] = g
        else:
            by_module[(g.module, g.attr)] = g
    out = []
    for model in pkg.modules.values():
        for info in model.functions.values():
            if not info.thread_reachable:
                continue
            if info.qualname.rsplit(".", 1)[-1] == "__init__":
                continue

            def scan(node: ast.AST, held: frozenset) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    now = held
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        for item in child.items:
                            chain = _attr_chain(item.context_expr)
                            if chain:
                                now = now | {chain[-1]}
                    self_cls = info.cls
                    if isinstance(child, ast.Attribute) and \
                            isinstance(child.value, ast.Name):
                        g = None
                        base = child.value.id
                        if base in ("self", "cls") and self_cls:
                            g = by_class.get(
                                (model.modname, self_cls), {}
                            ).get(child.attr)
                        else:
                            inst = pkg._instance_of(model, base)
                            if inst is not None:
                                g = by_class.get(inst, {}).get(child.attr)
                        if g is not None and g.lock not in now:
                            sup = model.is_suppressed(
                                "SYNC203", child.lineno, info.node.lineno)
                            out.append(Finding(
                                "SYNC203", model.path, child.lineno,
                                child.col_offset,
                                f"`{g.ident().split(' ->')[0]}` is "
                                f"guarded-by `{g.lock}` but "
                                f"`{info.qualname}` (thread-reachable) "
                                "touches it outside a "
                                f"`with {g.lock}` scope", sup,
                            ))
                    elif isinstance(child, ast.Name) and \
                            (model.modname, child.id) in by_module:
                        g = by_module[(model.modname, child.id)]
                        if g.lock not in now and not isinstance(
                                getattr(child, "ctx", None), ast.Store):
                            sup = model.is_suppressed(
                                "SYNC203", child.lineno, info.node.lineno)
                            out.append(Finding(
                                "SYNC203", model.path, child.lineno,
                                child.col_offset,
                                f"`{model.modname}.{child.id}` is "
                                f"guarded-by `{g.lock}` but "
                                f"`{info.qualname}` (thread-reachable) "
                                "touches it outside a "
                                f"`with {g.lock}` scope", sup,
                            ))
                    scan(child, now)

            scan(info.node, frozenset())
    return out


# ---------------------------------------------------------------------------
# Checker 2 — thread lifecycle
# ---------------------------------------------------------------------------


def _thread_is_daemon(model: _Module, info: _Func | None,
                      call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    # `t.daemon = True` after construction, anywhere in the same scope
    scope = info.node if info is not None else model.tree
    for sub in _own_nodes(scope) if info is not None else ast.walk(scope):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Constant) and sub.value.value:
            for t in sub.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    return True
    return False


def _module_has_join(model: _Module) -> set[str]:
    """Receiver names with a `.join()` call anywhere in the module:
    {'t'} for `t.join()`, {'_thread'} for `self._thread.join()`."""
    out: set[str] = set()
    for sub in ast.walk(model.tree):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == "join":
            chain = _attr_chain(sub.func.value)
            if chain:
                out.add(chain[-1])
    return out


def _thread_binding(model: _Module, call: ast.Call) -> str | None:
    """The name the Thread object is bound to: `t = Thread(...)` -> 't',
    `self._thread = Thread(...)` -> '_thread'."""
    for sub in ast.walk(model.tree):
        if isinstance(sub, ast.Assign) and sub.value is call:
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    return t.id
                if isinstance(t, ast.Attribute):
                    return t.attr
        # `self._thread = threading.Thread(...); ...` via intermediate:
        # `t = Thread(...); self._thread = t` is covered by the Name arm
    return None


def _check_thread_lifecycle(pkg: SyncPackage) -> list[Finding]:
    out = []
    checked_targets: set[int] = set()
    for model, info, call in pkg.thread_sites():
        def_line = info.node.lineno if info is not None else None
        # SYNC204 — non-daemon thread with no join on any shutdown path
        if not _thread_is_daemon(model, info, call):
            binding = _thread_binding(model, call)
            joins = _module_has_join(model)
            if binding is None or binding not in joins:
                sup = model.is_suppressed("SYNC204", call.lineno, def_line)
                where = info.qualname if info is not None else "<module>"
                out.append(Finding(
                    "SYNC204", model.path, call.lineno, call.col_offset,
                    f"non-daemon Thread constructed in `{where}` is never "
                    "joined in this module — interpreter shutdown blocks "
                    "on it with no shutdown path", sup,
                ))
        # SYNC205 — target exception handling
        target = pkg.thread_target(model, info, call)
        if target is None or id(target.node) in checked_targets:
            continue
        checked_targets.add(id(target.node))
        tmodel = pkg.modules[target.module]
        broad_handlers = []
        for sub in _own_nodes(target.node):
            if isinstance(sub, ast.Try):
                for h in sub.handlers:
                    if _is_broad_handler(h):
                        broad_handlers.append(h)
        if not broad_handlers:
            sup = tmodel.is_suppressed("SYNC205", target.node.lineno,
                                       target.node.lineno)
            out.append(Finding(
                "SYNC205", tmodel.path, target.node.lineno,
                target.node.col_offset,
                f"thread target `{target.qualname}` has no broad "
                "try/except: an exception kills the thread silently "
                "(stderr only, nothing feeds the recorder)", sup,
            ))
        for h in broad_handlers:
            if _handler_is_silent(h):
                sup = tmodel.is_suppressed("SYNC205", h.lineno,
                                           target.node.lineno)
                out.append(Finding(
                    "SYNC205", tmodel.path, h.lineno, h.col_offset,
                    f"thread target `{target.qualname}` swallows broad "
                    "exceptions with a pass-only handler — nothing "
                    "feeds a recorder seam", sup,
                ))
    return out


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    names = [h.type] if not isinstance(h.type, ast.Tuple) \
        else list(h.type.elts)
    for n in names:
        chain = _attr_chain(n)
        if chain and chain[-1] in ("Exception", "BaseException"):
            return True
    return False


def _handler_is_silent(h: ast.ExceptHandler) -> bool:
    for stmt in h.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, (ast.Call, ast.Raise)):
                return False
    return True


def _check_install_pairs(pkg: SyncPackage) -> list[Finding]:
    out = []
    for model in pkg.modules.values():
        for info in model.functions.values():
            name = info.qualname.rsplit(".", 1)[-1]
            if name in _INSTALLERS | _UNINSTALLERS:
                continue  # the managers themselves, not a pairing site
            installs, uninstalls = [], []
            unwound: set[int] = set()  # uninstall calls under try-unwind
            for sub in _own_nodes(info.node):
                if isinstance(sub, ast.Try):
                    for blk in ([h for hh in sub.handlers
                                 for h in hh.body] + sub.finalbody):
                        for s in ast.walk(blk):
                            if isinstance(s, ast.Call) and \
                                    _call_name(s) in _UNINSTALLERS:
                                unwound.add(id(s))
                if isinstance(sub, ast.Call):
                    cn = _call_name(sub)
                    if cn in _INSTALLERS:
                        installs.append(sub)
                    elif cn in _UNINSTALLERS:
                        uninstalls.append(sub)
            if installs and uninstalls and \
                    not any(id(u) in unwound for u in uninstalls):
                u = uninstalls[0]
                sup = model.is_suppressed("SYNC206", u.lineno,
                                          info.node.lineno)
                out.append(Finding(
                    "SYNC206", model.path, u.lineno, u.col_offset,
                    f"`{info.qualname}` pairs a recorder install with an "
                    "uninstall that only runs on the straight-line path "
                    "— an exception between them leaks an armed "
                    "recorder (wrap the uninstall in finally/except)",
                    sup,
                ))
    return out


def _call_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# ---------------------------------------------------------------------------
# Checker 3 — durability protocol
# ---------------------------------------------------------------------------

_TAINT_FINAL = 1
_TAINT_TMP = 2


class _PathTaint:
    """Per-function forward taint: names derived from a protected path
    root. `p + '.tmp'` (or a join whose basename ends '.tmp') demotes
    to tmp-taint, blessed iff the function also calls a `replace`."""

    def __init__(self, pkg: SyncPackage, model: _Module, info: _Func):
        self.pkg = pkg
        self.model = model
        self.info = info
        roots = pkg.roots_table
        self.env_roots = set(roots.get("env_path_levers", []))
        self.fn_roots = {n for names in roots.get("path_fns", {}).values()
                         for n in names}
        self.exempt = set(roots.get("exempt_basenames", []))
        self.taint: dict[str, int] = {}

    def _lever_name(self, a: ast.expr) -> str | None:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
        if isinstance(a, ast.Name):  # the `_ENV = "OCT_X"` indirection
            return self.model.str_consts.get(a.id)
        return None

    def _is_env_read(self, node: ast.Call) -> str | None:
        """os.environ.get('X') / os.getenv('X') -> 'X' when protected."""
        f = node.func
        chain = _attr_chain(f)
        lever = None
        if chain and chain[-1] in ("get", "getenv") and node.args:
            if chain[-1] == "getenv" or "environ" in chain:
                lever = self._lever_name(node.args[0])
        return lever if lever in self.env_roots else None

    def expr_taint(self, node: ast.expr) -> int:
        if isinstance(node, ast.Name):
            return self.taint.get(node.id, 0)
        if isinstance(node, ast.Subscript):
            # os.environ["X"]
            chain = _attr_chain(node.value)
            if chain and chain[-1] == "environ" and \
                    self._lever_name(node.slice) in self.env_roots:
                return _TAINT_FINAL
            return self.expr_taint(node.value)
        if isinstance(node, ast.Call):
            if self._is_env_read(node):
                return _TAINT_FINAL
            cn = _call_name(node)
            if cn in self.fn_roots:
                return _TAINT_FINAL
            if cn == "join":
                t = 0
                for a in node.args:
                    t = max(t, self.expr_taint(a))
                if t and node.args:
                    last = node.args[-1]
                    if isinstance(last, ast.Constant) and \
                            isinstance(last.value, str) and \
                            last.value.endswith(".tmp"):
                        return _TAINT_TMP
                return t
            return 0
        if isinstance(node, ast.BinOp):
            lt = self.expr_taint(node.left)
            rt = self.expr_taint(node.right)
            t = max(lt, rt)
            if t and isinstance(node.right, ast.Constant) and \
                    isinstance(node.right.value, str) and \
                    node.right.value.endswith(".tmp"):
                return _TAINT_TMP
            return t
        if isinstance(node, ast.JoinedStr):
            t = 0
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    t = max(t, self.expr_taint(v.value))
            if t and node.values and \
                    isinstance(node.values[-1], ast.Constant) and \
                    str(node.values[-1].value).endswith(".tmp"):
                return _TAINT_TMP
            return t
        if isinstance(node, ast.IfExp):
            return max(self.expr_taint(node.body),
                       self.expr_taint(node.orelse))
        return 0

    def basename_of(self, node: ast.expr) -> str | None:
        if isinstance(node, ast.Call) and _call_name(node) == "join" and \
                node.args:
            last = node.args[-1]
            if isinstance(last, ast.Constant) and \
                    isinstance(last.value, str):
                return os.path.basename(last.value)
        if isinstance(node, ast.BinOp) and \
                isinstance(node.right, ast.Constant) and \
                isinstance(node.right.value, str):
            return os.path.basename(node.right.value)
        if isinstance(node, ast.Name):
            return self._bound_basenames.get(node.id)
        return None

    def run(self) -> list[Finding]:
        self._bound_basenames: dict[str, str] = {}
        # fixpoint over assignments (loops/reordered helpers)
        for _ in range(4):
            changed = False
            for sub in _own_nodes(self.info.node):
                if isinstance(sub, ast.Assign):
                    t = self.expr_taint(sub.value)
                    bn = self.basename_of(sub.value)
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            if t and self.taint.get(tgt.id, 0) != t:
                                self.taint[tgt.id] = t
                                changed = True
                            if bn:
                                self._bound_basenames[tgt.id] = bn
            if not changed:
                break
        has_replace = any(
            isinstance(s, ast.Call) and _call_name(s) == "replace"
            for s in _own_nodes(self.info.node)
        )
        out = []
        for sub in _own_nodes(self.info.node):
            if not (isinstance(sub, ast.Call) and
                    isinstance(sub.func, ast.Name) and
                    sub.func.id == "open" and sub.args):
                continue
            mode = "r"
            if len(sub.args) > 1 and isinstance(sub.args[1], ast.Constant):
                mode = str(sub.args[1].value)
            for kw in sub.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if not any(c in mode for c in _WRITE_MODES):
                continue
            t = self.expr_taint(sub.args[0])
            if not t:
                continue
            bn = self.basename_of(sub.args[0])
            if bn in self.exempt:
                continue
            if t == _TAINT_TMP and has_replace:
                continue  # the blessed write-tmp -> rename idiom
            sup = self.model.is_suppressed("SYNC207", sub.lineno,
                                           self.info.node.lineno)
            detail = ("a `.tmp` write with no rename in this function"
                      if t == _TAINT_TMP else
                      "a bare write (no tmp, no fsync, no rename)")
            out.append(Finding(
                "SYNC207", self.model.path, sub.lineno, sub.col_offset,
                f"`{self.info.qualname}` opens a protected store path "
                f"for writing — {detail}; route it through write_atomic "
                "or the tmp+replace idiom", sup,
            ))
        return out


def _check_durability(pkg: SyncPackage) -> list[Finding]:
    out = []
    for model in pkg.modules.values():
        for info in model.functions.values():
            out.extend(_PathTaint(pkg, model, info).run())
    return out


# ---------------------------------------------------------------------------
# Entry points + inventory + ratchet
# ---------------------------------------------------------------------------


def _thread_ident(pkg: SyncPackage, model: _Module, info: _Func | None,
                  call: ast.Call) -> str:
    target = pkg.thread_target(model, info, call)
    if target is not None:
        return f"{target.module}.{target.qualname}"
    where = info.qualname if info is not None else "<module>"
    return f"{model.modname}.{where}.<dynamic-target>"


def inventory(pkg: SyncPackage) -> dict:
    """Line-number-free concurrency inventory, pinned in
    concurrency.json so a new lock/thread/flock/guarded-attr site is a
    conscious --update-sync, never a silent drive-by."""
    locks = sorted({lid for m in pkg.modules.values() for lid in m.locks})
    flocks = sorted({
        f"{m.modname}.{i.qualname}"
        for m in pkg.modules.values() for i in m.functions.values()
        for s in _own_nodes(i.node)
        if isinstance(s, ast.Call) and
        isinstance(s.func, ast.Attribute) and s.func.attr == "flock"
    })
    threads = sorted({
        _thread_ident(pkg, model, info, call)
        for model, info, call in pkg.thread_sites()
    })
    guarded = sorted({g.ident() for m in pkg.modules.values()
                      for g in m.guarded})
    return {"locks": locks, "flock_functions": flocks,
            "threads": threads, "guarded": guarded}


@dataclasses.dataclass
class SyncReport:
    findings: list
    inventory: dict


def sweep_paths(paths: list[str], rel_to: str | None = None,
                roots_table: dict | None = None) -> SyncReport:
    rel = rel_to or os.path.dirname(os.path.abspath(paths[0]))
    pkg = SyncPackage([p for p in paths if os.path.exists(p)], rel,
                      roots_table=roots_table)
    findings: list[Finding] = []
    findings += _check_lock_order(pkg)
    findings += _check_acquire_release(pkg)
    findings += _check_guarded(pkg)
    findings += _check_thread_lifecycle(pkg)
    findings += _check_install_pairs(pkg)
    findings += _check_durability(pkg)
    # SYNC208 runs last: it audits which declarations the rules above
    # actually consumed
    for model in pkg.modules.values():
        findings.extend(model.stale_suppressions())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    counts: dict[str, int] = {}
    out: list[Finding] = []
    for f in findings:
        base = f"{f.rule}::{f.path}::{f.message}"
        n = counts.get(base, 0)
        counts[base] = n + 1
        out.append(dataclasses.replace(f, seq=n) if n else f)
    return SyncReport(out, inventory(pkg))


def sweep_source(source: str, name: str = "<memory>",
                 roots_table: dict | None = None) -> list[Finding]:
    """Sweep a single source string (fixture tests)."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, f"{name}.py")
        with open(p, "w", encoding="utf-8") as f:
            f.write(source)
        rep = sweep_paths([p], rel_to=d, roots_table=roots_table)
    return [dataclasses.replace(f, path=name) for f in rep.findings]


def default_roots(repo_root: str | None = None) -> list[str]:
    repo = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return [os.path.join(repo, "ouroboros_consensus_tpu"),
            os.path.join(repo, "scripts"),
            os.path.join(repo, "bench.py")]


def load_baseline(path: str | None = None) -> dict:
    with open(path or _BASELINE_PATH, encoding="utf-8") as f:
        return json.load(f)


def baseline_payload(report: SyncReport) -> dict:
    return {
        "comment": "octsync ratchet (scripts/lint.py --update-sync): "
                   "grandfathered finding keys + the line-number-free "
                   "concurrency inventory. Shrink-only in normal "
                   "operation.",
        "findings": sorted({f.key() for f in report.findings
                            if not f.suppressed}),
        "inventory": report.inventory,
    }


def write_baseline(report: SyncReport, path: str | None = None) -> dict:
    payload = baseline_payload(report)
    with open(path or _BASELINE_PATH, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


def check_sync(report: SyncReport, baseline: dict | None = None) \
        -> tuple[list[str], list[str]]:
    """(violations, stale_notes) vs the concurrency.json ratchet: a new
    unsuppressed finding or inventory drift is a violation; a baseline
    key that stopped firing is a ratchet-tightening note."""
    base = baseline if baseline is not None else load_baseline()
    known = set(base.get("findings", []))
    current = {f.key() for f in report.findings if not f.suppressed}
    violations = [
        f.format() for f in report.findings
        if not f.suppressed and f.key() not in known
    ]
    pinned = base.get("inventory", {})
    for section, now in report.inventory.items():
        then = pinned.get(section, [])
        gained = sorted(set(now) - set(then))
        lost = sorted(set(then) - set(now))
        if gained or lost:
            delta = "; ".join(
                ([f"new: {', '.join(gained)}"] if gained else []) +
                ([f"gone: {', '.join(lost)}"] if lost else [])
            )
            violations.append(
                f"inventory drift in `{section}` ({delta}) — review and "
                "re-pin with scripts/lint.py --update-sync"
            )
    stale = sorted(known - current)
    return violations, stale
