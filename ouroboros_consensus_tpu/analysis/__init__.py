"""octlint — static analysis for jit-safety and jaxpr pathology.

Two cooperating passes, both born from the repo's worst recurring
failure class: COMPILE-TIME pathology (the XLA algebraic-simplifier
circular loop on the fused `verify_praos_core` graph — >30-min cold
compiles that forced the composed smoke eager, VERDICT r5 weak #3/#4)
and the host/device hazards that silently serialize a jitted hot path.

  Pass 1 (astlint)  — walks the package source and flags statically
                      detectable jit hazards with file:line diagnostics
                      and `# octlint: disable=RULE` suppressions (incl.
                      the OCT106 stale-suppression audit).
  Pass 2 (graphs)   — traces every registered kernel with abstract
                      inputs and computes per-graph pathology metrics
                      (unrolled multiply-chain depth, op fan-out,
                      rematerialization width) plus trace-time per-lane
                      point-op counts, failing any graph that exceeds
                      the checked-in `budgets.json`.
  Pass 3 (absint)   — octrange: abstract interpretation of the same
                      jaxprs under a per-row interval/overflow domain
                      (no-overflow proofs at production lane counts,
                      input specs in `shapes.json`) and a secret-taint
                      domain (no secret-dependent branches or access
                      patterns), ratcheted in `certified.json`.

Ships as a CLI (`python -m ouroboros_consensus_tpu.analysis`, with
`range`/`taint`/`pointops` subcommands and distinct exit codes), pytest
gates (`tests/test_analysis.py`, `tests/test_absint.py`, tier-1) and a
repo-wide ratchet (`scripts/lint.py` against `analysis/baseline.json`
and `analysis/certified.json`, with a git-diff `--changed` fast path).
"""

from __future__ import annotations

from .astlint import Finding, lint_paths, lint_source  # noqa: F401
from .graphs import (  # noqa: F401
    GraphReport,
    analyze_jaxpr,
    analyze_registered,
    check_budgets,
    load_budgets,
    registered_graphs,
)

# octrange (Pass 3) — jax-free at import time; tracing happens lazily
from .absint import (  # noqa: F401
    certifiable_graphs,
    certify_all,
    certify_graph,
    check_certified,
    load_certified,
    load_shapes,
)
