"""CLI: `python -m ouroboros_consensus_tpu.analysis [subcommand] [options]`.

Default run = both static passes over the package + the registered
kernel graphs (AST rules, jaxpr budgets, point-op budgets).

Subcommands:
  range      octrange interval/overflow certification (analysis/absint)
  taint      octrange secret-taint certification
  pointops   per-lane point-op counts vs their budgets.json ceilings
  cost       octwall predicted cold-compile walls vs the budgets.json
             "compile_wall" ceilings (analysis/costmodel)
  resources  device-resource pins (FLOPs / bytes accessed / peak HBM,
             obs/resources.py) vs the budgets.json "device_resources"
             section: hash-freshness + ceiling compares only — traces
             for the fresh feature hashes, never compiles
  sync       octsync concurrency & durability-protocol sweep
             (analysis/concurrency.py): lock-order / guarded-attribute
             / thread-lifecycle / tmp-fsync-rename checkers vs the
             analysis/concurrency.json ratchet. Pure AST — never
             imports jax
  flow       octflow exception-routing & degradation-lattice sweep
             (analysis/flow.py): raise-classification / corruption-
             laundering / verdict-fabrication / lattice-completeness /
             kill-switch-integrity / re-dispatch-pinning checkers vs
             the analysis/flow.json ratchet. Pure AST — never imports
             jax

Shared options:
  --json            machine-readable report on stdout (keys sorted —
                    stable for CI diffing)
  --graphs G [G...] restrict to these graphs

Default-run options:
  --paths P [P...]  lint these packages/files instead of the package
  --no-graphs       skip Pass 2 (pure AST run, no jax import)
  --all             include suppressed findings in the report
  --baseline B      subtract baselined finding keys (ratchet mode —
                    scripts/lint.py drives this)

range/taint options:
  --tier {fast,full}  lane-sweep tier from shapes.json (default fast)
  --no-ratchet        report only; skip the certified.json comparison

sync options:
  --paths P [P...]  sweep these files/dirs instead of the default roots
                    (package + scripts/ + bench.py)
  --all             include suppressed findings in the report
  --no-ratchet      report only; skip the concurrency.json comparison

flow options:
  --paths P [P...]  sweep these files/dirs instead of the default roots
                    (package + scripts/ + bench.py); partial sweeps
                    skip the whole-tree FLOW305 lever audit
  --all             include suppressed findings in the report
  --no-ratchet      report only; skip the flow.json comparison

Exit codes (distinct so CI can tell WHY the gate failed):
  0  clean
  1  unsuppressed AST finding(s)
  2  usage error (argparse)
  3  jaxpr-metric or point-op budget violation
  4  certification failure (range proof lost / taint ratchet violation)
  5  compile-wall ratchet violation (predicted cold-compile wall over
     its budgets.json "compile_wall" ceiling)
  6  device-resource ratchet violation (a registry graph without a
     "device_resources" pin, a stale-structure pin — feature hash no
     longer matching the traced graph — or a pinned FLOP/byte/peak-HBM
     value over its ceiling)
  7  octsync concurrency ratchet violation (a new unsuppressed
     lock/thread/durability finding, lock-or-thread inventory drift,
     or a stale suppression)
  8  octflow failure-taxonomy ratchet violation (a new unsuppressed
     FLOW3xx exception-routing finding, raise-site/handler/rung-edge/
     lever inventory drift, or a stale suppression)
When several classes fire at once the lowest code wins
(1 < 3 < 4 < 5 < 6 < 7 < 8).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import astlint, graphs

EXIT_OK = 0
EXIT_FINDINGS = 1
EXIT_BUDGET = 3
EXIT_CERT = 4
EXIT_COST = 5
EXIT_RESOURCES = 6
EXIT_SYNC = 7
EXIT_FLOW = 8


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pin_cpu() -> None:
    # abstract tracing never needs an accelerator, and this box's
    # sitecustomize force-registers a TPU plugin whose client init can
    # hang on a wedged tunnel — pin the platform BEFORE the first
    # backend touch so the lint gate cannot block on hardware
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # already initialized (e.g. under pytest conftest)


def _emit(payload: dict, as_json: bool, lines: list[str]) -> None:
    if as_json:
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for ln in lines:
            print(ln)


def _cmd_certify(args, domain: str) -> int:
    from . import absint

    _pin_cpu()
    names = args.graphs or [
        n for n in absint.certifiable_graphs()
        if domain in absint._spec_of(n).get("domains", ["range", "taint"])
    ]
    shapes = absint.load_shapes()
    reports = []
    for name in names:
        if domain == "range":
            for lanes in (
                [args.lanes] if args.lanes is not None
                else absint.sweep_lanes(name, args.tier, shapes)
            ):
                reports.append(absint.certify_range(name, lanes, shapes))
        else:
            lanes = (args.lanes if args.lanes is not None
                     else absint.sweep_lanes(name, args.tier, shapes)[0])
            reports.append(absint.certify_taint(name, lanes, shapes))
    violations: list[str] = []
    if not args.no_ratchet:
        violations = absint.check_certified(reports)
    failed = [r for r in reports if not r.ok]
    lines = []
    for r in reports:
        lanes = "default" if r.lanes is None else r.lanes
        status = "ok" if r.ok else "FAIL"
        extra = (" lane-universal" if r.domain == "range"
                 and r.lane_universal else "")
        lines.append(
            f"{r.graph}@{lanes} [{r.domain}] {status}: "
            f"{len(r.findings)} finding(s), {r.eqns} eqns{extra}"
        )
        lines.extend(f"  {f.format()}" for f in r.findings)
    lines.extend(f"RATCHET: {v}" for v in violations)
    lines.append(
        f"octrange {domain}: {len(failed)} failing graph-sweep(s), "
        f"{len(violations)} ratchet violation(s)"
    )
    _emit(
        {
            "domain": domain,
            "reports": [r.to_dict() for r in reports],
            "ratchet_violations": violations,
            "ok": not (failed or violations),
        },
        args.json, lines,
    )
    return EXIT_CERT if (failed or violations) else EXIT_OK


def _cmd_cost(args) -> int:
    """octwall: per-graph compile-cost features + predicted walls vs
    the budgets.json compile_wall ceilings (sorted-keys --json is
    byte-stable for CI diffing)."""
    from . import absint, costmodel

    _pin_cpu()
    budgets = graphs.load_budgets(args.budgets)
    names = args.graphs or graphs.registered_graphs()
    shapes = absint.load_shapes()
    # trace at the fast-sweep lane counts — the SAME traces the lint
    # gate pins against, so the drift note below is meaningful
    feats = [
        costmodel.graph_features(
            n, absint.sweep_lanes(n, "fast", shapes)[0]
        )
        for n in names
    ]
    rows = []
    for f in feats:
        pred = costmodel.predict(f)
        pin = costmodel.pinned(f.name) or {}
        rows.append({
            "graph": f.name,
            "features": f.to_dict(),
            "feature_hash": f.hash(),
            "predicted_s": None if pred is None else round(pred, 1),
            "pinned_hash": pin.get("feature_hash"),
            "advisories": costmodel.advisories(f, budgets),
        })
    violations = costmodel.check_compile_wall(feats, budgets)
    lines = []
    for r in rows:
        pred = "?" if r["predicted_s"] is None else f"{r['predicted_s']}s"
        drift = ("" if r["pinned_hash"] in (None, r["feature_hash"])
                 else " [features drifted from pin]")
        lines.append(
            f"{r['graph']}: predicted {pred} "
            f"(eqns={r['features']['eqns']} "
            f"max_comp={r['features']['max_comp_eqns']} "
            f"chain={r['features']['mul_chain_depth']}){drift}"
        )
        # advisories stay in the JSON rows; the text report leaves them
        # to check_compile_wall's COST lines (single source, no dupes)
    lines.extend(f"COST: {v}" for v in violations)
    lines.append(f"octwall: {len(violations)} violation(s)")
    _emit({"cost": rows, "violations": violations,
           "ok": not violations}, args.json, lines)
    return EXIT_COST if violations else EXIT_OK


def _cmd_resources(args) -> int:
    """Device-resource ratchet status (sorted-keys --json is byte-stable
    for CI diffing). Traces each graph once for the fresh octwall
    feature hash — the staleness key — but never lowers or compiles;
    regeneration is scripts/lint.py --update-resources."""
    from ..obs import resources as obs_res
    from . import absint, costmodel

    _pin_cpu()
    budgets = graphs.load_budgets(args.budgets)
    names = args.graphs or graphs.registered_graphs()
    shapes = absint.load_shapes()
    feats = [
        costmodel.graph_features(
            n, absint.sweep_lanes(n, "fast", shapes)[0]
        )
        for n in names
    ]
    rows = obs_res.resources_payload(names, budgets, feats)
    violations = obs_res.check_device_resources(feats, budgets)
    lines = []
    for name in sorted(rows):
        r = rows[name]
        pin = r["pin"]
        if pin is None:
            lines.append(f"{name}: NO PIN")
            continue
        status = "fresh" if r["fresh"] else "STALE-STRUCTURE"
        lines.append(
            f"{name}@{pin.get('at_lanes')}: "
            f"flops={pin.get('flops')} "
            f"bytes={pin.get('bytes_accessed')} "
            f"peak_hbm={pin.get('peak_hbm_bytes')} [{status}]"
        )
    lines.extend(f"RESOURCES: {v}" for v in violations)
    lines.append(f"resources: {len(violations)} violation(s)")
    _emit({"resources": rows, "violations": violations,
           "ok": not violations}, args.json, lines)
    return EXIT_RESOURCES if violations else EXIT_OK


def _cmd_sync(args) -> int:
    """octsync: concurrency & durability-protocol sweep vs the
    concurrency.json ratchet (sorted-keys --json is byte-stable for CI
    diffing). Pure AST — jax is never imported on this route."""
    from . import concurrency

    repo = os.path.dirname(_package_root())
    paths = args.paths or concurrency.default_roots(repo)
    report = concurrency.sweep_paths(
        paths, repo, concurrency.load_roots()
    )
    violations: list[str] = []
    stale: list[str] = []
    if not args.no_ratchet:
        violations, stale = concurrency.check_sync(
            report, concurrency.load_baseline()
        )
    shown = (report.findings if args.all
             else [f for f in report.findings if not f.suppressed])
    lines = [f.format() for f in shown]
    lines.extend(f"SYNC: {v}" for v in violations)
    lines.extend(
        f"note: concurrency baseline entry no longer fires "
        f"(run scripts/lint.py --update-sync to ratchet): {k}"
        for k in stale
    )
    n_sup = sum(1 for f in report.findings if f.suppressed)
    lines.append(
        f"octsync: {len(shown)} finding(s), {n_sup} suppressed, "
        f"{len(violations)} ratchet violation(s), "
        f"{len(stale)} stale ratchet entr(y/ies)"
    )
    _emit(
        {
            "findings": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "message": f.message,
                    "suppressed": f.suppressed,
                    "key": f.key(),
                }
                for f in shown
            ],
            "inventory": report.inventory,
            "violations": violations,
            "stale": stale,
            "ok": not violations,
        },
        args.json, lines,
    )
    return EXIT_SYNC if violations else EXIT_OK


def _cmd_flow(args) -> int:
    """octflow: exception-routing & degradation-lattice sweep vs the
    flow.json ratchet (sorted-keys --json is byte-stable for CI
    diffing). Pure AST — jax is never imported on this route."""
    from . import flow

    repo = os.path.dirname(_package_root())
    paths = args.paths or flow.default_roots(repo)
    cfg = flow.load_roots()
    if args.paths:
        # FLOW305 lever integrity is a whole-tree property — a partial
        # --paths sweep would read none of the documented levers and
        # drown the report in dead-lever noise
        cfg["kill_switches"] = []
    report = flow.sweep_paths(paths, repo, cfg)
    violations: list[str] = []
    stale: list[str] = []
    if not args.no_ratchet:
        violations, stale = flow.check_flow(report, flow.load_baseline())
    shown = (report.findings if args.all
             else [f for f in report.findings if not f.suppressed])
    lines = [f.format() for f in shown]
    lines.extend(f"FLOW: {v}" for v in violations)
    lines.extend(
        f"note: flow baseline entry no longer fires "
        f"(run scripts/lint.py --update-flow to ratchet): {k}"
        for k in stale
    )
    n_sup = sum(1 for f in report.findings if f.suppressed)
    lines.append(
        f"octflow: {len(shown)} finding(s), {n_sup} suppressed, "
        f"{len(violations)} ratchet violation(s), "
        f"{len(stale)} stale ratchet entr(y/ies)"
    )
    _emit(
        {
            "findings": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "message": f.message,
                    "suppressed": f.suppressed,
                    "key": f.key(),
                }
                for f in shown
            ],
            "inventory": report.inventory,
            "violations": violations,
            "stale": stale,
            "ok": not violations,
        },
        args.json, lines,
    )
    return EXIT_FLOW if violations else EXIT_OK


def _cmd_pointops(args) -> int:
    _pin_cpu()
    budgets = graphs.load_budgets(args.budgets)
    sec = budgets.get("point_ops", {})
    names = args.graphs or sorted(sec)
    rows = []
    for name in names:
        cfg = sec.get(name)
        lanes = int(cfg["at_lanes"]) if cfg else None
        stats = graphs.point_ops(name, lanes)
        rows.append({
            "graph": name,
            "at_lanes": lanes,
            "ops": stats["ops"],
            "lane_ops": stats["lane_ops"],
            "lane_ops_per_lane": (
                stats["lane_ops"] / lanes if lanes else None
            ),
            "budget": cfg["lane_ops_per_lane"] if cfg else None,
        })
    violations = graphs.check_point_ops(budgets, names=names)
    lines = [
        f"{r['graph']}@{r['at_lanes']}: {r['lane_ops_per_lane']:.1f} "
        f"lane-ops/lane (budget {r['budget']})"
        for r in rows
    ]
    lines.extend(f"BUDGET: {v}" for v in violations)
    lines.append(f"pointops: {len(violations)} violation(s)")
    _emit({"point_ops": rows, "violations": violations,
           "ok": not violations}, args.json, lines)
    return EXIT_BUDGET if violations else EXIT_OK


def _cmd_default(args) -> int:
    paths = args.paths or [_package_root()]
    findings = astlint.lint_paths(paths)

    # default runs also report rule coverage over the purpose-built
    # fixtures (tests/lint_fixtures) — a self-check that every rule
    # still fires; fixture findings never affect the exit status
    fixture_rules: list[str] = []
    if not args.paths:
        fdir = os.path.join(
            os.path.dirname(_package_root()), "tests", "lint_fixtures"
        )
        if os.path.isdir(fdir):
            fixture_rules = sorted({
                f.rule for f in astlint.lint_paths([fdir])
            })

    baseline_keys: set[str] = set()
    if args.baseline:
        with open(args.baseline, encoding="utf-8") as f:
            baseline_keys = set(json.load(f).get("findings", []))

    active = [
        f for f in findings
        if not f.suppressed and f.key() not in baseline_keys
    ]
    shown = findings if args.all else active

    reports: list[graphs.GraphReport] = []
    violations: list[str] = []
    if not args.no_graphs:
        _pin_cpu()
        budgets = graphs.load_budgets(args.budgets)
        reports = graphs.analyze_registered(args.graphs)
        violations = graphs.check_budgets(reports, budgets)
        violations += graphs.check_point_ops(budgets, names=args.graphs)

    failed = bool(active or violations)

    if args.json:
        out = {
            "findings": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "message": f.message,
                    "suppressed": f.suppressed,
                    "key": f.key(),
                }
                for f in shown
            ],
            "rules_fired": sorted({f.rule for f in shown}),
            "fixture_rules_fired": fixture_rules,
            "graphs": [r.to_dict() for r in reports],
            "budget_violations": violations,
            "ok": not failed,
        }
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        for f in shown:
            print(f.format())
        for r in reports:
            print(
                f"graph {r.name}: eqns={r.eqns} muls={r.mul_count} "
                f"mul_chain_depth={r.mul_chain_depth} "
                f"fanout={r.op_fanout} remat_width={r.remat_width} "
                f"computations={r.computations}"
            )
        for v in violations:
            print(f"BUDGET: {v}")
        n_sup = sum(1 for f in findings if f.suppressed)
        extra = (
            f", fixture rules firing: {'/'.join(fixture_rules)}"
            if fixture_rules else ""
        )
        print(
            f"octlint: {len(active)} finding(s), {n_sup} suppressed, "
            f"{len(violations)} budget violation(s){extra}"
        )
    if active:
        return EXIT_FINDINGS
    return EXIT_BUDGET if violations else EXIT_OK


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="ouroboros_consensus_tpu.analysis")
    sub = ap.add_subparsers(dest="cmd")

    def common(p, with_choices=True):
        p.add_argument("--json", action="store_true")
        p.add_argument(
            "--graphs", nargs="+", default=None,
            choices=None if not with_choices else None,
        )
        p.add_argument("--budgets", default=None,
                       help="alternate budgets.json")

    common(ap)
    ap.add_argument("--paths", nargs="+", default=None)
    ap.add_argument("--no-graphs", action="store_true")
    ap.add_argument("--all", action="store_true",
                    help="include suppressed findings")
    ap.add_argument("--baseline", default=None,
                    help="baseline.json of grandfathered finding keys")

    for name in ("range", "taint"):
        p = sub.add_parser(name)
        common(p)
        p.add_argument("--tier", choices=("fast", "full"), default="fast")
        p.add_argument("--lanes", type=int, default=None,
                       help="override the swept lane count")
        p.add_argument("--no-ratchet", action="store_true",
                       help="skip the certified.json comparison")

    common(sub.add_parser("pointops"))
    common(sub.add_parser("cost"))
    common(sub.add_parser("resources"))

    p = sub.add_parser("sync")
    p.add_argument("--json", action="store_true")
    p.add_argument("--paths", nargs="+", default=None)
    p.add_argument("--all", action="store_true",
                   help="include suppressed findings")
    p.add_argument("--no-ratchet", action="store_true",
                   help="skip the concurrency.json comparison")

    p = sub.add_parser("flow")
    p.add_argument("--json", action="store_true")
    p.add_argument("--paths", nargs="+", default=None)
    p.add_argument("--all", action="store_true",
                   help="include suppressed findings")
    p.add_argument("--no-ratchet", action="store_true",
                   help="skip the flow.json comparison")

    args = ap.parse_args(argv)
    if args.cmd in ("range", "taint"):
        return _cmd_certify(args, args.cmd)
    if args.cmd == "pointops":
        return _cmd_pointops(args)
    if args.cmd == "cost":
        return _cmd_cost(args)
    if args.cmd == "resources":
        return _cmd_resources(args)
    if args.cmd == "sync":
        return _cmd_sync(args)
    if args.cmd == "flow":
        return _cmd_flow(args)
    # default-run graph names must be registered (certification targets
    # include aux graphs; the default run's budget pass does not)
    if args.graphs:
        bad = set(args.graphs) - set(graphs.registered_graphs())
        if bad:
            ap.error(f"unknown graphs: {sorted(bad)}")
    return _cmd_default(args)


if __name__ == "__main__":
    sys.exit(main())
