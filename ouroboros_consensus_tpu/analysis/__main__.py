"""CLI: `python -m ouroboros_consensus_tpu.analysis [options]`.

Default run = both passes over the package + the registered kernel
graphs, exit 1 on any unsuppressed finding or budget violation.

  --json            machine-readable report on stdout
  --paths P [P...]  lint these packages/files instead of the package
  --no-graphs       skip Pass 2 (pure AST run, no jax import)
  --graphs G [G...] analyze only these registered graphs
  --all             include suppressed findings in the report
  --baseline B      subtract baselined finding keys (ratchet mode —
                    scripts/lint.py drives this)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import astlint, graphs


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="ouroboros_consensus_tpu.analysis")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--paths", nargs="+", default=None)
    ap.add_argument("--no-graphs", action="store_true")
    ap.add_argument("--graphs", nargs="+", default=None,
                    choices=graphs.registered_graphs())
    ap.add_argument("--all", action="store_true",
                    help="include suppressed findings")
    ap.add_argument("--baseline", default=None,
                    help="baseline.json of grandfathered finding keys")
    ap.add_argument("--budgets", default=None,
                    help="alternate budgets.json")
    args = ap.parse_args(argv)

    paths = args.paths or [_package_root()]
    findings = astlint.lint_paths(paths)

    # default runs also report rule coverage over the purpose-built
    # fixtures (tests/lint_fixtures) — a self-check that every rule
    # still fires; fixture findings never affect the exit status
    fixture_rules: list[str] = []
    if not args.paths:
        fdir = os.path.join(
            os.path.dirname(_package_root()), "tests", "lint_fixtures"
        )
        if os.path.isdir(fdir):
            fixture_rules = sorted({
                f.rule for f in astlint.lint_paths([fdir])
            })

    baseline_keys: set[str] = set()
    if args.baseline:
        with open(args.baseline, encoding="utf-8") as f:
            baseline_keys = set(json.load(f).get("findings", []))

    active = [
        f for f in findings
        if not f.suppressed and f.key() not in baseline_keys
    ]
    shown = findings if args.all else active

    reports: list[graphs.GraphReport] = []
    violations: list[str] = []
    if not args.no_graphs:
        # abstract tracing never needs an accelerator, and this box's
        # sitecustomize force-registers a TPU plugin whose client init
        # can hang on a wedged tunnel — pin the platform BEFORE the
        # first backend touch so the lint gate cannot block on hardware
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # already initialized (e.g. under pytest conftest)
        reports = graphs.analyze_registered(args.graphs)
        budgets = graphs.load_budgets(args.budgets)
        violations = graphs.check_budgets(reports, budgets)

    failed = bool(active or violations)

    if args.json:
        out = {
            "findings": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "message": f.message,
                    "suppressed": f.suppressed,
                    "key": f.key(),
                }
                for f in shown
            ],
            "rules_fired": sorted({f.rule for f in shown}),
            "fixture_rules_fired": fixture_rules,
            "graphs": [r.to_dict() for r in reports],
            "budget_violations": violations,
            "ok": not failed,
        }
        print(json.dumps(out, indent=2))
    else:
        for f in shown:
            print(f.format())
        for r in reports:
            print(
                f"graph {r.name}: eqns={r.eqns} muls={r.mul_count} "
                f"mul_chain_depth={r.mul_chain_depth} "
                f"fanout={r.op_fanout} remat_width={r.remat_width} "
                f"computations={r.computations}"
            )
        for v in violations:
            print(f"BUDGET: {v}")
        n_sup = sum(1 for f in findings if f.suppressed)
        extra = (
            f", fixture rules firing: {'/'.join(fixture_rules)}"
            if fixture_rules else ""
        )
        print(
            f"octlint: {len(active)} finding(s), {n_sup} suppressed, "
            f"{len(violations)} budget violation(s){extra}"
        )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
