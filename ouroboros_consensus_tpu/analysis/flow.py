"""Pass 6 — octflow: exception-routing & degradation-lattice analyzer.

The reference design's core safety claim (ChainDB must refuse
corruption loudly, never launder it through chain selection) lives in
this tree as a hand-maintained lattice: the `node/exit.DISPOSITIONS`
failure taxonomy (REFUSE / REPAIR / RECOVER / PROPAGATE), the
`RecoverySupervisor` rung ladder, and the OCT_* kill-switch engines.
PR 13 found two real corruption-laundering bugs in that lattice by
review; octflow turns each reviewed invariant into a gate. Pure AST +
the octsync call-graph (analysis/concurrency.SyncPackage) — never
imports the modules it scans, never imports jax.

Rules
  FLOW301 unclassified-raise     a `raise SomeClass(...)` in the
                                 crash/verdict-bearing modules
                                 (storage/, tools/, protocol/,
                                 obs/recovery.py) whose class — or any
                                 statically visible ancestor — has no
                                 row in `node/exit.DISPOSITIONS`.
                                 Builtins with settled semantics
                                 (ValueError, TypeError, SystemExit …)
                                 are exempt by config; `Exception`
                                 itself deliberately is NOT.
  FLOW302 corruption-laundering  a handler reachable from the recovery
                                 ladder / the validate_chain retire
                                 loops that explicitly catches a
                                 REFUSE- or REPAIR-classified type
                                 without re-raising or consulting
                                 triage/recoverable — the exact PR 13
                                 bug class (the ladder absorbing what
                                 the open-with-repair scan owns).
  FLOW303 silent-verdict-fabrication
                                 a broad (bare/Exception/BaseException)
                                 handler on a verdict-producing path
                                 inside the crash/verdict-bearing
                                 modules whose body neither raises,
                                 calls anything, nor forwards the
                                 bound exception object — a swallowed
                                 device fault becomes a fabricated
                                 verdict. (`return st, i, e` forwards
                                 the fault as data: not a finding.)
  FLOW304 incomplete-degradation-lattice
                                 (a) the LADDERS escalation table must
                                 be closed: every rung routed by the
                                 `_run_rung` if-chain, every backend
                                 chain ending in a rung that calls the
                                 exact-host-reference terminal;
                                 (b) every device dispatch site
                                 (dispatch_prepared / run_batch /
                                 sharded_* …) must sit in a function
                                 statically reachable from a recovery
                                 protector (recover_window /
                                 recover_fold / elect_window_recovering
                                 or the ladder itself) so a device
                                 fault always has a rung to fall to.
  FLOW305 kill-switch-integrity  every documented `OCT_*=0` lever row
                                 must actually GUARD something: a dead
                                 lever (read but never consumed by any
                                 if/while/predicate test) and a
                                 false-branch re-entry (both branches
                                 of a levered `if` call the same
                                 callees) are findings.
  FLOW306 unsanctioned-broad-handler
                                 a bare `except:` or
                                 `except BaseException:` that does not
                                 re-raise, outside the sanctioned
                                 seams listed in flow_roots.json
                                 (e.g. the prefetch pump that forwards
                                 the exception object to its consumer).
  FLOW307 unpinned-redispatch    an anomaly re-dispatch site (the
                                 functions named in `redispatch_pins`)
                                 stopped calling one of its pinned
                                 exact-reference callees — the
                                 re-dispatch no longer routes into the
                                 reference set the differential suites
                                 pin.
  FLOW308 stale-suppression      an `# octflow: disable=...` comment
                                 that suppresses nothing on the
                                 current tree (mirrors OCT106/SYNC208).

Suppression grammar (same shape as octlint/octsync):

  raise OddError(x)   # octflow: disable=FLOW301  <why it is safe>
  # `# octflow: disable` (no rule list) suppresses all rules on that
  # line; the def-line suppresses the whole body;
  # `# octflow: disable-file=FLOW306` suppresses the file.

octflow is a static over-approximation. It does NOT prove anything
about dynamically installed handlers (sys.excepthook, signal handlers,
monkeypatched methods), the C++ native scanner (errors crossing that
boundary arrive as the Python classes it raises), exceptions raised by
name through a variable (`raise err`), or call edges the octsync
resolver cannot see (callbacks, getattr dispatch) — see
analysis/README.md §Pass 6 for the full caveat list.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

from .astlint import _attr_chain
from .concurrency import (
    SyncPackage,
    _call_name,
    _handler_is_silent,
    _is_broad_handler,
    _own_nodes,
)

RULES = {
    "FLOW301": "unclassified-raise",
    "FLOW302": "corruption-laundering",
    "FLOW303": "silent-verdict-fabrication",
    "FLOW304": "incomplete-degradation-lattice",
    "FLOW305": "kill-switch-integrity",
    "FLOW306": "unsanctioned-broad-handler",
    "FLOW307": "unpinned-redispatch",
    "FLOW308": "stale-suppression",
}

_RULE_LIST = r"[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*"
_SUPPRESS_RE = re.compile(
    rf"#\s*octflow:\s*disable(?:=({_RULE_LIST}))?(?=[\s,]|$)"
)
_SUPPRESS_FILE_RE = re.compile(
    rf"#\s*octflow:\s*disable-file=({_RULE_LIST})"
)

_ROOTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "flow_roots.json")
_BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "flow.json")


def load_roots(path: str | None = None) -> dict:
    with open(path or _ROOTS_PATH, encoding="utf-8") as f:
        return json.load(f)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    seq: int = 0  # ordinal among same-keyed findings (see astlint)

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"[{RULES[self.rule]}] {self.message}{tag}"

    def key(self) -> str:
        base = f"{self.rule}::{self.path}::{self.message}"
        return base if self.seq == 0 else f"{base}::#{self.seq}"


# ---------------------------------------------------------------------------
# octflow suppressions (octsync grammar, octflow namespace)
# ---------------------------------------------------------------------------


class _Supp:
    def __init__(self, path: str, comment_lines) -> None:
        self.path = path
        self.suppress_file: set[str] = set()
        self.suppress_line: dict[int, set[str] | None] = {}
        self.decls: list[list] = []  # [line, rules|None, file_level, used]
        for i, line in comment_lines:
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.suppress_file |= rules
                self.decls.append([i, rules, True, False])
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = m.group(1)
                if rules is None:
                    self.suppress_line[i] = None
                    self.decls.append([i, None, False, False])
                else:
                    rs = {r.strip() for r in rules.split(",") if r.strip()}
                    self.suppress_line[i] = rs
                    self.decls.append([i, rs, False, False])

    def _mark_used(self, line: int | None, rule: str,
                   file_level: bool) -> None:
        for d in self.decls:
            if d[2] != file_level:
                continue
            if file_level:
                if d[1] is not None and rule in d[1]:
                    d[3] = True
                    return
            elif d[0] == line and (d[1] is None or rule in d[1]):
                d[3] = True
                return

    def is_suppressed(self, rule: str, line: int,
                      def_line: int | None) -> bool:
        if rule in self.suppress_file:
            self._mark_used(None, rule, True)
            return True
        for ln in (line, def_line):
            if ln is None:
                continue
            rules = self.suppress_line.get(ln, "missing")
            if rules is None or (rules != "missing" and rule in rules):
                self._mark_used(ln, rule, False)
                return True
        return False

    def stale(self) -> list[Finding]:
        out = []
        for d in self.decls:
            if d[3]:
                continue
            line, rules, file_level, _ = d
            what = "all rules" if rules is None else ",".join(sorted(rules))
            kind = "disable-file" if file_level else "disable"
            sup = self.is_suppressed("FLOW308", line, None)
            out.append(Finding(
                "FLOW308", self.path, line, 0,
                f"`# octflow: {kind}={what}` suppresses nothing on the "
                "current tree — remove the stale comment",
                sup,
            ))
        return out


# ---------------------------------------------------------------------------
# The analysis context: octsync call graph + the failure taxonomy
# ---------------------------------------------------------------------------


def _matches(fq: str, name: str) -> bool:
    """`fq` names `name` exactly or by dotted suffix — so a config entry
    `RecoverySupervisor._run_rung` finds
    `ouroboros_consensus_tpu.obs.recovery.RecoverySupervisor._run_rung`
    on the real tree AND `flow_lattice.RecoverySupervisor._run_rung` in
    a fixture sweep."""
    return fq == name or fq.endswith("." + name)


def _in_scope(path: str, prefixes: list[str]) -> bool:
    return any(path == p or path.startswith(p) for p in prefixes)


class _Ctx:
    """Everything the rules share: the SyncPackage call graph, the
    parsed DISPOSITIONS taxonomy, the class hierarchy, per-node owner
    functions, and per-path octflow suppressions."""

    def __init__(self, pkg: SyncPackage, cfg: dict, rel_to: str):
        self.pkg = pkg
        self.cfg = cfg
        self.findings: list[Finding] = []
        # octflow suppressions ride the module's one-shot comment scan
        self.supp: dict[str, _Supp] = {}
        for model in pkg.modules.values():
            self.supp[model.modname] = _Supp(model.path,
                                             model.comment_lines)
        # fq -> _Func index + node-id -> owning _Func map; the node
        # lists are walked ONCE here and cached — every checker
        # re-iterates these lists instead of re-walking the AST
        self.funcs: dict[str, object] = {}
        self.owner: dict[int, object] = {}
        self._own: dict[int, list] = {}
        self._mod_nodes: dict[str, list] = {}
        for model in pkg.modules.values():
            for info in model.functions.values():
                fq = f"{model.modname}.{info.qualname}"
                self.funcs[fq] = info
                own = list(_own_nodes(info.node))
                self._own[id(info.node)] = own
                for sub in own:
                    self.owner[id(sub)] = info
            self._mod_nodes[model.modname] = list(ast.walk(model.tree))
        # class name -> statically visible base names (merged tree-wide;
        # an over-approximation is the safe direction for FLOW302)
        self.bases: dict[str, set[str]] = {}
        for model in pkg.modules.values():
            for node in self._mod_nodes[model.modname]:
                if isinstance(node, ast.ClassDef):
                    bs = self.bases.setdefault(node.name, set())
                    for b in node.bases:
                        chain = _attr_chain(b)
                        if chain:
                            bs.add(chain[-1])
        # the DISPOSITIONS table, parsed statically from any swept
        # module (node/exit.py on the real tree)
        self.dispo: dict[str, str] = {}
        table = cfg.get("dispositions_table", "DISPOSITIONS")
        for model in pkg.modules.values():
            for stmt in model.tree.body:
                tgt = None
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    tgt = stmt.targets[0].id
                elif isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    tgt = stmt.target.id
                if tgt != table or not isinstance(
                        getattr(stmt, "value", None), ast.Dict):
                    continue
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        continue
                    if isinstance(v, ast.Attribute):
                        self.dispo[k.value] = v.attr.lower()
                    elif isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        self.dispo[k.value] = v.value.lower()

    # -- taxonomy ------------------------------------------------------------

    def disposition_of(self, name: str) -> str | None:
        """The class's own row, else the nearest classified ancestor in
        the statically visible hierarchy (BFS — the static analog of
        triage()'s MRO walk)."""
        seen, frontier = set(), [name]
        while frontier:
            nxt = []
            for n in frontier:
                if n in seen:
                    continue
                seen.add(n)
                d = self.dispo.get(n)
                if d is not None:
                    return d
                nxt.extend(self.bases.get(n, ()))
            frontier = nxt
        return None

    # -- plumbing ------------------------------------------------------------

    def owner_of(self, node: ast.AST):
        return self.owner.get(id(node))

    def own(self, info) -> list:
        """Cached `_own_nodes(info.node)` — the function body excluding
        nested def/class bodies."""
        cached = self._own.get(id(info.node))
        if cached is None:
            cached = list(_own_nodes(info.node))
            self._own[id(info.node)] = cached
        return cached

    def walk_module(self, model) -> list:
        """Cached `ast.walk(model.tree)`."""
        cached = self._mod_nodes.get(model.modname)
        if cached is None:
            cached = list(ast.walk(model.tree))
            self._mod_nodes[model.modname] = cached
        return cached

    def fq(self, model, info) -> str:
        return f"{model.modname}.{info.qualname}" if info is not None \
            else f"{model.modname}.<module>"

    def emit(self, rule: str, model, node, message: str) -> None:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        info = self.owner.get(id(node))
        def_line = info.node.lineno if info is not None else None
        sup = self.supp[model.modname].is_suppressed(rule, line, def_line)
        self.findings.append(
            Finding(rule, model.path, line, col, message, sup))

    def closure(self, seed_names: list[str]) -> set[str]:
        """fq names of every function reachable from functions matching
        `seed_names`, through resolved call edges + lexical nesting."""
        out: set[str] = set()
        work = []
        for fq, info in self.funcs.items():
            if any(_matches(fq, s) for s in seed_names):
                out.add(fq)
                work.append(info)
        while work:
            info = work.pop()
            model = self.pkg.modules[info.module]
            nxt = list(info.calls)
            nxt.extend(model.functions[qn] for qn in info.children)
            for t in nxt:
                tfq = f"{t.module}.{t.qualname}"
                if tfq not in out:
                    out.add(tfq)
                    work.append(t)
        return out


# ---------------------------------------------------------------------------
# FLOW301 — unclassified raise sites in the crash/verdict-bearing plane
# ---------------------------------------------------------------------------


def _raise_class(node: ast.Raise) -> str | None:
    """`raise X(...)` / `raise mod.X(...)` -> "X"; bare re-raise and
    `raise err` (a variable — class unknowable statically) -> None."""
    if not isinstance(node.exc, ast.Call):
        return None
    chain = _attr_chain(node.exc.func)
    if not chain:
        return None
    name = chain[-1]
    return name if name[:1].isupper() else None


def _check_raises(ctx: _Ctx) -> None:
    scope = ctx.cfg.get("raise_scope", [])
    exempt = set(ctx.cfg.get("builtin_exempt", []))
    for model in ctx.pkg.modules.values():
        if not _in_scope(model.path, scope):
            continue
        for node in ctx.walk_module(model):
            if not isinstance(node, ast.Raise):
                continue
            name = _raise_class(node)
            if name is None or name in exempt:
                continue
            if ctx.disposition_of(name) is not None:
                continue
            ctx.emit(
                "FLOW301", model, node,
                f"`raise {name}(...)` in a crash/verdict-bearing module "
                f"but `{name}` (and every visible ancestor) has no "
                "DISPOSITIONS row — classify it in node/exit.py "
                "(REFUSE/REPAIR/RECOVER/PROPAGATE) so triage() and the "
                "recovery ladder route it consciously",
            )


# ---------------------------------------------------------------------------
# FLOW302 / FLOW303 — handlers on the recovery + verdict planes
# ---------------------------------------------------------------------------


def _handler_names(h: ast.ExceptHandler) -> list[str]:
    if h.type is None:
        return []
    elts = list(h.type.elts) if isinstance(h.type, ast.Tuple) else [h.type]
    out = []
    for e in elts:
        chain = _attr_chain(e)
        if chain:
            out.append(chain[-1])
    return out


def _handler_reraises(h: ast.ExceptHandler) -> bool:
    return any(isinstance(s, ast.Raise) for st in h.body
               for s in ast.walk(st))


def _handler_triages(h: ast.ExceptHandler) -> bool:
    for st in h.body:
        for s in ast.walk(st):
            if isinstance(s, ast.Call) and \
                    _call_name(s) in ("triage", "recoverable"):
                return True
    return False


def _handler_forwards(h: ast.ExceptHandler) -> bool:
    """`except X as e:` whose body USES `e` (returns it as a verdict
    tuple, records it, wraps it) forwards the fault instead of
    swallowing it — the PBft host fold's `return st, i, e` idiom."""
    if h.name is None:
        return False
    return any(isinstance(s, ast.Name) and s.id == h.name
               for st in h.body for s in ast.walk(st))


def _check_handlers(ctx: _Ctx) -> None:
    ladder = set(ctx.closure(ctx.cfg.get("ladder", {}).get("roots", [])))
    verdict = set(ctx.closure(ctx.cfg.get("verdict_roots", [])))
    scope = ctx.cfg.get("raise_scope", [])
    sanctioned = ctx.cfg.get("sanctioned_broad", [])
    for model in ctx.pkg.modules.values():
        for node in ctx.walk_module(model):
            if not isinstance(node, ast.ExceptHandler):
                continue
            info = ctx.owner_of(node)
            fq = ctx.fq(model, info)
            # FLOW302: the ladder explicitly absorbing REFUSE/REPAIR
            if fq in ladder and not _handler_reraises(node) \
                    and not _handler_triages(node):
                for name in _handler_names(node):
                    d = ctx.disposition_of(name)
                    if d in ("refuse", "repair"):
                        ctx.emit(
                            "FLOW302", model, node,
                            f"handler on the recovery/retire plane "
                            f"(`{fq}`) catches `{name}` — a "
                            f"{d.upper()}-classified type — without "
                            "re-raising or consulting triage(): the "
                            "ladder would launder what the "
                            f"{d}-owner must see (PR 13 bug class)",
                        )
            # FLOW303: silent broad handler on a verdict path, within
            # the crash/verdict-bearing module scope (observability
            # helpers deep in the closure are not verdict producers)
            if fq in verdict and _in_scope(model.path, scope) \
                    and _is_broad_handler(node) \
                    and _handler_is_silent(node) \
                    and not _handler_forwards(node):
                ctx.emit(
                    "FLOW303", model, node,
                    f"broad handler in `{fq}` on a verdict-producing "
                    "path neither raises nor calls anything — a "
                    "swallowed fault here fabricates a verdict; "
                    "re-raise, or route through the recovery ladder",
                )
            # FLOW306: bare / BaseException outside sanctioned seams
            bare = node.type is None
            base_exc = any(n == "BaseException"
                           for n in _handler_names(node))
            if (bare or base_exc) and not _handler_reraises(node):
                if info is not None and any(
                        _matches(fq, s) for s in sanctioned):
                    continue
                what = "bare `except:`" if bare \
                    else "`except BaseException:`"
                ctx.emit(
                    "FLOW306", model, node,
                    f"{what} in `{fq}` does not re-raise and is not a "
                    "sanctioned seam (flow_roots.json "
                    "`sanctioned_broad`) — it can absorb "
                    "KeyboardInterrupt/SystemExit and mask shutdown",
                )


# ---------------------------------------------------------------------------
# FLOW304 — the degradation lattice must be closed
# ---------------------------------------------------------------------------


def _parse_ladder_table(model, table_name: str) -> dict[str, list[str]]:
    out: dict[str, list[str]] = {}
    for stmt in model.tree.body:
        tgt = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            tgt = stmt.targets[0].id
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            tgt = stmt.target.id
        if tgt != table_name or not isinstance(
                getattr(stmt, "value", None), ast.Dict):
            continue
        for k, v in zip(stmt.value.keys, stmt.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            if isinstance(v, (ast.Tuple, ast.List)):
                rungs = [e.value for e in v.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)]
                out[k.value] = rungs
    return out


def _parse_router(info) -> dict[str, set[str]]:
    """The `_run_rung` if-chain: rung-name constant -> the call names in
    that branch (the rung's re-validation route)."""
    out: dict[str, set[str]] = {}
    for node in _own_nodes(info.node):
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if not (isinstance(t, ast.Compare) and len(t.comparators) == 1):
            continue
        const = None
        for side in (t.left, t.comparators[0]):
            if isinstance(side, ast.Constant) and isinstance(side.value,
                                                             str):
                const = side.value
        if const is None:
            continue
        calls = {
            _call_name(s)
            for st in node.body for s in ast.walk(st)
            if isinstance(s, ast.Call) and _call_name(s)
        }
        out.setdefault(const, set()).update(calls)
    return out


def _check_lattice(ctx: _Ctx) -> list[str]:
    """(a) LADDERS wellformedness. Returns the rung-edge inventory."""
    spec = ctx.cfg.get("ladder", {})
    edges: list[str] = []
    lad_model = None
    for model in ctx.pkg.modules.values():
        if _matches(model.modname, spec.get("module", "")):
            lad_model = model
            break
    if lad_model is None:
        return edges
    table = _parse_ladder_table(lad_model, spec.get("table", "LADDERS"))
    router_info = None
    for fq, info in ctx.funcs.items():
        if info.module == lad_model.modname and \
                _matches(fq, spec.get("router", "")):
            router_info = info
            break
    routes = _parse_router(router_info) if router_info is not None else {}
    terminal = spec.get("terminal", "")
    anchor = router_info.node if router_info is not None \
        else lad_model.tree
    for backend, rungs in sorted(table.items()):
        for a, b in zip(rungs, rungs[1:]):
            edges.append(f"{backend}:{a}->{b}")
        for rung in rungs:
            if rung not in routes:
                ctx.emit(
                    "FLOW304", lad_model, anchor,
                    f"LADDERS[{backend!r}] names rung `{rung}` but the "
                    f"router `{spec.get('router')}` has no branch for "
                    "it — the escalation would die in ValueError "
                    "instead of degrading",
                )
        if not rungs or terminal not in routes.get(rungs[-1], set()):
            ctx.emit(
                "FLOW304", lad_model, anchor,
                f"LADDERS[{backend!r}] does not end in a rung that "
                f"routes to the exact-host-reference terminal "
                f"`{terminal}` — the `{backend}` chain has no floor "
                "that cannot fail for device reasons",
            )
    for rung, calls in sorted(routes.items()):
        for c in sorted(calls):
            edges.append(f"{rung}=>{c}")
    return sorted(set(edges))


def _check_dispatch_coverage(ctx: _Ctx) -> None:
    """(b) every device dispatch site reachable from a protector."""
    disp = ctx.cfg.get("dispatch", {})
    names = set(disp.get("functions", []))
    protectors = set(disp.get("protectors", []))
    exclude = disp.get("exclude", [])
    spec = ctx.cfg.get("ladder", {})
    # P: protector callers + the protectors themselves + the ladder
    seeds = []
    for fq, info in ctx.funcs.items():
        bare = fq.rsplit(".", 1)[-1]
        if bare in protectors:
            seeds.append(fq)
            continue
        for sub in ctx.own(info):
            if isinstance(sub, ast.Call) and _call_name(sub) in protectors:
                seeds.append(fq)
                break
    seeds.extend(spec.get("roots", []))
    covered = ctx.closure(seeds)
    for model in ctx.pkg.modules.values():
        if _in_scope(model.path, exclude):
            continue
        for node in ctx.walk_module(model):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) in names):
                continue
            info = ctx.owner_of(node)
            fq = ctx.fq(model, info)
            if info is not None and fq in covered:
                continue
            ctx.emit(
                "FLOW304", model, node,
                f"device dispatch `{_call_name(node)}` in `{fq}` is "
                "not reachable from any recovery protector "
                f"({'/'.join(sorted(protectors))}) or the ladder — a "
                "device fault here has no rung to fall to and no "
                "exact-host-reference floor",
            )


# ---------------------------------------------------------------------------
# FLOW305 — kill-switch integrity
# ---------------------------------------------------------------------------


def _env_attr(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id == "environ"


def _reads_in(node: ast.AST, consts: dict[str, str],
              levers: set[str]) -> set[str]:
    """Lever names read anywhere inside `node` (the envlevers stdlib
    seams, constant-aware through module/function string consts)."""
    def resolve(n):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            return n.value
        if isinstance(n, ast.Name):
            return consts.get(n.id)
        return None

    out: set[str] = set()

    def note(n):
        name = resolve(n)
        if name in levers:
            out.add(name)

    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute):
                if fn.attr in ("get", "pop") and _env_attr(fn.value) \
                        and sub.args:
                    note(sub.args[0])
                elif fn.attr == "getenv" \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id == "os" and sub.args:
                    note(sub.args[0])
        elif isinstance(sub, ast.Subscript):
            if _env_attr(sub.value) and isinstance(sub.ctx, ast.Load):
                note(sub.slice)
        elif isinstance(sub, ast.Compare):
            if len(sub.ops) == 1 \
                    and isinstance(sub.ops[0], (ast.In, ast.NotIn)) \
                    and _env_attr(sub.comparators[0]):
                note(sub.left)
    return out


def _kill_switches(ctx: _Ctx, rel_to: str) -> list[str]:
    """The `=0` rows of the obs/README "## Levers" table (or the
    `kill_switches` config override in fixture sweeps)."""
    override = ctx.cfg.get("kill_switches")
    if override is not None:
        return sorted(override)
    from .envlevers import kill_switch_levers
    readme = os.path.join(rel_to, "ouroboros_consensus_tpu", "obs",
                          "README.md")
    if not os.path.exists(readme):
        return []
    return sorted(kill_switch_levers(readme))


def _check_levers(ctx: _Ctx, rel_to: str) -> list[str]:
    levers = set(_kill_switches(ctx, rel_to))
    if not levers:
        return []
    read_sites: dict[str, list] = {L: [] for L in levers}
    guards: dict[str, int] = {L: 0 for L in levers}
    # phase 1: per-function/module-level units + who reads what (a
    # function that reads L is a predicate-for-L: `if enabled():`
    # anywhere then counts as a guard on L)
    pred_bare: dict[str, set[str]] = {L: set() for L in levers}
    units = []  # (model, info|None, own_nodes, consts)
    for model in ctx.pkg.modules.values():
        consts = dict(model.str_consts)
        for info in model.functions.values():
            units.append((model, info, ctx.own(info), consts))
        top = [n for n in ctx.walk_module(model)
               if id(n) not in ctx.owner]
        units.append((model, None, top, consts))
    for model, info, nodes, consts in units:
        for sub in nodes:
            if not isinstance(sub, (ast.Call, ast.Subscript,
                                    ast.Compare)):
                continue
            for L in _reads_in(sub, consts, levers):
                read_sites[L].append((model.path, sub.lineno, model,
                                      sub))
                if info is not None:
                    pred_bare[L].add(info.qualname.rsplit(".", 1)[-1])

    def levers_of(expr: ast.AST, consts: dict,
                  env: dict[str, set[str]]) -> set[str]:
        """Levers an expression is derived from: direct env reads,
        lever-derived names (`NONCE_SCAN and carry is not None`), and
        predicate calls (`columnar = _columnar_enabled()`)."""
        out = set(_reads_in(expr, consts, levers))
        for t in ast.walk(expr):
            if isinstance(t, ast.Name) and t.id in env:
                out |= env[t.id]
            elif isinstance(t, ast.Call):
                cn = _call_name(t)
                for L in levers:
                    if cn in pred_bare[L]:
                        out.add(L)
        return out

    # phase 2: module-level lever-derived names (`NONCE_SCAN = ...`)
    mod_vars: dict[str, dict[str, set[str]]] = {}
    for model in ctx.pkg.modules.values():
        mv: dict[str, set[str]] = {}
        for stmt in model.tree.body:
            if isinstance(stmt, ast.Assign) \
                    and not isinstance(stmt.value, ast.Constant):
                ls = levers_of(stmt.value, model.str_consts, mv)
                if ls:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            mv[t.id] = set(ls)
        mod_vars[model.modname] = mv
    # phase 3: guard sites — If/While/IfExp tests consuming a lever
    # read, a lever-derived local/module name, or a predicate call
    for model, info, nodes, consts in units:
        lever_vars: dict[str, set[str]] = dict(
            mod_vars.get(model.modname, {}))
        for sub in nodes:
            if isinstance(sub, ast.Assign) \
                    and not isinstance(sub.value, ast.Constant):
                ls = levers_of(sub.value, consts, lever_vars)
                for t in sub.targets:
                    if isinstance(t, ast.Name) and ls:
                        lever_vars[t.id] = set(ls)
        for sub in nodes:
            if not isinstance(sub, (ast.If, ast.While, ast.IfExp)):
                continue
            hit = levers_of(sub.test, consts, lever_vars)
            if not hit:
                continue
            for L in hit:
                guards[L] += 1
            if isinstance(sub, ast.If) and sub.orelse:
                body_calls = {
                    _call_name(s) for st in sub.body
                    for s in ast.walk(st)
                    if isinstance(s, ast.Call) and _call_name(s)}
                else_calls = {
                    _call_name(s) for st in sub.orelse
                    for s in ast.walk(st)
                    if isinstance(s, ast.Call) and _call_name(s)}
                if body_calls and body_calls == else_calls:
                    for L in sorted(hit):
                        ctx.emit(
                            "FLOW305", model, sub,
                            f"kill-switch `{L}` gates branches with "
                            "identical callees "
                            f"({', '.join(sorted(body_calls))}) — the "
                            "false branch re-enters the levered "
                            "implementation, so `=0` changes nothing",
                        )
    for L in sorted(levers):
        if guards[L]:
            continue
        sites = sorted(read_sites[L], key=lambda s: (s[0], s[1]))
        msg = (f"documented kill-switch `{L}` never guards a branch — "
               "no if/while/predicate test consumes it (dead lever: "
               "operators set `=0` and silently get nothing)")
        if sites:
            _, _, model, node = sites[0]
            ctx.emit("FLOW305", model, node, msg)
        else:
            ctx.findings.append(Finding(
                "FLOW305", "ouroboros_consensus_tpu/obs/README.md", 0, 0,
                msg + " — and nothing under the swept roots reads it",
            ))
    return [f"{L}:guards={guards[L]}" for L in sorted(levers)]


# ---------------------------------------------------------------------------
# FLOW307 — pinned exact-reference re-dispatch routes
# ---------------------------------------------------------------------------


def _check_redispatch(ctx: _Ctx) -> None:
    pins: dict[str, list[str]] = ctx.cfg.get("redispatch_pins", {})
    for pin, required in sorted(pins.items()):
        # only when the pin's module is part of this sweep (partial
        # `--paths` sweeps must not fabricate missing-function
        # findings); longest modname wins so `pkg.protocol.tpraos.X`
        # anchors to the tpraos module, not the package __init__
        owner_model = None
        for model in ctx.pkg.modules.values():
            if pin == model.modname \
                    or pin.startswith(model.modname + "."):
                if owner_model is None or \
                        len(model.modname) > len(owner_model.modname):
                    owner_model = model
        if owner_model is None:
            continue
        matched = [info for fq, info in ctx.funcs.items()
                   if _matches(fq, pin)]
        if not matched:
            ctx.emit(
                "FLOW307", owner_model, owner_model.tree,
                f"redispatch pin `{pin}` names a function that no "
                "longer exists — re-route the pin or restore the "
                "reference seam",
            )
            continue
        for info in matched:
            called = {
                _call_name(s) for s in ctx.own(info)
                if isinstance(s, ast.Call) and _call_name(s)}
            missing = [r for r in required if r not in called]
            if missing:
                model = ctx.pkg.modules[info.module]
                ctx.emit(
                    "FLOW307", model, info.node,
                    f"re-dispatch site `{pin}` no longer calls its "
                    f"pinned exact-reference callee(s) "
                    f"{', '.join(missing)} — the anomaly route has "
                    "drifted off the reference set the differential "
                    "suites pin",
                )


# ---------------------------------------------------------------------------
# Inventory + sweep
# ---------------------------------------------------------------------------


def _inventory(ctx: _Ctx, rung_edges: list[str],
               levers: list[str]) -> dict:
    raises_inv = set()
    scope = ctx.cfg.get("raise_scope", [])
    handlers = set()
    for model in ctx.pkg.modules.values():
        for node in ctx.walk_module(model):
            if isinstance(node, ast.Raise) and \
                    _in_scope(model.path, scope):
                name = _raise_class(node)
                if name:
                    info = ctx.owner_of(node)
                    raises_inv.add(f"{ctx.fq(model, info)}:{name}")
            elif isinstance(node, ast.ExceptHandler):
                info = ctx.owner_of(node)
                names = _handler_names(node)
                spec = "bare" if node.type is None \
                    else "+".join(sorted(names)) if names else "dynamic"
                handlers.add(f"{ctx.fq(model, info)}:{spec}")
    return {
        "raise_sites": sorted(raises_inv),
        "handlers": sorted(handlers),
        "rung_edges": rung_edges,
        "levers": levers,
    }


@dataclasses.dataclass
class FlowReport:
    findings: list
    inventory: dict


def sweep_paths(paths: list[str], rel_to: str | None = None,
                roots_table: dict | None = None) -> FlowReport:
    rel = rel_to or os.path.dirname(os.path.abspath(paths[0]))
    cfg = roots_table or load_roots()
    pkg = SyncPackage([p for p in paths if os.path.exists(p)], rel,
                      threads=False)
    ctx = _Ctx(pkg, cfg, rel)
    _check_raises(ctx)
    _check_handlers(ctx)
    rung_edges = _check_lattice(ctx)
    _check_dispatch_coverage(ctx)
    levers = _check_levers(ctx, rel)
    _check_redispatch(ctx)
    # FLOW308 runs last: it audits which declarations the rules above
    # actually consumed
    for supp in ctx.supp.values():
        ctx.findings.extend(supp.stale())
    findings = sorted(ctx.findings, key=lambda f: (f.path, f.line, f.rule))
    counts: dict[str, int] = {}
    out: list[Finding] = []
    for f in findings:
        base = f"{f.rule}::{f.path}::{f.message}"
        n = counts.get(base, 0)
        counts[base] = n + 1
        out.append(dataclasses.replace(f, seq=n) if n else f)
    return FlowReport(out, _inventory(ctx, rung_edges, levers))


def sweep_source(source: str, name: str = "<memory>",
                 roots_table: dict | None = None) -> list[Finding]:
    """Sweep a single source string (fixture tests)."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, f"{name}.py")
        with open(p, "w", encoding="utf-8") as f:
            f.write(source)
        rep = sweep_paths([p], rel_to=d, roots_table=roots_table)
    return [dataclasses.replace(f, path=name) for f in rep.findings]


def default_roots(repo_root: str | None = None) -> list[str]:
    repo = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return [os.path.join(repo, "ouroboros_consensus_tpu"),
            os.path.join(repo, "scripts"),
            os.path.join(repo, "bench.py")]


def load_baseline(path: str | None = None) -> dict:
    with open(path or _BASELINE_PATH, encoding="utf-8") as f:
        return json.load(f)


def baseline_payload(report: FlowReport) -> dict:
    return {
        "comment": "octflow ratchet (scripts/lint.py --update-flow): "
                   "grandfathered finding keys + the line-number-free "
                   "failure-routing inventory (raise sites, handlers, "
                   "rung edges, kill-switch guard counts). Shrink-only "
                   "in normal operation.",
        "findings": sorted({f.key() for f in report.findings
                            if not f.suppressed}),
        "inventory": report.inventory,
    }


def write_baseline(report: FlowReport, path: str | None = None) -> dict:
    payload = baseline_payload(report)
    with open(path or _BASELINE_PATH, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


def check_flow(report: FlowReport, baseline: dict | None = None) \
        -> tuple[list[str], list[str]]:
    """(violations, stale_notes) vs the flow.json ratchet: a new
    unsuppressed finding or inventory drift is a violation; a baseline
    key that stopped firing is a ratchet-tightening note."""
    base = baseline if baseline is not None else load_baseline()
    known = set(base.get("findings", []))
    violations = [
        f.format() for f in report.findings
        if not f.suppressed and f.key() not in known
    ]
    pinned = base.get("inventory", {})
    for section, now in report.inventory.items():
        then = pinned.get(section, [])
        gained = sorted(set(now) - set(then))
        lost = sorted(set(then) - set(now))
        if gained or lost:
            delta = "; ".join(
                ([f"new: {', '.join(gained)}"] if gained else []) +
                ([f"gone: {', '.join(lost)}"] if lost else [])
            )
            violations.append(
                f"inventory drift in `{section}` ({delta}) — review and "
                "re-pin with scripts/lint.py --update-flow"
            )
    current = {f.key() for f in report.findings if not f.suppressed}
    stale = sorted(known - current)
    return violations, stale
