"""octrange — abstract interpretation over the registered crypto jaxprs.

Pass 3 of the analysis subsystem: a jaxpr interpreter (no XLA compile,
no device — pure Python over the traced graph, cheap on the 1-core box)
in the classical Cousot & Cousot (POPL'77) style, instantiated with the
two domains in analysis/domains.py:

  range  — interval/overflow certification at PER-ROW granularity
           along the limb axis (axis 0 for the limb-first ops/pk
           kernels, the MINOR axis for the XLA-twin ops/field.py
           [..., 20] layout — domains.Rows / domains.LastRows). Input
           bounds are seeded from analysis/shapes.json (wire bytes
           0..255, nearly normalized limbs <= B_MAX, ...); transfer
           functions cover the op vocabulary the registered graphs
           actually use; scan/fori bodies run to a fixpoint with
           threshold widening (affine induction counters are pinned to
           their exact closed form instead). Any SIGNED-int eqn whose
           inferred bound leaves its dtype range, and any
           convert_element_type that truncates a non-proven-narrow
           value, is a finding. Unsigned wrap is DEFINED XLA semantics
           (the SHA-512/Blake2b lanes rely on it) and clamps to the
           full dtype range silently.

           Per-row is the load-bearing design point: the limb kernels'
           carry headroom is a PER-ROW invariant. `limbs.mul` folds its
           row 40 with weight FOLD^2 = 369664, which is only safe
           because rows 39-40 receive nothing but second-order carry
           residues (<= 1 after two passes); `limbs.sub` adds the SUBC
           column whose TOP limb is 12287 while the others reach
           2^15.5, so the FOLD-weighted top-row carry is <= 2 only
           per-row. A whole-tensor interval provably cannot certify
           either (it reports top*FOLD^2 as ~3.0e9 > 2^31) — measured
           before this rewrite as ~4k false overflow findings on
           ed_core alone. The LastRows mirror buys the same proof for
           the batch-major twin: field.mul's `.at[..., 0].add(top *
           FOLD^2)` is exactly the axis-transposed fold.

  taint  — secret-independence in the ct-verif spirit (Almeida et al.,
           USENIX Security'16), with two levels: `wire` (untrusted but
           PUBLIC header data — everything a verifier sees) and
           `secret` (sign-path scalars/nonces). ANY taint reaching a
           cond/while predicate is a finding (data-dependent control
           flow is also the TPU batch-uniformity hazard); SECRET taint
           reaching a gather/scatter/dynamic-slice index or a sort key
           is a finding (secret-dependent access pattern). Wire taint
           may steer access patterns: the MSM's per-window argsort runs
           over Fiat–Shamir coefficients, which are deterministic
           functions of PUBLIC wire bytes — public data cannot leak
           through timing, so the sort is clean by policy and the
           certificate records the wire marks that reached it
           (Report.wire_steered).

Lane-count soundness: bounds are certified either at explicit
production lane counts (the lane-SENSITIVE graphs — msm bucket counts,
sum_mod_l lane sums, verdict popcounts — re-traced at the shapes.json
sweep sizes; tracing cost is lane-count independent) or as
LANE-UNIVERSAL certificates: the interpreter records every axis size
that ever scales a bound (reduce/cumsum/dot contractions, iota
extents, collective axes), and if the traced lane-tile size never
appears in that set, no transfer ever consulted it, so the inferred
bounds hold verbatim at every lane count. (Trace-time Python
arithmetic on the lane count — baked literals — would evade the check;
exactly the graphs whose builders do that, msm/aggregate/verdict/spmd,
are the ones certified by explicit sweep instead.)

Certification results are pinned in analysis/certified.json (a ratchet
like baseline.json): scripts/lint.py fails when a graph loses its
proof or grows a taint finding beyond its pinned set.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

import numpy as np

from . import domains as D
from . import graphs

_SHAPES_PATH = os.path.join(os.path.dirname(__file__), "shapes.json")
_CERTIFIED_PATH = os.path.join(os.path.dirname(__file__), "certified.json")

# call-like primitives whose subjaxpr runs once with the caller's values
_CALL_PRIMS = {
    "pjit", "closed_call", "core_call", "remat", "checkpoint",
    "custom_jvp_call", "custom_vjp_call", "custom_partitioning",
}
# eqns whose (signed) result must fit the dtype — arithmetic that can
# actually overflow. Bitwise/select/shape ops always fit by construction.
_ARITH_CHECK = {
    "add", "sub", "mul", "neg", "abs", "dot_general", "reduce_sum",
    "cumsum", "scatter-add", "shift_left", "integer_pow", "psum",
    "reduce_prod", "cumprod", "pow",
}
# number of plain joins before widening kicks in, and the iteration cap
_FIX_JOINS = 2
_FIX_MAX = 24
# collective scale certified for psum/axis_index: bounds hold for any
# mesh up to this many devices along the batch axis (the traced mesh is
# a single CPU device; production meshes are orders of magnitude below
# this)
SPMD_AXIS_SCALE = 4096
# row-tracking cap: per-row intervals materialize only for axis-0
# extents up to this (the limb/byte axes are <= 41/400); anything
# larger collapses to a whole-tensor bound
ROW_CAP = 512


def _src_of(eqn) -> str:
    try:
        from jax._src import source_info_util

        s = source_info_util.summarize(eqn.source_info)
        # keep the path repo-relative and stable across checkouts
        for marker in ("ouroboros_consensus_tpu/", "tests/", "scripts/"):
            i = s.find(marker)
            if i > 0:
                return s[i:]
        return s
    except Exception:
        return "<unknown>"


@dataclasses.dataclass(frozen=True)
class Finding:
    kind: str  # overflow | truncate | unknown-prim |
    #            taint-branch | taint-index | taint-sort | taint-output
    graph: str
    prim: str
    src: str
    message: str

    def key(self) -> str:
        return f"{self.kind}::{self.graph}::{self.prim}::{self.src}"

    def format(self) -> str:
        return (f"{self.graph}: {self.kind} at {self.src} "
                f"[{self.prim}] {self.message}")


@dataclasses.dataclass
class Report:
    graph: str
    domain: str  # "range" | "taint"
    lanes: int | None  # explicit lane count, or None = registry tile
    ok: bool
    findings: list
    eqns: int = 0
    scale_factors: tuple = ()
    lane_universal: bool = False
    output_taint: tuple = ()  # taint domain: union of output marks
    wire_steered: tuple = ()  # taint domain: wire marks at sort/index sites

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["findings"] = [f.format() for f in self.findings]
        d["scale_factors"] = sorted(self.scale_factors)
        d["output_taint"] = sorted(self.output_taint)
        d["wire_steered"] = sorted(self.wire_steered)
        return d


def _dedup(findings: list) -> list:
    """One finding per (kind, src, prim) key, first occurrence wins —
    a memo-missed subjaxpr can report the same source eqn thousands of
    times across call paths."""
    seen: set[str] = set()
    out = []
    for f in findings:
        k = f.key()
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def _int_range(dtype) -> tuple[int, int] | None:
    import jax.numpy as jnp

    d = jnp.dtype(dtype)
    if d == jnp.dtype(bool):
        return (0, 1)
    if np.issubdtype(d, np.integer):
        info = np.iinfo(d)
        return (int(info.min), int(info.max))
    return None  # float — no range checks


def _is_signed(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.signedinteger)


def _is_literal(atom) -> bool:
    return hasattr(atom, "val")


def _sub_closed(eqn, key):
    """params[key] as (jaxpr, consts) whether it's closed or open."""
    v = eqn.params[key]
    if hasattr(v, "jaxpr"):  # ClosedJaxpr
        return v.jaxpr, v.consts
    return v, ()


# ---------------------------------------------------------------------------
# Shared driver
# ---------------------------------------------------------------------------


class _Interp:
    """Control-flow driver shared by both domains. Subclasses provide
    per-primitive transfer functions plus const/literal abstraction and
    the join/widen/eq lattice ops."""

    def __init__(self, graph_name: str):
        self.graph = graph_name
        self.findings: list[Finding] = []
        self.eqns = 0
        self.scale_factors: set[int] = set()
        self._memo: dict = {}
        self._const_memo: dict = {}
        self._recording = True
        self._defs: dict = {}
        # test hook (tests/test_absint.py soundness property): when set
        # to a list, collects (eqn, abstract_outs) for every TOP-level
        # eqn so a concrete eqn-by-eqn replay can check containment
        self.eqn_log: list | None = None
        self._level = 0

    # -- lattice hooks (subclass) -------------------------------------------

    def abs_const(self, c):
        raise NotImplementedError

    def abs_literal(self, lit):
        raise NotImplementedError

    def join(self, a, b):
        raise NotImplementedError

    def widen(self, old, new):
        return self.join(old, new)

    def per_step(self, x):
        """Abstraction of one scan step's slice of a stacked value (and
        of one step's output inside the stacked result): axis 0 is the
        SCAN axis there, so axis-0 row structure does not transfer."""
        return x

    def transfer(self, eqn, prim, ins, record):
        raise NotImplementedError

    # -- driver --------------------------------------------------------------

    def record(self, kind, eqn, message):
        self.findings.append(Finding(
            kind, self.graph, eqn.primitive.name, _src_of(eqn), message,
        ))

    def run_closed(self, closed_jaxpr, in_abs, record=True):
        jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        consts = getattr(closed_jaxpr, "consts", ())
        return self.run_jaxpr(jaxpr, consts, in_abs, record)

    def run_jaxpr(self, jaxpr, consts, in_abs, record=True):
        env: dict = {}
        defs: dict = {}
        self._level += 1
        for v, c in zip(jaxpr.constvars, consts):
            env[v] = self.abs_const(c)
        assert len(jaxpr.invars) == len(in_abs), (
            len(jaxpr.invars), len(in_abs))
        for v, a in zip(jaxpr.invars, in_abs):
            env[v] = a

        def read(atom):
            if _is_literal(atom):
                return self.abs_literal(atom)
            return env[atom]

        try:
            for eqn in jaxpr.eqns:
                self.eqns += 1
                prim = eqn.primitive.name
                ins = [read(a) for a in eqn.invars]
                if prim in _CALL_PRIMS:
                    outs = self._call(eqn, ins, record)
                elif prim == "scan":
                    outs = self._scan(eqn, ins, record)
                elif prim == "while":
                    outs = self._while(eqn, ins, record)
                elif prim == "cond":
                    outs = self._cond(eqn, ins, record)
                elif prim == "shard_map":
                    outs = self._shard_map(eqn, ins, record)
                else:
                    self._defs = defs
                    outs = self.transfer(eqn, prim, ins, record)
                if len(outs) != len(eqn.outvars):
                    raise AssertionError(
                        f"{prim}: {len(outs)} abstract outputs for "
                        f"{len(eqn.outvars)} outvars"
                    )
                if self.eqn_log is not None and self._level == 1 and record:
                    self.eqn_log.append((eqn, list(outs)))
                for v, o in zip(eqn.outvars, outs):
                    env[v] = o
                    defs[v] = eqn
            return [read(v) for v in jaxpr.outvars]
        finally:
            self._level -= 1

    def _call(self, eqn, ins, record):
        key_name = "jaxpr" if "jaxpr" in eqn.params else "call_jaxpr"
        sub, consts = _sub_closed(eqn, key_name)
        if eqn.primitive.name in ("custom_jvp_call", "custom_vjp_call"):
            # the call_jaxpr takes exactly the primal inputs
            ins = ins[: len(sub.invars)]
        return self._memoized(sub, consts, ins, record)

    def _memoized(self, sub, consts, ins, record):
        try:
            # keyed by the recording flag too: a non-recording
            # (fixpoint) hit must never mask the findings a recording
            # pass would have produced
            key = (id(sub), record, tuple(ins))
            hit = self._memo.get(key)
        except TypeError:  # unhashable abstract value (never expected)
            key = hit = None
        if hit is not None:
            outs, sub_findings, sub_eqns, sub_scales = hit
            self.eqns += sub_eqns
            self.scale_factors |= sub_scales
            self.findings.extend(sub_findings)
            return outs
        f0, e0, s0 = len(self.findings), self.eqns, set(self.scale_factors)
        outs = self.run_jaxpr(sub, consts, ins, record)
        if key is not None:
            self._memo[key] = (
                outs,
                tuple(self.findings[f0:]),
                self.eqns - e0,
                self.scale_factors - s0,
            )
        return outs

    def _scan(self, eqn, ins, record):
        p = eqn.params
        sub, consts = _sub_closed(eqn, "jaxpr")
        nc, ncar = p["num_consts"], p["num_carry"]
        sc = ins[:nc]
        carry = list(ins[nc: nc + ncar])
        # affine induction variables (fori_loop counters lower to a
        # `carry_out = carry_in + 1` scan carry) have an EXACT closed
        # form over the known trip count — pin them instead of widening
        # (a widened counter reaches int32 max and its next `i + 1`
        # would report a false overflow)
        pinned = self.pin_scan_carries(
            sub, nc, ncar, p.get("length", 0), carry
        )
        for k, a in pinned.items():
            carry[k] = a
        # per-step slice of each xs: axis 0 is the scan axis, so any
        # axis-0 row structure collapses to a step-universal bound
        xs = [self.per_step(x) for x in ins[nc + ncar:]]

        def step(cur):
            for k, a in pinned.items():
                cur[k] = a
            return self._memoized(sub, consts, sc + cur + xs, False)

        carry = self._fixpoint(step, carry, ncar)
        for k, a in pinned.items():
            carry[k] = a
        outs = self._memoized(sub, consts, sc + carry + xs, record)
        final_carry = [
            self.join(i, o) for i, o in zip(ins[nc: nc + ncar], outs[:ncar])
        ]
        # stacked ys: the new leading axis is the step axis
        return final_carry + [self.per_step(y) for y in outs[ncar:]]

    def _while(self, eqn, ins, record):
        p = eqn.params
        cj, cc = _sub_closed(eqn, "cond_jaxpr")
        bj, bc = _sub_closed(eqn, "body_jaxpr")
        ncc, nbc = p["cond_nconsts"], p["body_nconsts"]
        cond_consts = ins[:ncc]
        body_consts = ins[ncc: ncc + nbc]
        init = list(ins[ncc + nbc:])
        carry = self._fixpoint(
            lambda cur: self._memoized(bj, bc, body_consts + cur, False),
            init, len(init),
        )
        pred = self._memoized(cj, cc, cond_consts + carry, record)
        self.on_while_pred(eqn, pred[0], record)
        outs = self._memoized(bj, bc, body_consts + carry, record)
        return [self.join(i, o) for i, o in zip(init, outs)]

    def _fixpoint(self, step, carry, ncar):
        for it in range(_FIX_MAX):
            outs = step(list(carry))
            new = [self.join(c, o) for c, o in zip(carry, outs[:ncar])]
            if new == carry:
                return carry
            if it >= _FIX_JOINS:
                new = [self.widen(c, n) for c, n in zip(carry, new)]
            carry = new
        return carry  # widening ladder guarantees we land here stable

    def _cond(self, eqn, ins, record):
        self.on_cond_pred(eqn, ins[0], record)
        outs = None
        for br in eqn.params["branches"]:
            sub, consts = _sub_closed_value(br)
            o = self._memoized(sub, consts, ins[1:], record)
            outs = o if outs is None else [
                self.join(a, b) for a, b in zip(outs, o)
            ]
        return outs

    def _shard_map(self, eqn, ins, record):
        sub, consts = _sub_closed(eqn, "jaxpr")
        return self.run_jaxpr(sub, consts, ins, record)

    # taint hooks; the interval domain ignores predicates
    def on_cond_pred(self, eqn, pred, record):
        pass

    def on_while_pred(self, eqn, pred, record):
        pass

    # interval hook; other domains have no notion of a counter
    def pin_scan_carries(self, sub, nc, ncar, length, carry):
        return {}


def _sub_closed_value(v):
    if hasattr(v, "jaxpr"):
        return v.jaxpr, v.consts
    return v, ()


# ---------------------------------------------------------------------------
# Interval domain (per-row)
# ---------------------------------------------------------------------------


class IntervalInterp(_Interp):
    def _minmax(self, a):
        if a.size == 0:
            return (0, 0)
        if a.dtype == np.bool_:
            return (int(a.min()), int(a.max()))
        if np.issubdtype(a.dtype, np.floating):
            return (float(a.min()), float(a.max()))
        return (int(a.min()), int(a.max()))

    def abs_const(self, c):
        key = id(c)
        hit = self._const_memo.get(key)
        if hit is None:
            a = np.asarray(c)
            if a.size == 0:
                hit = (0, 0)
            elif a.ndim and 1 < a.shape[0] <= ROW_CAP:
                # per-row constants carry the limb structure the proofs
                # need (SUBC's 12287 top limb vs 2^15.5 elsewhere). A
                # rank-1 Rows doubles as last-axis structure: the
                # broadcast_in_dim that consumes it decides which
                # convention the value enters under.
                hit = D.rows(self._minmax(a[i]) for i in range(a.shape[0]))
                if (
                    not isinstance(hit, D.Rows) and a.ndim >= 2
                    and 1 < a.shape[-1] <= ROW_CAP
                ):
                    # axis-0-uniform but minor-axis-structured: the
                    # XLA-twin [..., 20] limb convention
                    hit = D.last_rows(
                        self._minmax(a[..., j]) for j in range(a.shape[-1])
                    )
            elif a.ndim >= 2 and 1 < a.shape[-1] <= ROW_CAP:
                hit = D.last_rows(
                    self._minmax(a[..., j]) for j in range(a.shape[-1])
                )
            else:
                hit = self._minmax(a)
            self._const_memo[key] = hit
        return hit

    def abs_literal(self, lit):
        v = lit.val
        if np.ndim(v) > 0:
            return self.abs_const(v)
        a = np.asarray(v)  # 0-d ndarray literals are not scalar instances
        if a.dtype == np.bool_:
            return (int(a), int(a))
        if np.issubdtype(a.dtype, np.floating):
            return (float(a), float(a))  # may be ±inf: floats are unchecked
        return D.iv_const(a)

    def join(self, a, b):
        return D.iv_join_any(a, b)

    def widen(self, old, new):
        return D.iv_widen_any(old, new)

    def per_step(self, x):
        return D.collapse(x)

    def pin_scan_carries(self, sub, nc, ncar, length, carry):
        """Affine induction variables: a SCALAR carry k whose body
        output is `carry_in[k] + c` (c a scalar literal, either sign
        via add/sub) walks init, init+c, ..., init+c*(length-1) — the
        exact interval, no widening. fori_loop counters are the
        motivating instance."""
        if not length:
            return {}
        defs = {}
        for e in sub.eqns:
            for v in e.outvars:
                defs[v] = e
        pinned = {}
        for k in range(ncar):
            inv = sub.invars[nc + k]
            if inv.aval.shape != ():
                continue
            init = carry[k]
            if isinstance(init, (D.Rows, D.LastRows)) or not isinstance(
                init[0], int
            ):
                continue
            ov = sub.outvars[k]
            if _is_literal(ov):
                continue
            e = defs.get(ov)
            if e is None or e.primitive.name not in ("add", "sub"):
                continue
            a, b = e.invars
            step = None
            if a is inv and _is_literal(b) and np.ndim(b.val) == 0:
                step = int(b.val)
                if e.primitive.name == "sub":
                    step = -step
            elif (e.primitive.name == "add" and b is inv
                  and _is_literal(a) and np.ndim(a.val) == 0):
                step = int(a.val)
            if step is None:
                continue
            span = step * (length - 1)
            pinned[k] = (init[0] + min(0, span), init[1] + max(0, span))
        return pinned

    def _check(self, eqn, prim, out, aval, record):
        """Dtype-range policy: signed overflow is a finding, unsigned
        wraps to the full range, results are clamped either way so one
        miss doesn't cascade. Checks are per-row when rows are
        tracked; the finding reports the worst row."""
        rng = _int_range(aval.dtype)
        if rng is None:
            return out
        worst = D.collapse(out)
        if rng[0] <= worst[0] and worst[1] <= rng[1]:
            return out
        if _is_signed(aval.dtype) and prim in _ARITH_CHECK:
            if record:
                self.record(
                    "overflow", eqn,
                    f"inferred bound [{worst[0]}, {worst[1]}] exceeds "
                    f"{np.dtype(aval.dtype).name} range",
                )
        # clamp rowwise (unsigned wrap is defined; signed already
        # reported — clamping stops one miss from cascading)
        if isinstance(out, (D.Rows, D.LastRows)):
            return self._map_struct(
                out,
                lambda r: (max(r[0], rng[0]),
                           min(max(r[1], rng[0]), rng[1])),
            )
        return (max(worst[0], rng[0]), min(max(worst[1], rng[0]), rng[1]))

    def transfer(self, eqn, prim, ins, record):
        out_avals = [v.aval for v in eqn.outvars]
        fn = _IV_TABLE.get(prim)
        if fn is None:
            if record:
                self.record(
                    "unknown-prim", eqn,
                    f"no interval transfer for `{prim}`; assuming full "
                    "dtype range (certification stays unproven)",
                )
            return [
                _int_range(a.dtype) or (-math.inf, math.inf)
                for a in out_avals
            ]
        self._recording = record
        outs = fn(self, eqn, ins)
        return [
            self._check(eqn, prim, o, a, record)
            for o, a in zip(outs, out_avals)
        ]

    # -- helpers -------------------------------------------------------------

    def _scaled(self, n: int):
        if n > 1:
            self.scale_factors.add(int(n))
        return n

    def _dtype_range(self, eqn):
        return _int_range(eqn.outvars[0].aval.dtype) or (
            -math.inf, math.inf)

    def _rows_for(self, x, n):
        """Length-n axis-0 row tuple for one elementwise operand
        (uniform, broadcast and other-convention operands apply their
        collapsed bound to every row)."""
        if isinstance(x, D.Rows) and len(x) == n:
            return x
        return (D.collapse(x),) * n

    def _last_rows_for(self, x, n):
        if isinstance(x, D.LastRows) and len(x) == n:
            return x
        return (D.collapse(x),) * n

    @staticmethod
    def _map_struct(x, f):
        """Apply f per row, preserving whichever convention x carries."""
        if isinstance(x, D.Rows):
            return D.rows(f(r) for r in x)
        if isinstance(x, D.LastRows):
            return D.last_rows(f(r) for r in x)
        return f(x)

    def _onehot_along(self, var, contract_dims) -> bool:
        """True when `var` is an {0,1} indicator produced by comparing
        an iota against a broadcast value, with the iota's dimension
        inside `contract_dims`: along that axis the iota values are all
        distinct, so at most ONE element per contracted row is nonzero
        and a dot against it is a SELECTION, not a sum (the one-hot MXU
        table lookups of ops/pk/curve._onehot_lookup)."""
        defs = getattr(self, "_defs", {})

        def resolve(v, dims):
            for _ in range(6):
                e = defs.get(v)
                if e is None:
                    return False
                name = e.primitive.name
                if name == "convert_element_type":
                    v = e.invars[0]
                    continue
                if name == "eq":
                    for side in e.invars:
                        if _is_literal(side):
                            continue
                        if _iota_dim_in(defs, side, dims):
                            return True
                    return False
                if name == "broadcast_in_dim":
                    bd = e.params["broadcast_dimensions"]
                    inner = {
                        i for i, d in enumerate(bd) if d in dims
                    }
                    if not inner:
                        return False
                    v, dims = e.invars[0], inner
                    continue
                return False
            return False

        return resolve(var, set(contract_dims))


def _iota_dim_in(defs, v, dims) -> bool:
    for _ in range(6):
        e = defs.get(v)
        if e is None:
            return False
        name = e.primitive.name
        if name == "iota":
            return e.params["dimension"] in dims
        if name == "broadcast_in_dim":
            bd = e.params["broadcast_dimensions"]
            inner = {i for i, d in enumerate(bd) if d in dims}
            if not inner:
                return False
            v, dims = e.invars[0], inner
            continue
        if name == "convert_element_type":
            v = e.invars[0]
            continue
        return False
    return False


# -- elementwise wrapper ------------------------------------------------------


def _ew(kernel):
    """Lift a scalar-interval kernel `kernel(self, eqn, vals) -> iv`
    to a per-row transfer: rows materialize only when some operand
    already carries them (byte columns stay uniform and cheap)."""

    def t(self, eqn, ins):
        shape = eqn.outvars[0].aval.shape
        if (
            shape and 1 < shape[0] <= ROW_CAP
            and any(isinstance(x, D.Rows) for x in ins)
        ):
            n = shape[0]
            cols = [self._rows_for(x, n) for x in ins]
            return [D.rows(
                kernel(self, eqn, [c[i] for c in cols]) for i in range(n)
            )]
        if (
            shape and 1 < shape[-1] <= ROW_CAP
            and any(isinstance(x, D.LastRows) for x in ins)
        ):
            n = shape[-1]
            cols = [self._last_rows_for(x, n) for x in ins]
            return [D.last_rows(
                kernel(self, eqn, [c[i] for c in cols]) for i in range(n)
            )]
        return [kernel(self, eqn, [D.collapse(x) for x in ins])]

    return t


def _k_add(self, eqn, v):
    return D.iv_add(v[0], v[1])


def _k_sub(self, eqn, v):
    return D.iv_sub(v[0], v[1])


def _k_mul(self, eqn, v):
    return D.iv_mul(v[0], v[1])


def _k_div(self, eqn, v):
    return D.iv_div(v[0], v[1])


def _k_rem(self, eqn, v):
    return D.iv_rem(v[0], v[1])


def _k_max(self, eqn, v):
    return (max(v[0][0], v[1][0]), max(v[0][1], v[1][1]))


def _k_min(self, eqn, v):
    return (min(v[0][0], v[1][0]), min(v[0][1], v[1][1]))


def _k_neg(self, eqn, v):
    return (-v[0][1], -v[0][0])


def _k_abs(self, eqn, v):
    lo, hi = v[0]
    m = max(abs(lo), abs(hi))
    return (0 if lo <= 0 <= hi else min(abs(lo), abs(hi)), m)


def _k_sign(self, eqn, v):
    lo, hi = v[0]
    return (-1 if lo < 0 else 0 if lo == 0 else 1,
            1 if hi > 0 else 0 if hi == 0 else -1)


def _k_and(self, eqn, v):
    return D.iv_and(v[0], v[1], self._dtype_range(eqn))


def _k_or(self, eqn, v):
    return D.iv_or(v[0], v[1], self._dtype_range(eqn))


def _k_xor(self, eqn, v):
    return D.iv_xor(v[0], v[1], self._dtype_range(eqn))


def _k_not(self, eqn, v):
    lo, hi = v[0]
    rng = self._dtype_range(eqn)
    if rng == (0, 1):
        return (0, 1)
    if not _is_signed(eqn.outvars[0].aval.dtype):
        top = rng[1]
        return (top - hi, top - lo)
    return (-hi - 1, -lo - 1)


def _k_shl(self, eqn, v):
    return D.iv_shl(v[0], v[1])


def _k_shr_arith(self, eqn, v):
    return D.iv_shr(v[0], v[1])


def _k_shr_logical(self, eqn, v):
    if v[0][0] >= 0:
        return D.iv_shr(v[0], v[1])
    return self._dtype_range(eqn)  # negative reinterpretation: bitwise


def _k_select_n(self, eqn, v):
    out = v[1]
    for x in v[2:]:
        out = D.iv_join(out, x)
    return out


def _k_clamp(self, eqn, v):
    lo_b, x, hi_b = v
    lo = max(lo_b[0], min(x[0], hi_b[1]))
    hi = min(hi_b[1], max(x[1], lo_b[0]))
    return (min(lo, hi), max(lo, hi))


def _k_ipow(self, eqn, v):
    return _ipow(v[0], eqn.params["y"])


def _ipow(a, y):
    y = int(y)
    m = max(abs(a[0]), abs(a[1]))
    hi = m ** y
    if y % 2 == 0:
        return (0, hi)
    return (min(a[0] ** y, a[1] ** y), max(a[0] ** y, a[1] ** y))


# -- structural transfers -----------------------------------------------------


def _t_identity(self, eqn, ins):
    return [ins[0]]


def _t_bool(self, eqn, ins):
    return [(0, 1)]


def _t_slice(self, eqn, ins):
    x = ins[0]
    p = eqn.params
    if isinstance(x, D.Rows):
        start, limit = p["start_indices"][0], p["limit_indices"][0]
        stride = (p["strides"][0] if p["strides"] else 1) or 1
        return [D.rows(tuple(x)[start:limit:stride])]
    if isinstance(x, D.LastRows):
        start, limit = p["start_indices"][-1], p["limit_indices"][-1]
        stride = (p["strides"][-1] if p["strides"] else 1) or 1
        return [D.last_rows(tuple(x)[start:limit:stride])]
    return [x]


def _t_concat(self, eqn, ins):
    dim = eqn.params["dimension"]
    out_shape = eqn.outvars[0].aval.shape
    rank = len(out_shape)
    n0 = out_shape[0] if out_shape else 0
    nl = out_shape[-1] if out_shape else 0
    if dim == 0 and 1 < n0 <= ROW_CAP:
        rws = []
        for x, atom in zip(ins, eqn.invars):
            k = atom.aval.shape[0]
            if isinstance(x, D.Rows) and len(x) == k:
                rws.extend(x)
            else:
                rws.extend([D.collapse(x)] * k)
        return [D.rows(rws)]
    if dim == rank - 1 and dim != 0 and 1 < nl <= ROW_CAP:
        rws = []
        for x, atom in zip(ins, eqn.invars):
            k = atom.aval.shape[-1]
            if isinstance(x, D.LastRows) and len(x) == k:
                rws.extend(x)
            else:
                rws.extend([D.collapse(x)] * k)
        return [D.last_rows(rws)]
    if dim != 0 and 1 < n0 <= ROW_CAP and any(
        isinstance(x, D.Rows) for x in ins
    ):
        cols = [self._rows_for(x, n0) for x in ins]
        out = []
        for i in range(n0):
            j = cols[0][i]
            for c in cols[1:]:
                j = D.iv_join(j, c[i])
            out.append(j)
        return [D.rows(out)]
    if dim != rank - 1 and 1 < nl <= ROW_CAP and any(
        isinstance(x, D.LastRows) for x in ins
    ):
        cols = [self._last_rows_for(x, nl) for x in ins]
        out = []
        for i in range(nl):
            j = cols[0][i]
            for c in cols[1:]:
                j = D.iv_join(j, c[i])
            out.append(j)
        return [D.last_rows(out)]
    out = D.collapse(ins[0])
    for x in ins[1:]:
        out = D.iv_join(out, D.collapse(x))
    return [out]


def _t_broadcast(self, eqn, ins):
    x = ins[0]
    if not isinstance(x, (D.Rows, D.LastRows)):
        return [x]
    bd = eqn.params["broadcast_dimensions"]
    shape = eqn.params["shape"]
    in_shape = eqn.invars[0].aval.shape
    out_rank = len(shape)
    if isinstance(x, D.Rows):
        if bd and bd[0] == 0 and in_shape and in_shape[0] == shape[0]:
            return [x]
        # a rank-1 Rows broadcast into the MINOR axis enters the
        # XLA-twin convention: [20] limbs -> [..., 20]
        if (
            len(in_shape) == 1 and bd and bd[0] == out_rank - 1
            and shape[-1] == in_shape[0]
        ):
            return [D.LastRows(tuple(x))]
        return [D.collapse(x)]
    if (
        bd and bd[-1] == out_rank - 1 and in_shape
        and in_shape[-1] == shape[-1]
    ):
        return [x]
    return [D.collapse(x)]


def _t_reshape(self, eqn, ins):
    x = ins[0]
    if not isinstance(x, (D.Rows, D.LastRows)):
        return [x]
    new = eqn.params["new_sizes"]
    old = eqn.invars[0].aval.shape
    if isinstance(x, D.Rows):
        if new and old and new[0] == old[0]:
            return [x]
    elif new and old and new[-1] == old[-1]:
        return [x]
    return [D.collapse(x)]


def _t_transpose(self, eqn, ins):
    x = ins[0]
    if not isinstance(x, (D.Rows, D.LastRows)):
        return [x]
    perm = eqn.params["permutation"]
    if not perm:
        return [x]
    if isinstance(x, D.Rows):
        if perm[0] == 0:
            return [x]
        if perm[-1] == 0:  # leading axis moved minor: convention flips
            return [D.LastRows(tuple(x))]
        return [D.collapse(x)]
    if perm[-1] == len(perm) - 1:
        return [x]
    if perm[0] == len(perm) - 1:
        return [D.Rows(tuple(x))]
    return [D.collapse(x)]


def _t_squeeze(self, eqn, ins):
    x = ins[0]
    if not isinstance(x, (D.Rows, D.LastRows)):
        return [x]
    dims = eqn.params["dimensions"]
    in_rank = len(eqn.invars[0].aval.shape)
    if isinstance(x, D.Rows):
        return [D.collapse(x) if 0 in dims else x]
    return [D.collapse(x) if (in_rank - 1) in dims else x]


def _t_rev(self, eqn, ins):
    x = ins[0]
    dims = eqn.params["dimensions"]
    if isinstance(x, D.Rows) and 0 in dims:
        return [D.rows(tuple(x)[::-1])]
    if isinstance(x, D.LastRows) and (
        len(eqn.invars[0].aval.shape) - 1
    ) in dims:
        return [D.last_rows(tuple(x)[::-1])]
    return [x]


def _t_pad(self, eqn, ins):
    x, pv = ins[0], D.collapse(ins[1])
    cfg = eqn.params["padding_config"]
    if not isinstance(x, (D.Rows, D.LastRows)):
        if any(lo or hi or it for lo, hi, it in cfg):
            return [D.iv_join(x, pv)]
        return [x]
    if isinstance(x, D.Rows):
        own, rest, build = cfg[0], cfg[1:], D.rows
    else:
        own, rest, build = cfg[-1], cfg[:-1], D.last_rows
    pad_rest = any(lo or hi or it for lo, hi, it in rest)
    lo0, hi0, it0 = own if cfg else (0, 0, 0)
    if it0 or lo0 < 0 or hi0 < 0:
        return [D.iv_join(D.collapse(x), pv)]
    rws = [D.iv_join(r, pv) if pad_rest else r for r in x]
    rws = [pv] * lo0 + rws + [pv] * hi0
    if len(rws) > ROW_CAP:
        return [D.iv_join(D.collapse(x), pv)]
    return [build(rws)]


def _t_iota(self, eqn, ins):
    d = eqn.params["dimension"]
    shape = eqn.params["shape"]
    n = shape[d]
    self._scaled(n)
    if 1 < n <= ROW_CAP:
        # per-row iota values are EXACT along the iota axis — the index
        # comparisons the one-hot lookups and padding masks build on
        if d == 0:
            return [D.rows((k, k) for k in range(n))]
        if d == len(shape) - 1:
            return [D.last_rows((k, k) for k in range(n))]
    return [(0, max(0, n - 1))]


def _struct_axis(x, shape):
    """(tracked axis, expand, build) for whichever convention x uses."""
    if isinstance(x, D.Rows):
        return 0, D.rows_of, D.rows
    if isinstance(x, D.LastRows):
        return len(shape) - 1, D.last_rows_of, D.last_rows
    return None, None, None


def _t_reduce_sum(self, eqn, ins):
    shape = eqn.invars[0].aval.shape
    axes = eqn.params["axes"]
    x = ins[0]
    raxis, expand, build = _struct_axis(x, shape)
    n_other = 1
    for ax in axes:
        if ax != raxis:
            n_other *= shape[ax]
            self._scaled(shape[ax])
    if raxis is not None and raxis in axes:
        self._scaled(shape[raxis])
        rws = expand(x, shape[raxis])
        lo = sum(r[0] for r in rws)
        hi = sum(r[1] for r in rws)
        return [(lo * n_other, hi * n_other)]
    if raxis is None and axes:
        # uniform: n_other already covers every reduced axis
        return [D.iv_scale(D.collapse(x), n_other)]
    if n_other == 1:
        return [x]
    return [build(D.iv_scale(r, n_other) for r in expand(x, shape[raxis]))]


def _t_reduce_prod(self, eqn, ins):
    shape = eqn.invars[0].aval.shape
    n = 1
    for ax in eqn.params["axes"]:
        n *= shape[ax]
        self._scaled(shape[ax])
    a = D.collapse(ins[0])
    m = max(abs(a[0]), abs(a[1]))
    hi = m ** n
    lo = 0 if a[0] >= 0 else -hi
    return [(lo, hi)]


def _t_reduce_max(self, eqn, ins):
    x = ins[0]
    shape = eqn.invars[0].aval.shape
    raxis, expand, _ = _struct_axis(x, shape)
    if raxis is not None and raxis in eqn.params["axes"]:
        rws = expand(x, shape[raxis])
        return [(max(r[0] for r in rws), max(r[1] for r in rws))]
    return [x]


def _t_reduce_min(self, eqn, ins):
    x = ins[0]
    shape = eqn.invars[0].aval.shape
    raxis, expand, _ = _struct_axis(x, shape)
    if raxis is not None and raxis in eqn.params["axes"]:
        rws = expand(x, shape[raxis])
        return [(min(r[0] for r in rws), min(r[1] for r in rws))]
    return [x]


def _t_argminmax(self, eqn, ins):
    n = 1
    shape = eqn.invars[0].aval.shape
    for ax in eqn.params["axes"]:
        n *= shape[ax]
        self._scaled(shape[ax])
    return [(0, max(0, n - 1))]


def _t_cumsum(self, eqn, ins):
    ax = eqn.params["axis"]
    shape = eqn.invars[0].aval.shape
    n = shape[ax]
    x = ins[0]
    self._scaled(n)
    raxis, _, build = _struct_axis(x, shape)
    if raxis is not None and ax == raxis:
        rws = list(x)
        if eqn.params.get("reverse"):
            rws = rws[::-1]
        lo = hi = 0
        out = []
        for r in rws:
            lo += r[0]
            hi += r[1]
            out.append((lo, hi))
        if eqn.params.get("reverse"):
            out = out[::-1]
        return [build(out)]
    if raxis is not None:
        return [build(
            (min(r[0], n * r[0]), max(r[1], n * r[1])) for r in x
        )]
    a = D.collapse(x)
    return [(min(a[0], n * a[0]), max(a[1], n * a[1]))]


def _t_cumprod(self, eqn, ins):
    ax = eqn.params["axis"]
    n = eqn.invars[0].aval.shape[ax]
    self._scaled(n)
    a = D.collapse(ins[0])
    m = max(abs(a[0]), abs(a[1]), 1)
    hi = m ** n
    lo = min(a[0], 0 if a[0] >= 0 else -hi)
    return [(lo, max(a[1], hi))]


def _t_dot_general(self, eqn, ins):
    (lc, rc), _ = eqn.params["dimension_numbers"]
    k = 1
    for ax in lc:
        n = eqn.invars[0].aval.shape[ax]
        k *= n
        self._scaled(n)
    prod = D.iv_mul(D.collapse(ins[0]), D.collapse(ins[1]))
    for operand_idx, cdims in ((0, lc), (1, rc)):
        atom = eqn.invars[operand_idx]
        if not _is_literal(atom) and self._onehot_along(atom, cdims):
            # at most one nonzero term: a selection, not a k-term sum
            return [D.iv_join((0, 0), prod)]
    return [D.iv_scale(prod, k)]


def _t_scatter_add(self, eqn, ins):
    dn = eqn.params["dimension_numbers"]
    upd_aval = eqn.invars[2].aval
    window = set(dn.update_window_dims)
    n = 1
    for i, s in enumerate(upd_aval.shape):
        if i not in window:
            n *= s
            self._scaled(s)
    add = D.iv_scale(D.collapse(ins[2]), n)
    x, idx = ins[0], ins[1]
    op_shape = eqn.invars[0].aval.shape
    last = len(op_shape) - 1
    if (
        isinstance(x, D.LastRows) and n == 1
        and tuple(dn.scatter_dims_to_operand_dims) == (last,)
        and tuple(dn.inserted_window_dims) == (last,)
        and not isinstance(idx, (D.Rows, D.LastRows))
        and idx[0] == idx[1] and 0 <= idx[0] < len(x)
    ):
        # the `.at[..., k].add(v)` idiom with a static k (field.py's
        # FOLD^2 fold of limb 40 onto limb 0): only row k widens
        k = int(idx[0])
        rws = list(x)
        rws[k] = (rws[k][0] + min(0, add[0]), rws[k][1] + max(0, add[1]))
        return [D.last_rows(rws)]
    lo, hi = D.collapse(x)
    return [(lo + min(0, add[0]), hi + max(0, add[1]))]


def _t_scatter_set(self, eqn, ins):
    x, u = ins[0], D.collapse(ins[2])
    return [self._map_struct(x, lambda r: D.iv_join(r, u))
            if isinstance(x, (D.Rows, D.LastRows)) else D.iv_join(x, u)]


def _t_dus(self, eqn, ins):
    x, u = ins[0], D.collapse(ins[1])
    return [self._map_struct(x, lambda r: D.iv_join(r, u))
            if isinstance(x, (D.Rows, D.LastRows)) else D.iv_join(x, u)]


def _t_gather(self, eqn, ins):
    return [D.collapse(ins[0])]


def _t_sort(self, eqn, ins):
    dim = eqn.params.get("dimension", 0)
    out = []
    for x, atom in zip(ins, eqn.invars):
        rank = len(atom.aval.shape)
        if isinstance(x, D.Rows) and dim == 0:
            out.append(D.collapse(x))  # sorting mixes the tracked rows
        elif isinstance(x, D.LastRows) and dim == rank - 1:
            out.append(D.collapse(x))
        else:
            out.append(x)  # per-row multisets are permuted, not mixed
    return out


def _t_popcount(self, eqn, ins):
    bits = np.dtype(eqn.invars[0].aval.dtype).itemsize * 8
    return [(0, bits)]


def _t_convert(self, eqn, ins):
    x = ins[0]
    new = eqn.params["new_dtype"]
    rng = _int_range(new)

    def conv1(iv):
        lo, hi = iv
        if rng is None:  # -> float
            return (float(lo), float(hi)), False
        if isinstance(lo, float) or isinstance(hi, float):
            if not (math.isfinite(lo) and math.isfinite(hi)):
                lo, hi = rng[0] - 1, rng[1] + 1  # force the truncate path
            else:
                lo, hi = math.trunc(lo), math.trunc(hi)  # XLA truncates
                lo, hi = min(lo, hi), max(lo, hi)
        if rng[0] <= lo and hi <= rng[1]:
            return (lo, hi), False
        return rng, (lo, hi)

    if isinstance(x, (D.Rows, D.LastRows)):
        build = D.rows if isinstance(x, D.Rows) else D.last_rows
        out, worst = [], None
        for r in x:
            o, trunc = conv1(r)
            out.append(o)
            if trunc and (worst is None or trunc[1] > worst[1]):
                worst = trunc
        if worst and self._recording:
            self.record(
                "truncate", eqn,
                f"convert to {np.dtype(new).name} truncates inferred "
                f"[{worst[0]}, {worst[1]}]",
            )
        return [build(out)]
    o, trunc = conv1(x)
    if trunc and self._recording:
        # truncation of a non-proven-narrow value — the specific check
        # the PR 3 bug class calls for (a narrowing cast is only safe
        # when the interpreter has PROVEN the operand narrow)
        self.record(
            "truncate", eqn,
            f"convert to {np.dtype(new).name} truncates inferred "
            f"[{trunc[0]}, {trunc[1]}]",
        )
    return [o]


def _t_psum(self, eqn, ins):
    s = self._scaled(SPMD_AXIS_SCALE)
    return [
        self._map_struct(x, lambda r: D.iv_scale(r, s)) for x in ins
    ]


def _t_pminmax(self, eqn, ins):
    return list(ins)


def _t_axis_index(self, eqn, ins):
    self._scaled(SPMD_AXIS_SCALE)
    return [(0, SPMD_AXIS_SCALE - 1)]


_IV_TABLE = {
    "add": _ew(_k_add),
    "sub": _ew(_k_sub),
    "mul": _ew(_k_mul),
    "div": _ew(_k_div),
    "rem": _ew(_k_rem),
    "max": _ew(_k_max),
    "min": _ew(_k_min),
    "neg": _ew(_k_neg),
    "abs": _ew(_k_abs),
    "sign": _ew(_k_sign),
    "and": _ew(_k_and),
    "or": _ew(_k_or),
    "xor": _ew(_k_xor),
    "not": _ew(_k_not),
    "shift_left": _ew(_k_shl),
    "shift_right_arithmetic": _ew(_k_shr_arith),
    "shift_right_logical": _ew(_k_shr_logical),
    "select_n": _ew(_k_select_n),
    "clamp": _ew(_k_clamp),
    "integer_pow": _ew(_k_ipow),
    "iota": _t_iota,
    "eq": _t_bool,
    "ne": _t_bool,
    "lt": _t_bool,
    "le": _t_bool,
    "gt": _t_bool,
    "ge": _t_bool,
    "is_finite": _t_bool,
    "reduce_and": _t_bool,
    "reduce_or": _t_bool,
    "reduce_xor": _t_bool,
    "reduce_sum": _t_reduce_sum,
    "reduce_prod": _t_reduce_prod,
    "reduce_min": _t_reduce_min,
    "reduce_max": _t_reduce_max,
    "argmax": _t_argminmax,
    "argmin": _t_argminmax,
    "cumsum": _t_cumsum,
    "cumprod": _t_cumprod,
    "dot_general": _t_dot_general,
    "scatter-add": _t_scatter_add,
    "scatter": _t_scatter_set,
    "dynamic_update_slice": _t_dus,
    "pad": _t_pad,
    "gather": _t_gather,
    "dynamic_slice": _t_gather,
    "sort": _t_sort,
    "population_count": _t_popcount,
    "convert_element_type": _t_convert,
    "psum": _t_psum,
    "pmin": _t_pminmax,
    "pmax": _t_pminmax,
    "axis_index": _t_axis_index,
    "device_put": _t_pminmax,
    "broadcast_in_dim": _t_broadcast,
    "reshape": _t_reshape,
    "transpose": _t_transpose,
    "squeeze": _t_squeeze,
    "rev": _t_rev,
    "slice": _t_slice,
    "copy": _t_identity,
    "stop_gradient": _t_identity,
    "concatenate": _t_concat,
}


# ---------------------------------------------------------------------------
# Taint domain
# ---------------------------------------------------------------------------

_INDEX_OPERANDS = {
    "gather": lambda eqn: [1],
    "scatter": lambda eqn: [1],
    "scatter-add": lambda eqn: [1],
    "dynamic_slice": lambda eqn: list(range(1, len(eqn.invars))),
    "dynamic_update_slice": lambda eqn: list(range(2, len(eqn.invars))),
}


class TaintInterp(_Interp):
    def __init__(self, graph_name: str):
        super().__init__(graph_name)
        # informational: wire marks that steered a sort/index site —
        # clean by policy (public data cannot leak through timing) but
        # pinned in the certificate so a new steering site is visible
        self.wire_steered: set[str] = set()

    def abs_const(self, c):
        return D.NO_TAINT

    def abs_literal(self, lit):
        return D.NO_TAINT

    def join(self, a, b):
        return D.taint_join(a, b)

    def on_cond_pred(self, eqn, pred, record):
        if pred and record:
            self.record(
                "taint-branch", eqn,
                f"cond predicate carries {sorted(pred)} — "
                "data-dependent control flow",
            )

    def on_while_pred(self, eqn, pred, record):
        if pred and record:
            self.record(
                "taint-branch", eqn,
                f"while condition carries {sorted(pred)} — "
                "data-dependent trip count",
            )

    def transfer(self, eqn, prim, ins, record):
        if record:
            idx_of = _INDEX_OPERANDS.get(prim)
            if idx_of is not None:
                marks = D.taint_join(*(ins[i] for i in idx_of(eqn)))
                secret = D.taint_secret(marks)
                if secret:
                    self.record(
                        "taint-index", eqn,
                        f"{prim} index derives from {sorted(secret)} — "
                        "secret-dependent access pattern",
                    )
                wire = D.taint_wire(marks)
                if wire:
                    self.wire_steered.add(
                        f"{prim}@{_src_of(eqn)}: {','.join(sorted(wire))}"
                    )
            elif prim == "sort":
                nk = eqn.params.get("num_keys", 1)
                marks = D.taint_join(*ins[:nk])
                secret = D.taint_secret(marks)
                if secret:
                    self.record(
                        "taint-sort", eqn,
                        f"sort keys derive from {sorted(secret)} — "
                        "secret-dependent permutation",
                    )
                wire = D.taint_wire(marks)
                if wire:
                    self.wire_steered.add(
                        f"sort@{_src_of(eqn)}: {','.join(sorted(wire))}"
                    )
        joined = D.taint_join(*ins) if ins else D.NO_TAINT
        return [joined] * len(eqn.outvars)


# ---------------------------------------------------------------------------
# Specs (analysis/shapes.json) and certification
# ---------------------------------------------------------------------------

# named bound classes the specs refer to; `limb` is the nearly
# normalized field-limb bound (ops/field.B_MAX), `limb13` a normalized
# 13-bit row (e.g. scalars < L after Barrett)
BOUND_CLASSES = {
    "byte": (0, 255),
    "bit": (0, 1),
    "bool": (0, 1),
    "nibble": (0, 15),
    "limb": (0, 9500),
    "limb13": (0, 8191),
    "nblocks": (0, 64),
    "i32": (-(2 ** 31), 2 ** 31 - 1),
    "nonneg": (0, 2 ** 31 - 1),
    "u32": (0, 2 ** 32 - 1),
}


def load_shapes(path: str | None = None) -> dict:
    with open(path or _SHAPES_PATH, encoding="utf-8") as f:
        return json.load(f)


def load_certified(path: str | None = None) -> dict:
    with open(path or _CERTIFIED_PATH, encoding="utf-8") as f:
        return json.load(f)


def _spec_of(name: str, shapes: dict | None = None) -> dict:
    shapes = shapes or load_shapes()
    spec = shapes["graphs"].get(name)
    if spec is None:
        raise KeyError(f"no input spec for graph {name!r} in shapes.json")
    return spec


def _trace_any(name: str, lanes: int | None):
    """Trace a registry graph or an absint-only aux target."""
    if name in graphs.REGISTRY:
        return graphs.trace_graph(name, lanes)
    import jax

    fn, args = AUX_REGISTRY[name](lanes)
    return jax.make_jaxpr(fn)(*args)


def input_intervals(name: str, closed, shapes: dict | None = None):
    spec = _spec_of(name, shapes)
    classes = spec["args"]
    invars = closed.jaxpr.invars
    if isinstance(classes, dict):
        # {"all": class, "<idx>": override} — the variadic aux targets
        base = classes.get("all")
        return [
            BOUND_CLASSES[classes.get(str(i), base)]
            for i in range(len(invars))
        ]
    if len(classes) != len(invars):
        raise ValueError(
            f"{name}: shapes.json lists {len(classes)} args, trace has "
            f"{len(invars)}"
        )
    return [BOUND_CLASSES[c] for c in classes]


def input_taints(name: str, closed, shapes: dict | None = None):
    spec = _spec_of(name, shapes)
    n = len(closed.jaxpr.invars)
    out = [D.NO_TAINT] * n
    for idx, mark in spec.get("taint", {}).items():
        level, label = mark.split(":", 1)
        out[int(idx)] = D.taint(level, label)
    return out


def certify_range(name: str, lanes: int | None = None,
                  shapes: dict | None = None) -> Report:
    """Interval/overflow certification of one graph at one lane count
    (None = the registry's default tile). A kernel's own shape guard
    firing at the swept lane count (e.g. sum_mod_l's t <= 2^17 assert)
    is a FAILED proof at that shape, not a crash of the gate."""
    shapes = shapes or load_shapes()
    try:
        closed = _trace_any(name, lanes)
    except Exception as e:
        return Report(
            graph=name, domain="range", lanes=lanes, ok=False,
            findings=[Finding(
                "trace-error", name, "trace", f"<trace@{lanes}>",
                f"{type(e).__name__}: {e}",
            )],
        )
    interp = IntervalInterp(name)
    interp.run_closed(closed, input_intervals(name, closed, shapes))
    spec = _spec_of(name, shapes)
    tile = lanes if lanes is not None else spec["default_tile"]
    universal = tile not in interp.scale_factors
    findings = _dedup(interp.findings)
    ok = not findings and (
        universal or bool(spec.get("lane_sensitive"))
    )
    return Report(
        graph=name, domain="range", lanes=lanes, ok=ok,
        findings=findings, eqns=interp.eqns,
        scale_factors=tuple(sorted(interp.scale_factors)),
        lane_universal=universal,
    )


def certify_taint(name: str, lanes: int | None = None,
                  shapes: dict | None = None) -> Report:
    """Secret-taint certification (taint structure is lane-count
    independent, so the caller usually passes the lane count whose
    trace is already cached)."""
    shapes = shapes or load_shapes()
    closed = _trace_any(name, lanes)
    interp = TaintInterp(name)
    outs = interp.run_closed(closed, input_taints(name, closed, shapes))
    out_marks = sorted(set().union(*outs)) if outs else []
    spec = _spec_of(name, shapes)
    findings = _dedup(interp.findings)
    if not spec.get("declassified_outputs", True):
        secret = [m for m in out_marks if m.startswith("secret:")]
        if secret:
            findings.append(Finding(
                "taint-output", name, "outvars", "<graph outputs>",
                f"secret marks {secret} reach host materialization",
            ))
    return Report(
        graph=name, domain="taint", lanes=lanes, ok=not findings,
        findings=findings, eqns=interp.eqns,
        output_taint=tuple(out_marks),
        wire_steered=tuple(sorted(interp.wire_steered)),
    )


def sweep_lanes(name: str, tier: str,
                shapes: dict | None = None) -> list[int | None]:
    spec = _spec_of(name, shapes)
    sw = spec.get("sweeps", {})
    lanes = sw.get(tier, sw.get("fast", [None]))
    return [None if v is None else int(v) for v in lanes]


def certify_graph(name: str, tier: str = "fast",
                  shapes: dict | None = None) -> list[Report]:
    """The spec's domains over the tier's lane sweep. The taint pass
    reuses the first swept lane count's trace (same cache key)."""
    shapes = shapes or load_shapes()
    spec = _spec_of(name, shapes)
    domains = spec.get("domains", ["range", "taint"])
    out = []
    lane_list = sweep_lanes(name, tier, shapes)
    if "range" in domains:
        for lanes in lane_list:
            out.append(certify_range(name, lanes, shapes))
    if "taint" in domains:
        out.append(certify_taint(name, lane_list[0], shapes))
    return out


def certify_all(tier: str = "fast", names: list[str] | None = None,
                shapes: dict | None = None) -> list[Report]:
    """Certify every (or the named) graph over the tier's sweeps, one
    graph at a time so each trace is consumed by both domains while it
    is still in trace_graph's LRU cache."""
    shapes = shapes or load_shapes()
    out: list[Report] = []
    for name in names if names is not None else certifiable_graphs():
        out.extend(certify_graph(name, tier, shapes))
    return out


def certified_payload(reports: list[Report],
                      shapes: dict | None = None) -> dict:
    """The certified.json pin structure for a report sweep: per graph,
    the range status ('proven' / 'lost' / 'skipped' for taint-only
    specs), the certified lane counts, and the pinned taint finding
    keys (sorted — machine-stable for CI diffing)."""
    shapes = shapes or load_shapes()
    pins: dict = {}
    for r in reports:
        g = pins.setdefault(r.graph, {})
        if r.domain == "range":
            lost = g.get("range") == "lost" or not r.ok
            g["range"] = "lost" if lost else "proven"
            g.setdefault("range_lanes", []).append(r.lanes)
            g["lane_universal"] = bool(
                g.get("lane_universal", True) and r.lane_universal
            )
        else:
            g["taint"] = "clean" if not r.findings else "pinned"
            g["taint_findings"] = sorted(f.key() for f in r.findings)
            g["output_taint"] = sorted(r.output_taint)
            g["wire_steered"] = sorted(r.wire_steered)
    for name in shapes["graphs"]:
        if name in pins and "range" not in pins[name]:
            pins[name]["range"] = "skipped"
    return pins


def write_certified(reports: list[Report], path: str | None = None,
                    shapes: dict | None = None) -> dict:
    payload = {
        "comment": (
            "octrange certification ratchet (analysis/absint.py; the "
            "certified.json twin of baseline.json). Every graph pins "
            "its range proof status and its exact taint finding keys; "
            "scripts/lint.py fails when a kernel edit loses a proof, "
            "grows a new taint finding, or leaves a pinned finding "
            "stale. Regenerate deliberately with "
            "scripts/lint.py --update-certified."
        ),
        "graphs": certified_payload(reports, shapes),
    }
    with open(path or _CERTIFIED_PATH, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


def check_certified(reports: list[Report],
                    certified: dict | None = None) -> list[str]:
    """Ratchet: every report must match its pinned certified.json
    status — a graph pinned `proven`/`clean` that now has findings (or
    taint findings beyond its pinned key set) is a violation, as is a
    report with no pin at all."""
    certified = certified if certified is not None else load_certified()
    pins = certified.get("graphs", {})
    violations = []
    for r in reports:
        pin = pins.get(r.graph)
        if pin is None:
            violations.append(
                f"{r.graph}: no certified.json entry (pin this graph)")
            continue
        if r.domain == "range":
            status = pin.get("range")
            if status != "proven":
                violations.append(
                    f"{r.graph}: certified.json range status is "
                    f"{status!r}, expected 'proven'")
            if not r.ok:
                msgs = "; ".join(f.format() for f in r.findings[:4])
                extra = (
                    msgs or "bounds are lane-dependent but the graph is "
                            "not marked lane_sensitive")
                violations.append(
                    f"{r.graph}: range proof LOST at lanes="
                    f"{r.lanes}: {extra}")
        else:
            pinned = set(pin.get("taint_findings", []))
            current = {f.key() for f in r.findings}
            new = current - pinned
            stale = pinned - current
            if pin.get("taint") == "clean" and current:
                violations.append(
                    f"{r.graph}: taint was pinned clean, now: " +
                    "; ".join(sorted(new or current)))
            elif new:
                violations.append(
                    f"{r.graph}: NEW taint findings: " +
                    "; ".join(sorted(new)))
            if stale:
                violations.append(
                    f"{r.graph}: stale pinned taint findings (tighten "
                    f"certified.json): " + "; ".join(sorted(stale)))
    return violations


# ---------------------------------------------------------------------------
# Absint-only aux targets (lane-sensitive leaf kernels + the sign path)
# ---------------------------------------------------------------------------


def _aux_sum_mod_l(nterms: int, default_t: int):
    def build(t=None):
        import jax
        from jax import numpy as jnp

        from ..ops.pk import limbs as fe

        tt = t or default_t

        def fn(*terms):
            return fe.sum_mod_l(list(terms))

        args = tuple(
            jax.ShapeDtypeStruct((20, tt), jnp.int32) for _ in range(nterms)
        )
        return fn, args

    return build


def _aux_mul_mod_l(t=None):
    import jax
    from jax import numpy as jnp

    from ..ops.pk import limbs as fe

    tt = t or 8192
    s = jax.ShapeDtypeStruct((20, tt), jnp.int32)
    return fe.mul_mod_l, (s, s)


def _aux_ed25519_sign(t=None):
    import jax
    from jax import numpy as jnp

    from ..ops import ed25519_batch as eb

    b = t or 4
    nb = 2

    def u8(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint8)

    def u32(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint32)

    def i32(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.int32)

    args = (
        u8(b, 32), u8(b, 32), u32(b, nb, 16, 2), i32(b),
        u32(b, nb, 16, 2), i32(b),
    )
    return eb.sign, args


AUX_REGISTRY = {
    # the PR 3 sum_mod_l carry-normalization proof obligations: 3 terms
    # at the ~87k-lane boundary (the documented overflow threshold is
    # 2^31/8191 = 262177 lane-terms; 3 x 87381 = 262143 sits just
    # under), the 40 x 8192 max-term regression shape, and the
    # 128 x 8192 "epoch" shape (= 2^20 lane-terms, the 1M-headers
    # equivalent of one aggregated window stream)
    "sum_mod_l_3t": _aux_sum_mod_l(3, 87381),
    "sum_mod_l_40t": _aux_sum_mod_l(40, 8192),
    "sum_mod_l_epoch": _aux_sum_mod_l(128, 8192),
    "mul_mod_l": _aux_mul_mod_l,
    # sign path: REAL secrets (clamped scalar a, nonce-hash blocks) —
    # the taint certificate pins whatever secret-indexed access the
    # XLA-twin fixed-base ladder performs
    "ed25519_sign": _aux_ed25519_sign,
}


# traced source modules per aux target (the scripts/lint.py --changed
# fast path; REGISTRY graphs use graphs.GRAPH_SOURCES)
_LIMBS = ["ouroboros_consensus_tpu/ops/pk/limbs.py",
          "ouroboros_consensus_tpu/ops/field.py"]
AUX_SOURCES: dict[str, list[str]] = {
    "sum_mod_l_3t": _LIMBS,
    "sum_mod_l_40t": _LIMBS,
    "sum_mod_l_epoch": _LIMBS,
    "mul_mod_l": _LIMBS,
    "ed25519_sign": [
        "ouroboros_consensus_tpu/ops/ed25519_batch.py",
        "ouroboros_consensus_tpu/ops/curve.py",
        "ouroboros_consensus_tpu/ops/scalar.py",
        "ouroboros_consensus_tpu/ops/bigint.py",
        "ouroboros_consensus_tpu/ops/field.py",
        "ouroboros_consensus_tpu/ops/sha512.py",
        "ouroboros_consensus_tpu/ops/u64.py",
    ],
}


def certifiable_graphs() -> list[str]:
    return sorted(set(graphs.REGISTRY) | set(AUX_REGISTRY))


def check_registry_drift(shapes: dict | None = None) -> list[str]:
    """Registry drift gate (scripts/lint.py): every graphs.py REGISTRY
    entry (and every aux target) must carry a shapes.json input spec
    and a GRAPH_SOURCES/AUX_SOURCES mapping. A missing spec used to
    surface only as a KeyError deep inside certification (or, for the
    --changed source mapping, as a graph silently never re-selected by
    the fast path) — this makes the drift a loud, named violation."""
    shapes = shapes or load_shapes()
    spec_names = set(shapes.get("graphs", {}))
    violations: list[str] = []
    for name in sorted(graphs.REGISTRY):
        if name not in spec_names:
            violations.append(
                f"{name}: REGISTRY entry has no shapes.json input spec "
                "(certification would be skipped)"
            )
        if name not in graphs.GRAPH_SOURCES:
            violations.append(
                f"{name}: REGISTRY entry has no GRAPH_SOURCES mapping "
                "(--changed would never re-select it)"
            )
    for name in sorted(AUX_REGISTRY):
        if name not in spec_names:
            violations.append(
                f"{name}: aux target has no shapes.json input spec "
                "(certification would be skipped)"
            )
        if name not in AUX_SOURCES:
            violations.append(
                f"{name}: aux target has no AUX_SOURCES mapping "
                "(--changed would never re-select it)"
            )
    return violations
