"""Block forging: assemble and KES-sign a Praos block.

Reference: `forgeBlock`/`mkHeader` — Block/Forging.hs:143 and the Praos
`mkHeader` instance (ouroboros-consensus-cardano shelley
Protocol/Praos.hs:102): build the header body, KES-sign its serialisation
with the hot key at the current evolution, attach the signature.

Used by the forging loop (node/), db_synthesizer (tools/) and tests.
"""

from __future__ import annotations

from ..ops.host import ecvrf as host_ecvrf
from ..ops.host import fast
from ..ops.host import kes as host_kes
from ..protocol import nonces
from ..protocol.praos import PraosIsLeader, PraosParams
from ..testing.fixtures import PoolCredentials
from .praos_block import Block, Header, HeaderBody, body_hash


def evaluate_vrf(pool: PoolCredentials, slot: int, epoch_nonce: nonces.Nonce):
    """VRF.evalCertified at InputVRF(slot, eta0) (Praos.hs:397)."""
    alpha = nonces.mk_input_vrf(slot, epoch_nonce)
    proof = fast.ecvrf_prove(pool.vrf_seed, alpha)
    return PraosIsLeader(fast.ecvrf_proof_to_hash(proof), proof)


def forge_block(
    params: PraosParams,
    pool: PoolCredentials,
    *,
    slot: int,
    block_no: int,
    prev_hash: bytes | None,
    epoch_nonce: nonces.Nonce,
    txs: tuple[bytes, ...] = (),
    ocert_counter: int = 0,
    is_leader: PraosIsLeader | None = None,
    protocol_version: tuple[int, int] = (9, 0),
    hotkey=None,  # protocol.hotkey.HotKey: evolve-and-sign in place
    ocert=None,  # the issued OCert accompanying `hotkey`
) -> Block:
    """Forge a protocol-valid block for `slot` (the caller is responsible
    for having won the slot; db_synthesizer checks check_is_leader first).

    With `hotkey`/`ocert` (the node path, NodeKernel), the evolving key
    signs at its own evolution and the certificate is used as issued
    (Ledger/HotKey.hs:142). Without them (synthesizer/test path) a
    throwaway OCert is issued at the containing evolution-window start
    and the signature derived statically from the pool's root seed.
    """
    if is_leader is None:
        is_leader = evaluate_vrf(pool, slot, epoch_nonce)
    kp = params.kes_period_of(slot)
    if ocert is None:
        # issue the ocert at the containing evolution-window start so
        # that 0 <= t < max_kes_evolutions always holds
        c0 = max(0, kp - (kp % params.max_kes_evolutions))
        ocert = pool.make_ocert(ocert_counter, c0)
    body = HeaderBody(
        block_no=block_no,
        slot=slot,
        prev_hash=prev_hash,
        issuer_vk=pool.vk_cold,
        vrf_vk=pool.vrf_vk,
        vrf_output=is_leader.vrf_output,
        vrf_proof=is_leader.vrf_proof,
        body_size=sum(len(t_) for t_ in txs),
        body_hash=body_hash(txs),
        ocert=ocert,
        protocol_version=protocol_version,
    )
    if hotkey is not None:
        kes_sig = hotkey.sign(kp, body.signed_bytes)
    else:
        t = kp - ocert.kes_period
        kes_sig = host_kes.sign(pool.kes_seed, pool.kes_depth, t, body.signed_bytes)
    return Block(Header(body, kes_sig), tuple(txs))
