"""Block abstraction: points, header fields, chain hashes.

Reference equivalents: `Ouroboros.Consensus.Block.Abstract` /
`Block/RealPoint.hs` (HeaderFields, Point, RealPoint, ChainHash). The
Haskell type-class tower (`GetHeader`, `HasHeader`, …) collapses to plain
structural duck-typing on the host control plane: any object with
`.slot`, `.block_no`, `.hash_`, `.prev_hash` participates in chain logic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


GENESIS_HASH = None  # ChainHash: None = GenesisHash, bytes = BlockHash


@dataclass(frozen=True, order=True)
class Point:
    """A point on the chain: (slot, hash); None means genesis/origin.

    Reference: `Ouroboros.Network.Block.Point` as re-exported by
    Block/Abstract.hs; RealPoint (Block/RealPoint.hs:30) is a Point
    guaranteed non-genesis.
    """

    slot: int
    hash_: bytes

    def __repr__(self):
        return f"Point({self.slot}, {self.hash_[:6].hex()})"


ORIGIN: Optional[Point] = None


@dataclass(frozen=True)
class HeaderFields:
    """The fields every header exposes (Block/Abstract.hs HeaderFields)."""

    slot: int
    block_no: int
    hash_: bytes


def block_point(b) -> Point:
    return Point(b.slot, b.hash_)


def issuer_vk_of(header):
    """The forging pool's cold vk, wherever the block type keeps it:
    on the header itself, or inside the KES-signed header body (the
    real Praos layout, praos_block.HeaderBody). None for issuerless
    headers (mock/BFT-era, EBBs)."""
    issuer = getattr(header, "issuer_vk", None)
    if issuer is None:
        body = getattr(header, "body", None)
        issuer = getattr(body, "issuer_vk", None) if body is not None else None
    return issuer
