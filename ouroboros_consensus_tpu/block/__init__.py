"""Block/header model: abstraction, Praos block, CBOR codecs, forging."""

from .abstract import GENESIS_HASH, ORIGIN, HeaderFields, Point, block_point
from .praos_block import Block, Header, HeaderBody, body_hash
from .forge import forge_block, evaluate_vrf
