"""Praos header & block model with deterministic CBOR codecs.

Reference: the standalone Praos header
(ouroboros-consensus-protocol/.../Protocol/Praos/Header.hs:62-125):
`HeaderBody` carries 10 fields (block number, slot, prev hash, issuer VK,
VRF VK, VRF certificate, body size, body hash, OCert, protocol version);
`Header = (HeaderBody, KES signature)` memoises its serialized bytes, and
the header hash is Blake2b-256 of the CBOR (Header.hs:158).

The KES signature signs the CBOR of the HeaderBody — exactly the bytes the
batched verifier consumes (`HeaderView.signed_bytes`).

The block is this framework's own: header + a list of opaque tx byte
strings (the mock ledger interprets them; Shelley-depth tx bodies are out
of hot-path scope per SURVEY.md §7.2 step 11).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import cached_property
from typing import Sequence

from ..ops.host.hashes import blake2b_256
from ..protocol.views import HeaderView, OCert
from ..utils import cbor
from .abstract import HeaderFields, Point


@dataclass(frozen=True)
class HeaderBody:
    """The KES-signed part of a Praos header (Praos/Header.hs:62-84)."""

    block_no: int
    slot: int
    prev_hash: bytes | None  # None = genesis
    issuer_vk: bytes  # 32 — cold key
    vrf_vk: bytes  # 32
    vrf_output: bytes  # 64 — certified output beta
    vrf_proof: bytes  # ECVRF proof pi: 80 (draft-03) or 128 (batch-compat)
    body_size: int
    body_hash: bytes  # 32
    ocert: OCert
    protocol_version: tuple[int, int] = (9, 0)

    def to_cbor_obj(self):
        return [
            self.block_no,
            self.slot,
            self.prev_hash,
            self.issuer_vk,
            self.vrf_vk,
            [self.vrf_output, self.vrf_proof],
            self.body_size,
            self.body_hash,
            [self.ocert.vk_hot, self.ocert.counter, self.ocert.kes_period, self.ocert.sigma],
            [self.protocol_version[0], self.protocol_version[1]],
        ]

    @classmethod
    def from_cbor_obj(cls, obj) -> "HeaderBody":
        (bn, slot, prev, ivk, vvk, (vout, vproof), bsz, bh, oc, pv) = obj
        return cls(
            block_no=bn, slot=slot,
            prev_hash=bytes(prev) if prev is not None else None,
            issuer_vk=bytes(ivk), vrf_vk=bytes(vvk),
            vrf_output=bytes(vout), vrf_proof=bytes(vproof),
            body_size=bsz, body_hash=bytes(bh),
            ocert=OCert(bytes(oc[0]), oc[1], oc[2], bytes(oc[3])),
            protocol_version=(pv[0], pv[1]),
        )

    @cached_property
    def signed_bytes(self) -> bytes:
        """Memoised CBOR — the exact bytes the KES signature covers
        (Header.hs:120-125 `headerBodyBytes`)."""
        return cbor.encode(self.to_cbor_obj())


@dataclass(frozen=True)
class Header:
    body: HeaderBody
    kes_sig: bytes

    @cached_property
    def bytes_(self) -> bytes:
        return cbor.encode([self.body.to_cbor_obj(), self.kes_sig])

    @cached_property
    def hash_(self) -> bytes:
        """Blake2b-256 of the serialized header (Header.hs:158)."""
        return blake2b_256(self.bytes_)

    @property
    def slot(self) -> int:
        return self.body.slot

    @property
    def block_no(self) -> int:
        return self.body.block_no

    @property
    def prev_hash(self) -> bytes | None:
        return self.body.prev_hash

    @property
    def fields(self) -> HeaderFields:
        return HeaderFields(self.slot, self.block_no, self.hash_)

    @property
    def point(self) -> Point:
        return Point(self.slot, self.hash_)

    def to_view(self) -> HeaderView:
        """Project the exact validation inputs (Praos/Views.hs:22-39)."""
        b = self.body
        return HeaderView(
            prev_hash=b.prev_hash,
            vk_cold=b.issuer_vk,
            vrf_vk=b.vrf_vk,
            vrf_output=b.vrf_output,
            vrf_proof=b.vrf_proof,
            ocert=b.ocert,
            slot=b.slot,
            signed_bytes=b.signed_bytes,
            kes_sig=self.kes_sig,
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "Header":
        body_obj, sig = cbor.decode(data)
        return cls(HeaderBody.from_cbor_obj(body_obj), bytes(sig))


def body_hash(txs: Sequence[bytes]) -> bytes:
    """Blake2b-256 over the canonical CBOR of the tx list."""
    return blake2b_256(cbor.encode(list(txs)))


@dataclass(frozen=True)
class Block:
    """header + opaque txs; the unit ChainDB stores and the ledger applies."""

    header: Header
    txs: tuple[bytes, ...] = ()

    @cached_property
    def bytes_(self) -> bytes:
        return cbor.encode([[self.header.body.to_cbor_obj(), self.header.kes_sig], list(self.txs)])

    @property
    def hash_(self) -> bytes:
        return self.header.hash_

    @property
    def slot(self) -> int:
        return self.header.slot

    @property
    def block_no(self) -> int:
        return self.header.block_no

    @property
    def prev_hash(self) -> bytes | None:
        return self.header.prev_hash

    @property
    def point(self) -> Point:
        return self.header.point

    @classmethod
    def from_bytes(cls, data: bytes) -> "Block":
        (body_obj, sig), txs = cbor.decode(data)
        return cls(
            Header(HeaderBody.from_cbor_obj(body_obj), bytes(sig)),
            tuple(bytes(t) for t in txs),
        )

    def check_integrity(self) -> bool:
        """nodeCheckIntegrity analog (shelley Ledger/Integrity.hs:14-20):
        body hash matches; KES verification is the batched verifier's job
        (storage validation routes whole chunks through it)."""
        return body_hash(self.txs) == self.header.body.body_hash
