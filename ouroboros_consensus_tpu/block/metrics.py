"""BlockSupportsMetrics: self-issued detection + node block metrics.

Reference: `Ouroboros.Consensus.Block.SupportsMetrics` —
`isSelfIssued :: BlockConfig blk -> Header blk -> WhetherSelfIssued`
(the HFC and era instances dispatch per era), consumed by the node's
metric reporting (NodeKernel peer metrics; cardano-node maps the
tracers onto EKG/Prometheus). Here: compare the header's issuer key
against the node's forging credential, fold per-adoption counts into a
`NodeMetrics` record the kernel owns, and — when `bind` hands it an
obs metrics registry — mirror every fold into `oct_node_*` Prometheus
counters (the EKG bridge analog, ouroboros_consensus_tpu/obs).
"""

from __future__ import annotations

from dataclasses import dataclass, field


def is_self_issued(header, our_cold_vk: bytes | None) -> bool:
    """WhetherSelfIssued (SupportsMetrics.hs): did WE forge this block?
    Blocks without an issuer (mock/BFT-era headers) are never self."""
    if our_cold_vk is None:
        return False
    from .abstract import issuer_vk_of

    return issuer_vk_of(header) == our_cold_vk


# the counter fields mirrored into the registry as oct_node_<name>_total
_COUNTER_HELP = {
    "blocks_forged": "blocks this node forged",
    "blocks_could_not_forge": "won slots the hot key could not sign",
    "blocks_adopted_self": "self-forged blocks adopted",
    "blocks_adopted_peer": "peer blocks adopted",
    "chain_switches": "fork switches (rollbacks)",
    "slots_led": "slots this node led",
    "batches_validated": "device validation batches completed",
    "headers_validated": "headers that validated in batches",
    "headers_invalid": "headers that failed batch validation",
    "batch_device_s": "cumulative device batch seconds",
}


@dataclass
class NodeMetrics:
    """The kernel's counters (NodeKernel.hs metric reporting analog).

    Batch-validation counts (`note_batch`) fold the TPU-specific
    `ValidatedBatch` events — one fused device batch per event — that
    previously went nowhere."""

    blocks_forged: int = 0
    blocks_could_not_forge: int = 0
    blocks_adopted_self: int = 0
    blocks_adopted_peer: int = 0
    chain_switches: int = 0
    slots_led: int = 0
    batches_validated: int = 0
    headers_validated: int = 0
    headers_invalid: int = 0
    batch_device_s: float = 0.0
    _mirrors: dict | None = field(
        default=None, repr=False, compare=False
    )

    def bind(self, registry) -> "NodeMetrics":
        """Mirror every subsequent fold into `oct_node_*_total` counters
        of an obs MetricsRegistry (idempotent per registry)."""
        self._mirrors = {
            name: registry.counter(f"oct_node_{name}_total", help_)
            for name, help_ in _COUNTER_HELP.items()
        }
        return self

    def inc(self, name: str, amount: float = 1) -> None:
        """Fold one count: the attribute AND its registry mirror."""
        setattr(self, name, getattr(self, name) + amount)
        if self._mirrors is not None:
            self._mirrors[name].inc(amount)

    def note_adopted(self, headers, our_cold_vk: bytes | None) -> None:
        for h in headers:
            if is_self_issued(h, our_cold_vk):
                self.inc("blocks_adopted_self")
            else:
                self.inc("blocks_adopted_peer")

    def note_batch(self, ev) -> None:
        """Fold one `ValidatedBatch` event (utils.trace): a fused device
        batch of `n_headers` lanes of which `n_valid` passed."""
        self.inc("batches_validated")
        self.inc("headers_validated", ev.n_valid)
        self.inc("headers_invalid", ev.n_headers - ev.n_valid)
        self.inc("batch_device_s", ev.device_s)
