"""BlockSupportsMetrics: self-issued detection + node block metrics.

Reference: `Ouroboros.Consensus.Block.SupportsMetrics` —
`isSelfIssued :: BlockConfig blk -> Header blk -> WhetherSelfIssued`
(the HFC and era instances dispatch per era), consumed by the node's
metric reporting (NodeKernel peer metrics; cardano-node maps the
tracers onto EKG/Prometheus). Here: compare the header's issuer key
against the node's forging credential, and fold per-adoption counts
into a `NodeMetrics` record the kernel owns.
"""

from __future__ import annotations

from dataclasses import dataclass, field


def is_self_issued(header, our_cold_vk: bytes | None) -> bool:
    """WhetherSelfIssued (SupportsMetrics.hs): did WE forge this block?
    Blocks without an issuer (mock/BFT-era headers) are never self."""
    if our_cold_vk is None:
        return False
    from .abstract import issuer_vk_of

    return issuer_vk_of(header) == our_cold_vk


@dataclass
class NodeMetrics:
    """The kernel's counters (NodeKernel.hs metric reporting analog)."""

    blocks_forged: int = 0
    blocks_could_not_forge: int = 0
    blocks_adopted_self: int = 0
    blocks_adopted_peer: int = 0
    chain_switches: int = 0
    slots_led: int = 0

    def note_adopted(self, headers, our_cold_vk: bytes | None) -> None:
        for h in headers:
            if is_self_issued(h, our_cold_vk):
                self.blocks_adopted_self += 1
            else:
                self.blocks_adopted_peer += 1
