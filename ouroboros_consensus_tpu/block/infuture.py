"""CheckInFuture: refuse to select blocks from the future.

Reference: `Ouroboros.Consensus.Fragment.InFuture` — `CheckInFuture m blk`
(InFuture.hs:45) truncates candidate fragments at the first header whose
slot onset is ahead of the wallclock, tolerating a configurable
`ClockSkew` (InFuture.hs:99; `defaultClockSkew` = 5 s). Chain selection
runs every candidate through this check before comparison, so a peer
cannot win selection by claiming future slots.

Simplification vs the reference: headers within the skew are ALSO
deferred here (the reference admits them into a retry queue,
cdbFutureBlocks, and reprocesses on the next slot tick; callers re-add
blocks naturally via ChainSync in this framework).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

DEFAULT_CLOCK_SKEW_SECONDS = 5.0  # InFuture.hs:99 defaultClockSkew


@dataclass
class CheckInFuture:
    """now() is the wallclock source (sim virtual time in tests); slot
    onset = slot * slot_length relative to the same epoch-0 origin."""

    now: Callable[[], float]
    slot_length: float = 1.0
    max_clock_skew: float = DEFAULT_CLOCK_SKEW_SECONDS

    def is_in_future(self, slot: int) -> bool:
        return slot * self.slot_length > self.now() + self.max_clock_skew

    def truncate(self, blocks: Sequence) -> tuple[list, list]:
        """(kept prefix, in-future suffix) — a candidate is cut at its
        FIRST in-future header (InFuture.hs checkInFuture)."""
        for i, b in enumerate(blocks):
            if self.is_in_future(b.slot):
                return list(blocks[:i]), list(blocks[i:])
        return list(blocks), []


def no_check() -> CheckInFuture:
    """dontCheck (InFuture.hs): for tools replaying historical chains."""
    return CheckInFuture(now=lambda: float("inf"))
