"""Hard-fork combinator: compose N eras into one protocol/ledger/block.

Reference: `Ouroboros.Consensus.HardFork.Combinator` — `HardForkBlock xs`
(Basics.hs:65), the per-era `Telescope` state (State/Types.hs:38), the
cross-era `ConsensusProtocol` instance (Combinator/Protocol.hs), ledger
(Combinator/Ledger.hs) and state translations (Translation.hs:20-22).

TPU-first inversion: the reference's type-level n-ary sums (SOP) become a
plain era index + dispatch tables. Batched validation is unaffected —
an era boundary is simply another batch cut, like an epoch boundary
(tools/db_analyser segments at min(epoch, era) granularity), so the fused
kernels never see mixed-era control flow.

Era transitions are config-driven (`TriggerHardForkAtEpoch` analog,
Cardano/Node.hs) via each era's `end_epoch`; ledger-decided transitions
(singleEraTransition) plug in by overriding `HardForkLedger.transition`.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Callable, Sequence

from ..utils import cbor
from .history import Summary


@dataclass(frozen=True)
class Era:
    """One era of the composite (SingleEraBlock analog)."""

    name: str
    protocol: Any  # ConsensusProtocol instance-as-object
    ledger: Any  # Ledger instance-as-object
    # translations INTO this era from the previous one (identity default)
    translate_chain_dep: Callable[[Any], Any] = lambda s: s
    translate_ledger_state: Callable[[Any], Any] = lambda s: s
    # tx translation INTO this era from the previous (InjectTxs.hs pair
    # translations); None = txs cannot cross this boundary
    translate_tx: Callable[[bytes], bytes] | None = None


@dataclass(frozen=True)
class HFState:
    """The Telescope collapsed to (current era index, its state) — past
    eras' states are dead after translation (State/Types.hs Past)."""

    era: int
    inner: Any

    @property
    def utxo(self):
        """Mempool anchoring reads the inner ledger state's UTxO (the
        HFC mempool projects into the current era, Combinator/Mempool.hs)."""
        return self.inner.utxo


@dataclass(frozen=True)
class TickedHFState:
    era: int
    inner: Any  # the era protocol's/ledger's ticked state

    @property
    def state(self) -> Any:
        """Un-ticked inner payload (mempool snapshot path reads the
        ticked LEDGER state's .state — delegate to the era's)."""
        return self.inner.state


class HardForkProtocol:
    """ConsensusProtocol (HardForkBlock xs) (Combinator/Protocol.hs)."""

    def __init__(self, eras: Sequence[Era], summary: Summary):
        assert len(eras) == len(summary.eras)
        self.eras = list(eras)
        self.summary = summary
        self.security_param = max(
            getattr(e.protocol, "security_param", 0) for e in eras
        )

    def era_of_slot(self, slot: int) -> int:
        return self.summary.era_index_of_slot(slot)

    @property
    def params(self):
        """Forging-side parameter view (KES schedule, leader coeff):
        Cardano keeps the KES period arithmetic uniform across eras, so
        the newest era's params stand for the composite (the HFC's
        forging config shape, Combinator/Forging.hs)."""
        return self.eras[-1].protocol.params

    def initial_state(self) -> HFState:
        return HFState(0, self.eras[0].protocol.initial_state())

    def _cross_eras(self, state: HFState, target: int) -> HFState:
        """Walk the telescope forward, translating at each boundary
        (Translation.hs translateChainDepState)."""
        era, inner = state.era, state.inner
        while era < target:
            era += 1
            inner = self.eras[era].translate_chain_dep(inner)
        return HFState(era, inner)

    def tick(self, ledger_view, slot: int, state: HFState) -> TickedHFState:
        target = self.era_of_slot(slot)
        if target < state.era:
            raise ValueError(f"slot {slot} is in past era {target} < {state.era}")
        state = self._cross_eras(state, target)
        ticked = self.eras[target].protocol.tick(ledger_view, slot, state.inner)
        return TickedHFState(target, ticked)

    def update(self, view, slot: int, ticked: TickedHFState) -> HFState:
        inner = self.eras[ticked.era].protocol.update(view, slot, ticked.inner)
        return HFState(ticked.era, inner)

    def reupdate(self, view, slot: int, ticked: TickedHFState) -> HFState:
        inner = self.eras[ticked.era].protocol.reupdate(view, slot, ticked.inner)
        return HFState(ticked.era, inner)

    def check_is_leader(self, can_be_leader, slot: int, ticked: TickedHFState):
        return self.eras[ticked.era].protocol.check_is_leader(
            can_be_leader, slot, ticked.inner
        )

    # -- chain order across eras (Combinator/Protocol/ChainSel.hs) --------

    def select_view(self, header):
        era = self.era_of_slot(header.slot)
        return (era, self.eras[era].protocol.select_view(header))

    @staticmethod
    def _block_no_of(view):
        """Every inner SelectView exposes a block number: richer views
        (Praos) as .block_no, simple protocols (BFT/PBFT/LeaderSchedule)
        return the block number itself."""
        return view.block_no if hasattr(view, "block_no") else view

    def compare_candidates(self, ours, theirs) -> int:
        """AcrossEraSelection: same era → era rules; different eras →
        block number only (the universally comparable component).
        None = empty chain, loses to any candidate (ConsensusProtocol
        contract relied on by ChainDB's initial selection)."""
        if theirs is None:
            return 0 if ours is None else -1
        if ours is None:
            return 1
        (ea, va), (eb, vb) = ours, theirs
        if ea == eb:
            return self.eras[ea].protocol.compare_candidates(va, vb)
        a_no, b_no = self._block_no_of(va), self._block_no_of(vb)
        return (b_no > a_no) - (b_no < a_no)

    # -- batched validation (era-segmented) --------------------------------

    def validate_batch(self, ticked: TickedHFState, views, collect_states=False):
        inner_proto = self.eras[ticked.era].protocol
        res = inner_proto.validate_batch(ticked.inner, views, collect_states)
        return replace(res, state=HFState(ticked.era, res.state)) if hasattr(
            res, "state"
        ) else res


class _HFMempoolView:
    """Era-tagged mempool scratch: the inner view plus which era's rules
    fold it (Combinator/Mempool.hs's era-indexed WrapValidatedGenTx)."""

    __slots__ = ("era", "inner")

    def __init__(self, era: int, inner):
        self.era = era
        self.inner = inner


class HardForkLedger:
    """LedgerState (HardForkBlock xs) (Combinator/Ledger.hs) — same
    telescope walk for ledger states."""

    def __init__(self, eras: Sequence[Era], summary: Summary):
        self.eras = list(eras)
        self.summary = summary

    def _cross_eras(self, state: HFState, target: int) -> HFState:
        era, inner = state.era, state.inner
        while era < target:
            era += 1
            inner = self.eras[era].translate_ledger_state(inner)
        return HFState(era, inner)

    def genesis_state(self, inner) -> HFState:
        return HFState(0, inner)

    def tick(self, state: HFState, slot: int):
        target = self.summary.era_index_of_slot(slot)
        if target < state.era:
            raise ValueError(f"slot {slot} is in past era {target} < {state.era}")
        state = self._cross_eras(state, target)
        return TickedHFState(target, self.eras[target].ledger.tick(state.inner, slot))

    def apply_block(self, ticked: TickedHFState, block) -> HFState:
        inner = self.eras[ticked.era].ledger.apply_block(
            ticked.inner, unwrap(block)
        )
        return HFState(ticked.era, inner)

    def reapply_block(self, ticked: TickedHFState, block) -> HFState:
        inner = self.eras[ticked.era].ledger.reapply_block(
            ticked.inner, unwrap(block)
        )
        return HFState(ticked.era, inner)

    def inspect(self, old_state: HFState, new_state: HFState) -> list:
        """InspectLedger for the HFC (Combinator/Ledger.hs
        inspectHardForkLedger): report era boundary crossings — and
        delegate to the current era's own inspect when it has one."""
        from ..ledger.inspect import HardForkEraTransition, inspect_ledger

        events: list = []
        if new_state.era != old_state.era:
            events.append(
                HardForkEraTransition(
                    message=(
                        f"era transition: {self.eras[old_state.era].name}"
                        f" -> {self.eras[new_state.era].name}"
                    ),
                    from_era=self.eras[old_state.era].name,
                    to_era=self.eras[new_state.era].name,
                )
            )
        else:
            events.extend(
                inspect_ledger(
                    self.eras[new_state.era].ledger,
                    old_state.inner,
                    new_state.inner,
                )
            )
        return events

    def tip_slot(self, state: HFState):
        return self.eras[state.era].ledger.tip_slot(state.inner)

    def protocol_ledger_view(self, ticked: TickedHFState):
        return self.eras[ticked.era].ledger.protocol_ledger_view(ticked.inner)

    def ledger_view_forecast_at(self, state: HFState):
        """Forecast that CROSSES era boundaries (the reference's
        cross-era forecast, HardFork/Combinator/Ledger.hs): a view for a
        slot past the next transition comes from the target era's ledger
        over the TRANSLATED state — forging and validation must agree on
        boundary-straddling views when eras derive them differently.
        The horizon stays the anchor era's (nothing past it is
        knowable)."""
        base = self.eras[state.era].ledger.ledger_view_forecast_at(state.inner)
        crossed_fc: dict[int, Any] = {}  # target era -> its Forecast

        def view_fn(slot):
            target = self.summary.era_index_of_slot(slot)
            if target <= state.era:
                # slots of the anchor era (or before it — the anchor
                # era's ledger still holds that history)
                return base.view_fn(slot)
            if target not in crossed_fc:
                # translate ONCE per target era (the anchor state is
                # immutable; Shelley's translation re-seals the whole
                # stake distribution — not per-slot work)
                crossed = self._cross_eras(state, target)
                crossed_fc[target] = self.eras[
                    target
                ].ledger.ledger_view_forecast_at(crossed.inner)
            # forecast_for, not view_fn: the TARGET era's own horizon
            # must also hold, or a pre-fork node would forge with views
            # a post-fork node refuses to produce
            return crossed_fc[target].forecast_for(slot)

        from ..ledger.abstract import Forecast

        return Forecast(at=base.at, max_for=base.max_for, view_fn=view_fn)

    def mempool_view(self, state: HFState, slot: int):
        """Mempool projection into the era of `slot` (the HFC mempool
        validates against the current era, Combinator/Mempool.hs): the
        anchor state is walked across any boundary first, then the inner
        ledger's own view seam applies (Shelley TxView / mock dict)."""
        target = self.summary.era_index_of_slot(slot)
        if isinstance(state, HFState):
            if target > state.era:
                state = self._cross_eras(state, target)
            era, inner_state = state.era, state.inner
        else:
            # an already-projected inner state: the forge path passes
            # TickedHFState.state, which unwraps to the era's own ledger
            # state — it belongs to the era of `slot`
            era, inner_state = target, state
        ledger = self.eras[era].ledger
        mk = getattr(ledger, "mempool_view", None)
        inner = mk(inner_state, slot) if mk is not None else dict(
            inner_state.utxo
        )
        return _HFMempoolView(era, inner)

    def apply_tx(self, view, tx_bytes: bytes):
        """Mempool path: an era-tagged view (from `mempool_view`)
        validates under ITS era's rules; a plain dict (legacy callers)
        under the newest era's (earlier-era txs reach here through
        inject_tx's translations — Combinator/Mempool.hs dispatches by
        the GenTx era tag)."""
        if isinstance(view, _HFMempoolView):
            view.inner = self.eras[view.era].ledger.apply_tx(
                view.inner, tx_bytes
            )
            return view
        return self.eras[-1].ledger.apply_tx(view, tx_bytes)

    def tick_then_apply(self, state, block):
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state, block):
        return self.reapply_block(self.tick(state, block.slot), block)


# -- era-tagged block wrapper (NestedContent / Serialisation analog) ---------


@dataclass(frozen=True)
class HardForkBlock:
    """A block tagged with its era (HardForkBlock's one-constructor-per-
    era sum collapsed to an index + payload)."""

    era: int
    block: Any

    @property
    def slot(self) -> int:
        return self.block.slot

    @property
    def block_no(self) -> int:
        return self.block.block_no

    @property
    def hash_(self) -> bytes:
        return self.block.hash_

    @property
    def prev_hash(self):
        return self.block.prev_hash

    @property
    def header(self):
        return self.block.header

    @property
    def txs(self):
        return self.block.txs

    @property
    def point(self):
        return self.block.point

    @property
    def bytes_(self) -> bytes:
        # era tag + inner bytes (Combinator/Serialisation era tags)
        return cbor.encode([self.era, self.block.bytes_])

    def check_integrity(self) -> bool:
        return self.block.check_integrity()


def unwrap(block):
    return block.block if isinstance(block, HardForkBlock) else block


def decode_block(data: bytes, era_decoders: Sequence[Callable[[bytes], Any]]):
    era, inner = cbor.decode(data)
    return HardForkBlock(era, era_decoders[era](inner))


# ---------------------------------------------------------------------------
# Cross-era transactions + queries
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class HardForkTx:
    """GenTx (HardForkBlock xs): a transaction tagged with the era whose
    rules produced it (Combinator/Mempool.hs)."""

    era: int
    tx: bytes


class CannotInjectTx(Exception):
    """InjectTxs.hs cannotInjectTx: no translation path to the current
    era (e.g. a Byron tx offered after the Shelley boundary with no
    Byron→Shelley tx translation configured)."""


class TxFromFutureEra(Exception):
    """A tx tagged with an era the chain has not reached yet."""


def inject_tx(eras: Sequence[Era], state_era: int, tx: HardForkTx) -> bytes:
    """Lift `tx` into the state's era through the pairwise translations
    (Combinator/InjectTxs.hs) — the HFC mempool runs every incoming tx
    through this before applying it under the CURRENT era's rules."""
    era, raw = tx.era, tx.tx
    if era > state_era:
        raise TxFromFutureEra(f"tx era {era} > chain era {state_era}")
    while era < state_era:
        translate = eras[era + 1].translate_tx
        if translate is None:
            raise CannotInjectTx(
                f"no tx translation {eras[era].name} -> {eras[era + 1].name}"
            )
        raw = translate(raw)
        era += 1
    return raw


def hard_fork_query(
    ledger: "HardForkLedger", summary: Summary, state: HFState,
    name: str, args=(),
):
    """Query (HardForkBlock xs) (Combinator/Ledger/Query.hs): HFC-level
    queries answered from the telescope + summary; anything else
    dispatches to the CURRENT era's ledger."""
    if name == "get_current_era":
        return state.era, ledger.eras[state.era].name
    if name == "get_era_start":
        return summary.eras[state.era].start.slot
    if name == "get_interpreter":
        # the reference ships the whole Summary to clients so they can
        # run time conversions locally (GetInterpreter)
        return summary
    inner = ledger.eras[state.era].ledger
    fn = getattr(inner, "query", None)
    if fn is None:
        raise KeyError(f"unknown hard-fork query {name!r}")
    return fn(state.inner, name, args)
