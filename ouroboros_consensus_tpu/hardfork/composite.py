"""The mixed-era composite: ByronMock(PBFT) → Shelley(TPraos) →
Babbage(Praos) [→ Conway(Praos) → Leios(Praos)] through the hard-fork
combinator — BASELINE config 5.

The optional 4th/5th eras (enabled by `conway_epochs`) are Praos-class
eras with GENUINELY different ledger parameters — Conway doubles the
epoch length and halves the active-slot coefficient, Leios changes both
again — so the HFC translations and the per-era epoch/threshold
arithmetic are non-trivial, mirroring the 7-era CardanoBlock
(Cardano/Block.hs:96) where every Shelley-family step changes ledger
params.

Reference: `CardanoBlock` (Cardano/Block.hs:96 — ByronBlock ':
CardanoShelleyEras), the `CanHardFork` pairwise translations
(Cardano/CanHardFork.hs:273), and `protocolInfoCardano` (Cardano/Node.hs)
collapsed to the three protocol classes that matter for consensus: one
PBFT era and the two Praos-class eras sharing the batched TPU crypto
backend. Era boundaries are config-driven (TriggerHardForkAtEpoch).

`synthesize` forges a chain crossing both transitions into an on-disk
ImmutableDB of era-tagged blocks; `revalidate` streams it back and
validates every segment with the chosen backend — the Praos-class
segments as fused device batches, the PBFT segment as a batched Ed25519
verify + host threshold fold.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from fractions import Fraction
from typing import Sequence

import numpy as np

from ..block import forge as praos_forge
from ..block.praos_block import Block as PraosBlock
from ..ops import ed25519_batch
from ..protocol import batch as pbatch
from ..protocol import nonces, praos, tpraos
from ..protocol.instances import PBftParams, PBftProtocol, PraosProtocol
from ..protocol.views import hash_vrf_vk
from ..storage.immutable import ImmutableDB
from ..testing import fixtures
from .byron_mock import ByronMockBlock
from .combinator import (
    Era,
    HardForkBlock,
    HardForkLedger,
    HardForkProtocol,
    decode_block,
)
from .history import EraParams, summarize


@dataclass(frozen=True)
class CardanoMockConfig:
    """Genesis-file analog for the 3-era composite."""

    byron_epochs: int = 2
    byron_epoch_length: int = 40
    shelley_epochs: int = 2
    n_delegs: int = 2  # genesis delegates (byron signers = tpraos overlay)
    shelley_d: Fraction = Fraction(1, 2)
    shelley_f: Fraction = Fraction(1)
    babbage_f: Fraction = Fraction(1)
    epoch_length: int = 60  # shelley + babbage
    # 4th/5th eras (None = the classic 3-era composite). Conway doubles
    # the epoch length and changes f; Leios changes both again.
    conway_epochs: int | None = None  # babbage epochs before conway
    conway_f: Fraction = Fraction(1, 2)
    conway_epoch_length: int = 120
    leios_epochs: int | None = None  # conway epochs before leios
    leios_f: Fraction = Fraction(1)
    leios_epoch_length: int = 30
    k: int = 5
    kes_depth: int = 3
    # with n_delegs=2 round-robin and window k, each delegate signs
    # ~k/2 + 1 of any window — the threshold must clear that
    pbft_threshold: Fraction = Fraction(4, 5)
    shelley_initial_nonce: bytes = b"\x0b" * 32
    # LEDGERS IN THE LOOP: era 0 = real Byron-class UTxO+delegation
    # ledger, era 1 = real Shelley STS, eras 2+ = Mary-class multi-asset
    # rules (each with ITS era's epoch length via the era-relative
    # ShelleyGenesis) — synthesize forges real value-moving txs and
    # revalidate folds every block through the era ledgers (the
    # reference's db-analyser always replays the real ledger; opt-in so
    # the consensus-only bench path stays unchanged).
    with_ledgers: bool = False
    # THE FULL 7-ERA CHAIN (Cardano/Block.hs:96): byron → shelley →
    # allegra → mary → alonzo → babbage → conway, each Shelley-family
    # step a genuinely different RULE SET (timelocks / multi-asset /
    # phase-2 scripts / reference inputs / governance), TPraos through
    # alonzo and Praos from babbage on (Shelley/Eras.hs:85-97). Each
    # bounded era lasts `era_epochs`; conway is open-ended. Overrides
    # the conway_epochs/leios_epochs legacy knobs.
    seven_era: bool = False
    era_epochs: int = 2


class CardanoMock:
    """The assembled composite (protocolInfoCardano analog)."""

    def __init__(self, cfg: CardanoMockConfig):
        self.cfg = cfg
        self.delegs = [
            fixtures.make_pool(100 + i, kes_depth=cfg.kes_depth)
            for i in range(cfg.n_delegs)
        ]
        self.pools = [fixtures.make_pool(0, kes_depth=cfg.kes_depth)]
        base_view = fixtures.make_ledger_view(self.pools)
        self.praos_view = base_view
        self.tpraos_view = tpraos.TPraosLedgerView(
            pool_distr=base_view.pool_distr,
            gen_delegs=[
                tpraos.GenDeleg(d.vk_cold, hash_vrf_vk(d.vrf_vk))
                for d in self.delegs
            ],
        )
        common = dict(
            slots_per_kes_period=100,
            max_kes_evolutions=62,
            security_param=cfg.k,
            epoch_length=cfg.epoch_length,
            kes_depth=cfg.kes_depth,
        )
        self.tpraos_params = tpraos.TPraosParams(
            praos=praos.PraosParams(
                active_slot_coeff=cfg.shelley_f, **common
            ),
            decentralization=cfg.shelley_d,
        )
        self.praos_params = praos.PraosParams(
            active_slot_coeff=cfg.babbage_f, **common
        )
        self.conway_params = praos.PraosParams(
            active_slot_coeff=cfg.conway_f,
            **{**common, "epoch_length": cfg.conway_epoch_length},
        )
        self.leios_params = praos.PraosParams(
            active_slot_coeff=cfg.leios_f,
            **{**common, "epoch_length": cfg.leios_epoch_length},
        )
        self.pbft = PBftProtocol(
            PBftParams(
                num_genesis_keys=cfg.n_delegs,
                threshold=cfg.pbft_threshold,
                window=cfg.k,
                security_param=cfg.k,
            ),
            [d.vk_cold for d in self.delegs],
        )
        self.tpraos_proto = tpraos.TPraosProtocol(self.tpraos_params)
        nonce = cfg.shelley_initial_nonce
        if cfg.seven_era:
            self._init_seven_era(nonce)
            return
        era_params = [
            EraParams(cfg.byron_epoch_length, Fraction(1)),
            EraParams(cfg.epoch_length, Fraction(1)),
            EraParams(cfg.epoch_length, Fraction(1)),
        ]
        bounds = [
            cfg.byron_epochs,
            cfg.byron_epochs + cfg.shelley_epochs,
            None,
        ]
        if cfg.conway_epochs is not None:
            era_params.append(EraParams(cfg.conway_epoch_length, Fraction(1)))
            bounds[-1] = bounds[-2] + cfg.conway_epochs
            bounds.append(None)
            if cfg.leios_epochs is not None:
                era_params.append(
                    EraParams(cfg.leios_epoch_length, Fraction(1))
                )
                bounds[-1] = bounds[-2] + cfg.leios_epochs
                bounds.append(None)
        self.summary = summarize(Fraction(0), era_params, bounds)
        self.praos_proto = PraosProtocol(self.praos_params)
        self.eras = [
            Era("byron", self.pbft, ledger=None),
            Era(
                "shelley",
                self.tpraos_proto,
                ledger=None,
                # Byron's PBftState carries nothing Praos-shaped: Shelley
                # starts from the genesis nonce (CanHardFork.hs
                # translateLedgerStateByronToShelley + protocol init)
                translate_chain_dep=lambda _s: replace(
                    tpraos.TPraosState(), epoch_nonce=nonce
                ),
            ),
            Era(
                "babbage",
                self.praos_proto,
                ledger=None,
                translate_chain_dep=tpraos.translate_state,
            ),
        ]
        self.decoders = [
            ByronMockBlock.from_bytes,
            PraosBlock.from_bytes,
            PraosBlock.from_bytes,
        ]
        if cfg.conway_epochs is not None:
            # Praos -> Praos translation: the chain-dep state (nonces,
            # ocert counters) carries over verbatim; what CHANGES is the
            # era's ledger params (epoch length, f) — the translation is
            # non-trivial at the time layer, exactly like the
            # Shelley-family steps of CanHardFork.hs:273
            self.eras.append(
                Era(
                    "conway",
                    PraosProtocol(self.conway_params),
                    ledger=None,
                    translate_chain_dep=lambda s: s,
                )
            )
            self.decoders.append(PraosBlock.from_bytes)
            if cfg.leios_epochs is not None:
                self.eras.append(
                    Era(
                        "leios",
                        PraosProtocol(self.leios_params),
                        ledger=None,
                        translate_chain_dep=lambda s: s,
                    )
                )
                self.decoders.append(PraosBlock.from_bytes)
        self.hf = HardForkProtocol(self.eras, self.summary)
        self.inner_params = [
            None,
            self.tpraos_params,
            self.praos_params,
            self.conway_params,
            self.leios_params,
        ]
        self.hf_ledger = None
        if cfg.with_ledgers:
            self._init_ledgers()

    def _init_seven_era(self, nonce: bytes) -> None:
        """The full 7-era composite: era list, HFC summary, decoders,
        and (with_ledgers) the six real rule sets with their pairwise
        translations (CanHardFork.hs:273)."""
        cfg = self.cfg
        era_params = [EraParams(cfg.byron_epoch_length, Fraction(1))] + [
            EraParams(cfg.epoch_length, Fraction(1))
        ] * 6
        bounds: list = [cfg.byron_epochs]
        for _ in range(5):
            bounds.append(bounds[-1] + cfg.era_epochs)
        bounds.append(None)
        self.summary = summarize(Fraction(0), era_params, bounds)
        self.praos_proto = PraosProtocol(self.praos_params)
        self.eras = [
            Era("byron", self.pbft, ledger=None),
            Era(
                "shelley", self.tpraos_proto, ledger=None,
                translate_chain_dep=lambda _s: replace(
                    tpraos.TPraosState(), epoch_nonce=nonce
                ),
            ),
            Era("allegra", self.tpraos_proto, ledger=None,
                translate_chain_dep=lambda s: s),
            Era("mary", self.tpraos_proto, ledger=None,
                translate_chain_dep=lambda s: s),
            Era("alonzo", self.tpraos_proto, ledger=None,
                translate_chain_dep=lambda s: s),
            # the protocol CLASS changes here, like the reference's
            # Babbage step (TPraos -> Praos)
            Era("babbage", self.praos_proto, ledger=None,
                translate_chain_dep=tpraos.translate_state),
            Era("conway", self.praos_proto, ledger=None,
                translate_chain_dep=lambda s: s),
        ]
        self.decoders = [ByronMockBlock.from_bytes] + [
            PraosBlock.from_bytes
        ] * 6
        self.inner_params = [
            None,
            self.tpraos_params, self.tpraos_params, self.tpraos_params,
            self.tpraos_params,
            self.praos_params, self.praos_params,
        ]
        self.hf = HardForkProtocol(self.eras, self.summary)
        self.hf_ledger = None
        if cfg.with_ledgers:
            self._init_seven_era_ledgers()

    def _init_seven_era_ledgers(self) -> None:
        from ..ledger import allegra as al
        from ..ledger import alonzo as az
        from ..ledger import babbage as bb
        from ..ledger import conway as cw
        from ..ledger import mary as mary_mod
        from ..ledger.allegra import AllegraLedger
        from ..ledger.alonzo import AlonzoLedger
        from ..ledger.babbage import BabbageLedger
        from ..ledger.byron import ByronGenesis, ByronLedger, ByronPParams
        from ..ledger.conway import ConwayLedger
        from ..ledger.mary import MaryLedger
        from ..ledger.shelley import (
            PParams as ShPParams,
            ShelleyGenesis,
            ShelleyLedger,
        )

        cfg = self.cfg
        shelley_start = self.summary.eras[1].start.slot
        self.byron_ledger = ByronLedger(ByronGenesis(
            pparams=ByronPParams(
                min_fee_a=self.LEDGER_BYRON_FEE, min_fee_b=0
            ),
            genesis_keys=tuple(d.vk_cold for d in self.delegs),
            epoch_length=cfg.byron_epoch_length,
            security_param=cfg.k,
        ))

        def era_genesis(era_ix: int) -> ShelleyGenesis:
            bound = self.summary.eras[era_ix].start
            return ShelleyGenesis(
                pparams=ShPParams(min_fee_a=0, min_fee_b=0),
                epoch_length=cfg.epoch_length,
                stability_window=3 * cfg.k,
                era_start_slot=bound.slot,
                era_start_epoch=bound.epoch,
            )

        shelley_led = ShelleyLedger(era_genesis(1))
        allegra_led = AllegraLedger(era_genesis(2))
        mary_led = MaryLedger(era_genesis(3))
        alonzo_led = AlonzoLedger(era_genesis(4))
        babbage_led = BabbageLedger(era_genesis(5))
        conway_led = ConwayLedger(era_genesis(6))
        self.eras = [
            replace(self.eras[0], ledger=self.byron_ledger),
            replace(
                self.eras[1], ledger=shelley_led,
                translate_ledger_state=(
                    lambda st: shelley_led.translate_from_utxo_ledger(
                        st, at_slot=shelley_start
                    )
                ),
            ),
            replace(
                self.eras[2], ledger=allegra_led,
                # Shelley→Allegra: state identical (Coin stays Coin)
                translate_ledger_state=allegra_led.translate_from_shelley,
                translate_tx=al.translate_tx_from_shelley,
            ),
            replace(
                self.eras[3], ledger=mary_led,
                # Allegra→Mary: Coin widens to MaryValue
                translate_ledger_state=mary_led.translate_from_allegra,
                translate_tx=mary_mod.translate_tx_from_allegra,
            ),
            replace(
                self.eras[4], ledger=alonzo_led,
                # Mary→Alonzo: pparams widen with script economics
                translate_ledger_state=alonzo_led.translate_from_mary,
                translate_tx=az.translate_tx_from_mary,
            ),
            replace(
                self.eras[5], ledger=babbage_led,
                translate_ledger_state=babbage_led.translate_from_alonzo,
                translate_tx=bb.translate_tx_from_alonzo,
            ),
            replace(
                self.eras[6], ledger=conway_led,
                # Babbage→Conway: ConwayState (gov sub-state), PPUP
                # proposals dropped
                translate_ledger_state=conway_led.translate_from_babbage,
                translate_tx=cw.translate_tx_from_babbage,
            ),
        ]
        self.hf = HardForkProtocol(self.eras, self.summary)
        self.hf_ledger = HardForkLedger(self.eras, self.summary)

    def is_tpraos_era(self, era: int) -> bool:
        return isinstance(self.eras[era].protocol, tpraos.TPraosProtocol)

    # the well-known spending key of the ledger-backed composite: the
    # whole synthesized value chain rides on it (revalidate re-derives
    # the genesis outputs from it)
    LEDGER_SPEND_SEED = b"\x51" * 32
    LEDGER_GENESIS_COIN = 10_000_000
    LEDGER_BYRON_FEE = 10
    MINT_POLICY_SEED = b"\x52" * 32
    MINT_ASSET = b"MIX"

    def _init_ledgers(self) -> None:
        from ..ledger import mary as mary_mod
        from ..ledger.byron import ByronGenesis, ByronLedger, ByronPParams
        from ..ledger.mary import MaryLedger
        from ..ledger.shelley import (
            PParams as ShPParams,
            ShelleyGenesis,
            ShelleyLedger,
        )

        cfg = self.cfg
        shelley_start = self.summary.eras[1].start.slot
        self.byron_ledger = ByronLedger(ByronGenesis(
            pparams=ByronPParams(
                min_fee_a=self.LEDGER_BYRON_FEE, min_fee_b=0
            ),
            genesis_keys=tuple(d.vk_cold for d in self.delegs),
            epoch_length=cfg.byron_epoch_length,
            security_param=cfg.k,
        ))

        def era_genesis(era_ix: int, epoch_length: int) -> ShelleyGenesis:
            # era-relative epoch arithmetic from the HFC Summary bound
            # (the reference hands the ledger an EpochInfo the same way)
            bound = self.summary.eras[era_ix].start
            return ShelleyGenesis(
                pparams=ShPParams(min_fee_a=0, min_fee_b=0),
                epoch_length=epoch_length,
                stability_window=3 * cfg.k,
                era_start_slot=bound.slot,
                era_start_epoch=bound.epoch,
            )

        self.shelley_ledger = ShelleyLedger(
            era_genesis(1, cfg.epoch_length)
        )
        self.mary_ledger = MaryLedger(era_genesis(2, cfg.epoch_length))
        ledger_eras = [
            replace(self.eras[0], ledger=self.byron_ledger),
            replace(
                self.eras[1],
                ledger=self.shelley_ledger,
                # Byron->Shelley: carry the UTxO verbatim
                # (CanHardFork.hs translateLedgerStateByronToShelley)
                translate_ledger_state=(
                    lambda st: self.shelley_ledger.translate_from_utxo_ledger(
                        st, at_slot=shelley_start
                    )
                ),
            ),
            replace(
                self.eras[2],
                ledger=self.mary_ledger,
                # Shelley->Mary: Coin widens to MaryValue
                translate_ledger_state=self.mary_ledger.translate_from_shelley,
                translate_tx=mary_mod.translate_tx_from_shelley,
            ),
        ]
        # 4th/5th eras: Mary-class rules under the era's OWN epoch
        # length (the era-relative genesis makes a mid-chain epoch-length
        # change sound); the state carries over verbatim — what changes
        # is the rules' clock, like the reference's later-era steps
        for ix in range(3, len(self.eras)):
            ln = (cfg.conway_epoch_length if ix == 3
                  else cfg.leios_epoch_length)
            led = MaryLedger(era_genesis(ix, ln))
            ledger_eras.append(replace(
                self.eras[ix],
                ledger=led,
                translate_ledger_state=lambda st: st,
                translate_tx=lambda tx: tx,
            ))
        self.eras = ledger_eras
        self.hf = HardForkProtocol(self.eras, self.summary)
        self.hf_ledger = HardForkLedger(self.eras, self.summary)

    def ledger_genesis_state(self):
        """The HFState the ledger-backed chain starts from (Byron era,
        one genesis output held by the well-known spending key)."""
        from ..ledger.byron import addr_of
        from ..ops.host import ed25519 as host_ed25519

        addr = addr_of(host_ed25519.secret_to_public(self.LEDGER_SPEND_SEED))
        inner = self.byron_ledger.genesis_state(
            [(addr, self.LEDGER_GENESIS_COIN)]
        )
        return self.hf_ledger.genesis_state(inner)

    def view_for_era(self, era: int):
        if era == 0:
            return None
        return self.tpraos_view if self.is_tpraos_era(era) else self.praos_view


# ---------------------------------------------------------------------------
# Synthesis (db-synthesizer over the composite)
# ---------------------------------------------------------------------------


class _LedgerTxChain:
    """The value chain the ledger-backed composite forges: era-0 txs
    spend Byron UTxO (fee-paying, witnessed), the carried output is
    spent under the Shelley rules, and the Mary-class era mints a native
    asset that rides the rest of the chain — so revalidation proves
    era-0 value stayed spendable across BOTH translations."""

    def __init__(self, cm: "CardanoMock"):
        from ..ledger.byron import addr_of
        from ..ops.host import ed25519 as host_ed25519

        self.cm = cm
        self.vk = host_ed25519.secret_to_public(cm.LEDGER_SPEND_SEED)
        self.addr = addr_of(self.vk)
        self.outpoint = (bytes(32), 0)
        self.value = cm.LEDGER_GENESIS_COIN
        self.assets: dict = {}
        self.minted = False

    def tx_for(self, era: int) -> bytes:
        """One tx for the next block of `era`, dispatched on the era's
        LEDGER CLASS (the same builder serves the legacy 3/5-era chain,
        where the later eras run Mary-class rules, and the 7-era chain,
        where every era has its own rule set)."""
        from ..ledger.allegra import AllegraLedger
        from ..ledger.alonzo import AlonzoLedger
        from ..ledger.babbage import BabbageLedger
        from ..ledger.byron import ByronLedger
        from ..ledger.conway import ConwayLedger
        from ..ledger.mary import MaryLedger
        from ..ledger.shelley import ShelleyLedger

        led = self.cm.eras[era].ledger
        if isinstance(led, ByronLedger):
            return self._byron_tx()
        if isinstance(led, ConwayLedger):
            return self._conway_tx()
        if isinstance(led, BabbageLedger):
            return self._babbage_tx()
        if isinstance(led, AlonzoLedger):
            return self._alonzo_tx()
        if isinstance(led, MaryLedger):
            return self._mary_tx()
        if isinstance(led, AllegraLedger):
            return self._allegra_tx()
        assert isinstance(led, ShelleyLedger), led
        return self._shelley_tx()

    def _byron_tx(self) -> bytes:
        from ..ledger import byron as byron_led

        fee = self.cm.LEDGER_BYRON_FEE
        outs = [(self.addr, self.value - fee)]
        tx = byron_led.make_tx(
            [self.outpoint], outs, [self.cm.LEDGER_SPEND_SEED]
        )
        self.outpoint = (byron_led.tx_id_of([self.outpoint], outs), 0)
        self.value -= fee
        return tx

    def _shelley_tx(self) -> bytes:
        from ..ledger import shelley as shelley_mod

        tx = shelley_mod.encode_tx(
            [self.outpoint], [(self.addr, None, self.value)],
            fee=0, ttl=2**62,
        )
        self.outpoint = (shelley_mod.tx_id(tx), 0)
        return tx

    def _allegra_tx(self) -> bytes:
        from ..ledger import allegra as al
        from ..ledger import shelley as shelley_mod

        tx = al.encode_tx(
            [self.outpoint], [(self.addr, None, self.value)], fee=0,
        )
        self.outpoint = (shelley_mod.tx_id(tx), 0)
        return tx

    def _mary_tx(self) -> bytes:
        from ..ledger import mary as mary_mod
        from ..ledger import shelley as shelley_mod
        from ..ops.host import ed25519 as host_ed25519

        # mint once, then carry the asset along
        pid = mary_mod.policy_id(
            host_ed25519.secret_to_public(self.cm.MINT_POLICY_SEED)
        )
        if not self.minted:
            self.assets = {(pid, self.cm.MINT_ASSET): 1_000}
            outs = [(self.addr, None,
                     mary_mod.MaryValue(self.value, self.assets))]
            wit = mary_mod.make_mint_witness(
                self.cm.MINT_POLICY_SEED, [self.outpoint], outs, 0,
                (None, None), {self.cm.MINT_ASSET: 1_000},
            )
            tx = mary_mod.encode_tx([self.outpoint], outs, mint=[wit])
            self.minted = True
        else:
            outs = [(self.addr, None,
                     mary_mod.MaryValue(self.value, self.assets))]
            tx = mary_mod.encode_tx([self.outpoint], outs)
        self.outpoint = (shelley_mod.tx_id(tx), 0)
        return tx

    # phase-2 exercise state (alonzo era): 0 = not started, 1 = locked
    # (p2/collateral outpoints live), 2 = spent
    _p2_stage = 0
    _p2_out = None
    _coll_out = None
    _gov_stage = 0
    _gov_action_tid = None

    def _p2_script(self):
        from ..ledger import alonzo as az
        from ..utils import cbor

        script = az.plutus_script([4, [1], [2]])  # redeemer == datum
        datum = cbor.encode(b"open-sesame")
        return script, datum

    def _alonzo_tx(self) -> bytes:
        from ..ledger import allegra as al
        from ..ledger import alonzo as az
        from ..ledger import mary as mary_mod
        from ..ledger import shelley as shelley_mod
        from ..utils import cbor

        script, datum = self._p2_script()
        if self._p2_stage == 0:
            # split: carry + a phase-2 locked output + ada-only collateral
            saddr = al.script_addr(script)
            dh = az.datum_hash(datum)
            outs = [
                (self.addr, None,
                 mary_mod.MaryValue(self.value - 10, self.assets)),
                (saddr, None, 5, dh),
                (self.addr, None, 5),
            ]
            tx = az.encode_tx([self.outpoint], outs)
            tid = shelley_mod.tx_id(tx)
            self.outpoint = (tid, 0)
            self._p2_out = (tid, 1)
            self._coll_out = (tid, 2)
            self.value -= 10
            self._p2_stage = 1
            return tx
        if self._p2_stage == 1:
            # spend the locked output under the script (phase 2 runs
            # during revalidation, incl. the ledger replay)
            tx = az.encode_tx(
                [self._p2_out], [(self.addr, None, 4)],
                collateral=[self._coll_out],
                scripts=[script], datums=[datum],
                redeemers=[(0, 0, cbor.decode(datum))],
                budget=100, fee=1,
            )
            self._p2_stage = 2
            return tx
        tx = az.encode_tx(
            [self.outpoint],
            [(self.addr, None, mary_mod.MaryValue(self.value, self.assets))],
        )
        self.outpoint = (shelley_mod.tx_id(tx), 0)
        return tx

    def _babbage_tx(self) -> bytes:
        from ..ledger import babbage as bb
        from ..ledger import mary as mary_mod
        from ..ledger import shelley as shelley_mod

        tx = bb.encode_tx(
            [self.outpoint],
            [(self.addr, None, mary_mod.MaryValue(self.value, self.assets))],
        )
        self.outpoint = (shelley_mod.tx_id(tx), 0)
        return tx

    DREP_CRED = b"composite-drep-cred-28-bytes"  # 28 bytes

    def _conway_tx(self) -> bytes:
        from ..ledger import conway as cw
        from ..ledger import mary as mary_mod
        from ..ledger import shelley as shelley_mod

        if self._gov_stage == 0:
            # register a DRep and propose a (harmless) param change —
            # deposits ride the conservation equation; with no stake
            # delegated the action expires and refunds to treasury
            pp = cw.ConwayPParams()
            dep = pp.drep_deposit + pp.gov_action_deposit
            tx = cw.encode_tx(
                [self.outpoint],
                [(self.addr, None,
                  mary_mod.MaryValue(self.value - dep, self.assets))],
                certs=[[7, self.DREP_CRED]],
                proposals=[(self.DREP_CRED, [0, {b"min_fee_b": 0}])],
            )
            tid = shelley_mod.tx_id(tx)
            self.outpoint = (tid, 0)
            self.value -= dep
            self._gov_action_tid = tid
            self._gov_stage = 1
            return tx
        if self._gov_stage == 1:
            # the registered DRep votes yes (zero stake — exercises the
            # vote path without ratifying)
            tx = cw.encode_tx(
                [self.outpoint],
                [(self.addr, None,
                  mary_mod.MaryValue(self.value, self.assets))],
                votes=[(self.DREP_CRED, self._gov_action_tid, 0, True)],
            )
            self.outpoint = (shelley_mod.tx_id(tx), 0)
            self._gov_stage = 2
            return tx
        tx = cw.encode_tx(
            [self.outpoint],
            [(self.addr, None, mary_mod.MaryValue(self.value, self.assets))],
        )
        self.outpoint = (shelley_mod.tx_id(tx), 0)
        return tx


def synthesize(path: str, cfg: CardanoMockConfig, n_slots: int, chunk_size: int = 500):
    """Forge a chain crossing both era boundaries; returns block count."""
    from . import byron_mock

    cm = CardanoMock(cfg)
    os.makedirs(path, exist_ok=True)
    imm = ImmutableDB(os.path.join(path, "immutable"), chunk_size=chunk_size)
    if not imm.is_empty:
        raise RuntimeError(f"refusing to forge into non-empty DB at {path}")

    st = cm.hf.initial_state()
    chain = _LedgerTxChain(cm) if cfg.with_ledgers else None
    lst = cm.ledger_genesis_state() if cfg.with_ledgers else None
    prev: bytes | None = None
    block_no = 0
    n_blocks = 0
    for slot in range(n_slots):
        era = cm.hf.era_of_slot(slot)
        ticked = cm.hf.tick(cm.view_for_era(era), slot, st)
        if era == 0:
            if slot % cfg.byron_epoch_length == 0:
                # each Byron epoch opens with an EBB (Byron/EBBs.hs):
                # unsigned, empty, block number NOT advanced
                ebb = byron_mock.forge_ebb(
                    slot=slot, block_no=max(0, block_no - 1), prev_hash=prev
                )
                hfb = HardForkBlock(era, ebb)
                imm.append_block(slot, ebb.block_no, hfb.hash_, hfb.bytes_)
                st = cm.hf.reupdate(ebb.header.to_view(), slot, ticked)
                if lst is not None:
                    lst = cm.hf_ledger.tick_then_apply(lst, hfb)
                prev = hfb.hash_
                n_blocks += 1
                continue  # the EBB owns the epoch's first slot
            j = slot % cfg.n_delegs
            blk = byron_mock.forge_block(
                cm.delegs[j].cold_seed,
                slot=slot, block_no=block_no, prev_hash=prev,
                txs=(
                    (chain.tx_for(0),) if chain is not None
                    else (b"byron-tx-%d" % slot,)
                ),
            )
        else:
            params = cm.inner_params[era]
            eta0 = ticked.inner.state.epoch_nonce
            if cm.is_tpraos_era(era):
                a = tpraos.overlay_slot_assignment(
                    cm.tpraos_params, cfg.n_delegs, slot
                )
                if a is not None:
                    active, j = a
                    if not active:
                        continue  # inactive overlay slot stays empty
                    creds = cm.delegs[j]
                else:
                    creds = cm.pools[0]
                inner_params = cm.tpraos_params.praos
            else:
                creds = cm.pools[0]
                inner_params = params
                if inner_params.active_slot_coeff != 1:
                    # f < 1 era: consult the real leader lottery
                    win = praos.check_is_leader(
                        inner_params,
                        fixtures.can_be_leader(creds),
                        slot,
                        praos.TickedPraosState(
                            replace(
                                praos.PraosState(), epoch_nonce=eta0
                            ),
                            cm.praos_view,
                        ),
                    )
                    if win is None:
                        continue
            blk = praos_forge.forge_block(
                inner_params, creds,
                slot=slot, block_no=block_no, prev_hash=prev,
                epoch_nonce=eta0,
                txs=(
                    (chain.tx_for(era),) if chain is not None
                    else (b"tx-%d" % slot,)
                ),
            )
        hfb = HardForkBlock(era, blk)
        imm.append_block(slot, block_no, hfb.hash_, hfb.bytes_)
        st = cm.hf.reupdate(blk.header.to_view(), slot, ticked)
        if lst is not None:
            lst = cm.hf_ledger.tick_then_apply(lst, hfb)
        prev = hfb.hash_
        block_no += 1
        n_blocks += 1
    imm.flush()
    return n_blocks


# ---------------------------------------------------------------------------
# Revalidation (db-analyser --only-validation over the composite)
# ---------------------------------------------------------------------------


@dataclass
class MixedResult:
    n_blocks: int = 0
    n_valid: int = 0
    error: Exception | None = None
    final_state: object | None = None
    per_era: dict | None = None
    final_ledger_state: object | None = None  # with_ledgers only


def _bucket_pad(items, fill):
    n = pbatch.bucket_size(len(items))
    return items + [fill] * (n - len(items)), len(items)


def _validate_pbft_segment(proto: PBftProtocol, headers, st, backend: str):
    """Byron segment: signatures batched (device Ed25519 kernel or the
    native C++ verifier), delegate-membership + window threshold folded
    sequentially on host — the exact PBft rule order (Protocol/PBFT.hs
    :284: delegate check, signature, threshold)."""
    from ..protocol.instances import PBFT_BOUNDARY_VIEW

    views = [h.to_view() for h in headers]
    if backend == "host":
        for i, (h, view) in enumerate(zip(headers, views)):
            try:
                st = proto.update(view, h.slot, proto.tick(None, h.slot, st))
            except Exception as e:
                return st, i, e
        return st, len(views), None

    # EBBs (PBftValidateBoundary) carry no signature: exclude their
    # lanes from the batch and skip them in the host fold below
    regular = [v for v in views if v is not PBFT_BOUNDARY_VIEW]
    if backend == "native":
        from .. import native_loader as nl

        reg_ok = [
            nl.native_ed25519_verify(
                v.issuer_vk, v.signature, v.signed_bytes
            )
            for v in regular
        ]
    elif regular:
        padded, n = _bucket_pad(regular, regular[0])
        ok = ed25519_batch.verify_batch(
            [v.issuer_vk for v in padded],
            [v.signature for v in padded],
            [v.signed_bytes for v in padded],
        )
        reg_ok = list(ok[:n])
    else:
        reg_ok = []
    it = iter(reg_ok)
    sig_ok = [True if v is PBFT_BOUNDARY_VIEW else next(it) for v in views]
    for i, (h, view) in enumerate(zip(headers, views)):
        try:
            if view is PBFT_BOUNDARY_VIEW:
                continue  # boundary: no state change (PBFT.hs:326)
            st = proto.apply_checked_sig(st, h.slot, view.issuer_vk, sig_ok[i])
        except Exception as e:
            return st, i, e
    return st, len(views), None


def revalidate(path: str, cfg: CardanoMockConfig, backend: str = "device") -> MixedResult:
    """Full mixed-era revalidation (config 5: Cardano/CanHardFork.hs:273
    semantics): decode era-tagged blocks, walk the telescope, validate
    each era segment with its protocol — Praos-class eras through the
    batched backend."""
    cm = CardanoMock(cfg)
    # repair=False: this analysis holds no DB lock (direct embedder —
    # COVERAGE.md §5.17 honest gap), so it must never mutate the store;
    # a lagging index is reparsed in memory only
    imm = ImmutableDB(os.path.join(path, "immutable"), repair=False)
    res = MixedResult(per_era={})

    blocks = [decode_block(raw, cm.decoders) for _e, raw in imm.stream_all()]
    res.n_blocks = len(blocks)
    st = cm.hf.initial_state()
    i = 0
    while i < len(blocks):
        era = blocks[i].era
        j = i
        while j < len(blocks) and blocks[j].era == era:
            j += 1
        seg = blocks[i:j]
        # walk the telescope into this era (translations)
        st = cm.hf._cross_eras(st, era)
        proto = cm.eras[era].protocol
        if era == 0:
            inner, n_ok, err = _validate_pbft_segment(
                proto, [b.header for b in seg], st.inner, backend
            )
            st = replace(st, inner=inner)
        else:
            params = cm.inner_params[era]
            lview = cm.view_for_era(era)
            inner = st.inner
            n_ok = 0
            err = None
            # epoch-segmented batches inside the era segment
            s0 = 0
            hvs = [b.header.to_view() for b in seg]
            inner_backend = "host-fold" if backend == "host" else backend
            while s0 < len(hvs):
                s1 = s0
                ep = params.epoch_of(hvs[s0].slot)
                while s1 < len(hvs) and params.epoch_of(hvs[s1].slot) == ep:
                    s1 += 1
                ticked = proto.tick(lview, hvs[s0].slot, inner)
                b = proto.validate_batch(
                    ticked, hvs[s0:s1], backend=inner_backend
                )
                inner = b.state
                n_ok += b.n_valid
                if b.error is not None:
                    err = b.error
                    break
                s0 = s1
            st = replace(st, inner=inner)
        res.n_valid += n_ok
        res.per_era[cm.eras[era].name] = res.per_era.get(cm.eras[era].name, 0) + n_ok
        if err is not None:
            res.error = err
            break
        i = j
    res.final_state = st
    if cfg.with_ledgers and res.error is None:
        # the ledger replay (db-analyser always does this; opt-in here):
        # full rule application per block, translations at era crossings;
        # a ledger-rule failure reports through MixedResult.error exactly
        # like a consensus-segment failure
        from ..ledger.abstract import LedgerError

        lst = cm.ledger_genesis_state()
        try:
            for blk in blocks:
                lst = cm.hf_ledger.tick_then_apply(lst, blk)
        except LedgerError as e:
            res.error = e
        res.final_ledger_state = lst
    return res
