"""Hard-fork combinator: era composition + era-aware time conversions
(reference: Ouroboros.Consensus.HardFork)."""

from .combinator import (
    Era,
    HardForkBlock,
    HardForkLedger,
    HardForkProtocol,
    HFState,
    TickedHFState,
    decode_block,
)
from .history import (
    Bound,
    EraParams,
    EraSummary,
    PastHorizon,
    Summary,
    summarize,
)

__all__ = [
    "Era", "HardForkBlock", "HardForkLedger", "HardForkProtocol",
    "HFState", "TickedHFState", "decode_block", "Bound", "EraParams",
    "EraSummary", "PastHorizon", "Summary", "summarize",
]
