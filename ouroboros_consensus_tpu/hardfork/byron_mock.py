"""Byron-analog era: PBFT over Ed25519-signed mock blocks.

Reference shape: `ouroboros-consensus-cardano/src/byron/.../Byron/Ledger/
Block.hs` (delegate-signed headers) under `Protocol/PBFT.hs` (signing
window) — with the Byron ledger's tx machinery replaced by opaque tx
bytes, the same strategy the reference's own mock-block library uses for
ThreadNet (src/mock-block/). This is the first era of the mixed-era
composite (hardfork/composite.py), giving BASELINE config 5 its
Byron→Shelley→Babbage shape.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import cached_property
from typing import Sequence

from ..block.abstract import Point
from ..ops.host import ed25519 as host_ed25519
from ..protocol.instances import PBFT_BOUNDARY_VIEW as BOUNDARY_VIEW
from ..protocol.instances import PBftView
from ..utils import cbor


def _b2b(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


@dataclass(frozen=True)
class ByronMockHeader:
    """Header: delegate-signed (cold Ed25519) over the body fields.

    `is_ebb` marks an EPOCH BOUNDARY BLOCK (Block/EBB.hs, Byron/EBBs.hs):
    unsigned, empty, sharing its epoch's first slot and its PREDECESSOR's
    block number — validation treats it as PBftValidateBoundary (no
    signature, no window update, PBFT.hs:326)."""

    block_no: int
    slot: int
    prev_hash: bytes | None
    issuer_vk: bytes  # 32 — genesis delegate key (zeros for an EBB)
    body_hash: bytes  # 32
    sig: bytes  # 64 — Ed25519 over signed_bytes (zeros for an EBB)
    is_ebb: bool = False

    @cached_property
    def signed_bytes(self) -> bytes:
        return cbor.encode(
            [self.block_no, self.slot, self.prev_hash, self.issuer_vk,
             self.body_hash, self.is_ebb]
        )

    @cached_property
    def bytes_(self) -> bytes:
        return cbor.encode(
            [self.block_no, self.slot, self.prev_hash, self.issuer_vk,
             self.body_hash, self.sig, self.is_ebb]
        )

    @cached_property
    def hash_(self) -> bytes:
        return _b2b(self.bytes_)

    @property
    def point(self) -> Point:
        return Point(self.slot, self.hash_)

    def to_view(self):
        """ValidateView: PBftValidateBoundary for EBBs (a sentinel the
        protocol recognizes), PBftValidateRegular otherwise."""
        if self.is_ebb:
            return BOUNDARY_VIEW
        return PBftView(self.issuer_vk, self.signed_bytes, self.sig)

    @classmethod
    def from_bytes(cls, data: bytes) -> "ByronMockHeader":
        bn, slot, prev, vk, bh, sig, ebb = cbor.decode(data)
        return cls(bn, slot, prev, vk, bh, sig, bool(ebb))


def body_hash(txs: Sequence[bytes]) -> bytes:
    return _b2b(cbor.encode(list(txs)))


@dataclass(frozen=True)
class ByronMockBlock:
    header: ByronMockHeader
    txs: tuple[bytes, ...] = ()

    @cached_property
    def bytes_(self) -> bytes:
        return cbor.encode([self.header.bytes_, list(self.txs)])

    @property
    def hash_(self) -> bytes:
        return self.header.hash_

    @property
    def slot(self) -> int:
        return self.header.slot

    @property
    def block_no(self) -> int:
        return self.header.block_no

    @property
    def prev_hash(self) -> bytes | None:
        return self.header.prev_hash

    @property
    def point(self) -> Point:
        return self.header.point

    def check_integrity(self) -> bool:
        return body_hash(self.txs) == self.header.body_hash

    @classmethod
    def from_bytes(cls, data: bytes) -> "ByronMockBlock":
        hdr, txs = cbor.decode(data)
        return cls(ByronMockHeader.from_bytes(hdr), tuple(txs))


def forge_block(
    seed: bytes,
    *,
    slot: int,
    block_no: int,
    prev_hash: bytes | None,
    txs: tuple[bytes, ...] = (),
) -> ByronMockBlock:
    """Forge a delegate block (Byron forging: sign the header body with
    the delegate's Ed25519 key — Byron/Forge.hs shape)."""
    vk = host_ed25519.secret_to_public(seed)
    bh = body_hash(txs)
    unsigned = ByronMockHeader(block_no, slot, prev_hash, vk, bh, b"\x00" * 64)
    sig = host_ed25519.sign(seed, unsigned.signed_bytes)
    return ByronMockBlock(
        ByronMockHeader(block_no, slot, prev_hash, vk, bh, sig), tuple(txs)
    )


def forge_ebb(
    *, slot: int, block_no: int, prev_hash: bytes | None
) -> ByronMockBlock:
    """Forge an epoch boundary block (Byron/EBBs.hs): unsigned, empty;
    `block_no` must equal the PREDECESSOR's (EBBs do not advance the
    block count), `slot` the new epoch's first slot."""
    hdr = ByronMockHeader(
        block_no, slot, prev_hash, b"\x00" * 32, body_hash(()),
        b"\x00" * 64, is_ebb=True,
    )
    return ByronMockBlock(hdr, ())
