"""Hard-fork history: era-aware slot/epoch/wallclock conversions.

Reference: `Ouroboros.Consensus.HardFork.History` — `EraParams` + safe
zones (EraParams.hs:131), `Summary`/`EraEnd` (Summary.hs:178), and the
query DSL with `wallclockToSlot`/`slotToWallclock` (Qry.hs:463,478).

The TPU build keeps the same semantics but drops the typed query DSL:
a `Summary` is a list of era summaries with closed-form per-era affine
conversions; every query is a lookup of the containing era followed by
arithmetic. Queries beyond the summary's horizon raise `PastHorizon`
(the forecast-safety property the reference enforces through the
`Qry` interpreter)."""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction


class PastHorizon(Exception):
    """Query outside the summary's certain range (Qry.hs PastHorizon)."""


@dataclass(frozen=True)
class EraParams:
    """EraParams.hs:131 — static per-era conversion constants."""

    epoch_size: int  # slots per epoch
    slot_length: Fraction  # seconds per slot
    safe_zone: int = 0  # slots after the tip within which no era change


@dataclass(frozen=True)
class Bound:
    """A point where an era begins/ends — all three coordinates
    (Summary.hs Bound)."""

    time: Fraction  # seconds since system start
    slot: int
    epoch: int


@dataclass(frozen=True)
class EraSummary:
    """One era's extent: [start, end) with its params (Summary.hs:151)."""

    start: Bound
    end: Bound | None  # None = unbounded (the final/current era)
    params: EraParams

    def contains_slot(self, slot: int) -> bool:
        if slot < self.start.slot:
            return False
        return self.end is None or slot < self.end.slot

    def contains_time(self, t: Fraction) -> bool:
        if t < self.start.time:
            return False
        return self.end is None or t < self.end.time

    def contains_epoch(self, e: int) -> bool:
        if e < self.start.epoch:
            return False
        return self.end is None or e < self.end.epoch


def mk_bound_from_start(start: Bound, params: EraParams, n_epochs: int) -> Bound:
    """End bound of an era running `n_epochs` epochs from `start`."""
    slots = n_epochs * params.epoch_size
    return Bound(
        time=start.time + slots * params.slot_length,
        slot=start.slot + slots,
        epoch=start.epoch + n_epochs,
    )


@dataclass(frozen=True)
class Summary:
    """The known era structure (Summary.hs:178). Invariants: contiguous
    bounds; only the last era may be open-ended."""

    eras: tuple[EraSummary, ...]

    def __post_init__(self):
        prev_end = None
        for i, e in enumerate(self.eras):
            if prev_end is not None:
                assert e.start == prev_end, "summary gap"
            assert e.end is not None or i == len(self.eras) - 1
            prev_end = e.end

    # -- era lookups -------------------------------------------------------

    def era_of_slot(self, slot: int) -> EraSummary:
        for e in self.eras:
            if e.contains_slot(slot):
                return e
        raise PastHorizon(f"slot {slot}")

    def era_index_of_slot(self, slot: int) -> int:
        for i, e in enumerate(self.eras):
            if e.contains_slot(slot):
                return i
        raise PastHorizon(f"slot {slot}")

    def era_of_epoch(self, epoch: int) -> EraSummary:
        for e in self.eras:
            if e.contains_epoch(epoch):
                return e
        raise PastHorizon(f"epoch {epoch}")

    # -- conversions (Qry.hs:463,478) --------------------------------------

    def wallclock_to_slot(self, t: Fraction) -> tuple[int, Fraction]:
        """(slot containing t, time spent in it)."""
        for e in self.eras:
            if e.contains_time(t):
                dt = t - e.start.time
                n = int(dt / e.params.slot_length)
                spent = dt - n * e.params.slot_length
                return e.start.slot + n, spent
        raise PastHorizon(f"time {t}")

    def slot_to_wallclock(self, slot: int) -> tuple[Fraction, Fraction]:
        """(start time of slot, its length)."""
        e = self.era_of_slot(slot)
        return (
            e.start.time + (slot - e.start.slot) * e.params.slot_length,
            e.params.slot_length,
        )

    def slot_to_epoch(self, slot: int) -> tuple[int, int]:
        """(epoch containing slot, slot's index within it)."""
        e = self.era_of_slot(slot)
        rel = slot - e.start.slot
        return e.start.epoch + rel // e.params.epoch_size, rel % e.params.epoch_size

    def epoch_to_first_slot(self, epoch: int) -> int:
        e = self.era_of_epoch(epoch)
        return e.start.slot + (epoch - e.start.epoch) * e.params.epoch_size

    def epoch_size(self, epoch: int) -> int:
        return self.era_of_epoch(epoch).params.epoch_size


def summarize(
    system_start: Fraction,
    era_params: list[EraParams],
    transition_epochs: list[int | None],
) -> Summary:
    """Build a Summary from per-era params and the epoch at which each
    era ENDS (None for the final, open era) — the shape protocolInfo
    computes from genesis + TriggerHardForkAtEpoch configs."""
    assert len(era_params) == len(transition_epochs)
    eras: list[EraSummary] = []
    start = Bound(Fraction(system_start), 0, 0)
    for params, end_epoch in zip(era_params, transition_epochs):
        if end_epoch is None:
            eras.append(EraSummary(start, None, params))
            break
        n = end_epoch - start.epoch
        assert n >= 0, "era ends before it starts"
        end = mk_bound_from_start(start, params, n)
        eras.append(EraSummary(start, end, params))
        start = end
    return Summary(tuple(eras))
