"""TopLevelConfig: the per-layer static configuration bundle.

Reference: `Ouroboros.Consensus.Config` — `TopLevelConfig`
(Config.hs:38) groups the protocol / ledger / block / codec / storage
configurations that `ProtocolInfo` constructors assemble and every
subsystem picks its slice from; `SecurityParam` (Config/SecurityParam.hs)
rides inside the protocol config.

This framework's subsystems take their slices directly (PraosParams,
MockConfig, chunk sizes...), so the bundle is a convenience record with
an `open_chaindb`-shaped projection — what `mkChainDbArgs` does in the
reference's node assembly (diffusion Node.hs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Any


@dataclass(frozen=True)
class StorageConfig:
    """The ChainDB/ImmutableDB/VolatileDB knobs (cdbsArgs analog)."""

    chunk_size: int = 21600
    snapshot_interval: int = 100
    max_blocks_per_file: int = 1000


@dataclass(frozen=True)
class BlockConfig:
    """Static block-production parameters (BlockConfig analog)."""

    protocol_version: tuple[int, int] = (9, 0)
    max_header_size: int = 1100


@dataclass(frozen=True)
class TopLevelConfig:
    """topLevelConfig{Protocol,Ledger,Block,Storage} (Config.hs:38-57).
    The codec slice has no analog: this framework's CBOR codecs are
    version-independent functions (utils/cbor.py)."""

    protocol: Any  # e.g. protocol.praos.PraosParams
    ledger: Any  # e.g. ledger.mock.MockConfig
    block: BlockConfig = field(default_factory=BlockConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)

    @property
    def security_param(self) -> int:
        """configSecurityParam (Config.hs:74)."""
        return self.protocol.security_param


class HardForkSlotClock:
    """hardForkBlockchainTime (BlockchainTime/WallClock/HardFork.hs:9):
    wallclock ↔ slot conversions that re-query the HFC summary, so
    era-varying slot lengths are honored — unlike the fixed-length
    SlotClock (node/kernel.py) used by single-era tests."""

    def __init__(self, summary, t0: float = 0.0):
        self.summary = summary
        self.t0 = t0

    def slot_of(self, now: float) -> int:
        slot, _offset = self.summary.wallclock_to_slot(
            Fraction(now - self.t0).limit_denominator(10**9)
        )
        return slot

    def start_of(self, slot: int) -> float:
        start, _length = self.summary.slot_to_wallclock(slot)
        return self.t0 + float(start)
