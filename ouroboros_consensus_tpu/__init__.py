"""ouroboros_consensus_tpu — a TPU-native consensus & storage framework.

A brand-new implementation of the capabilities of the Cardano consensus and
storage layer (reference: karknu/ouroboros-consensus, Haskell), designed
TPU-first: the block-validation hot path (Ed25519 / KES / ECVRF signature
verification, Blake2b / SHA-512 hashing) runs as batched JAX/XLA kernels on
columnar header batches, while the control plane (chain selection, storage,
mempool, mini-protocols) is host-side Python with a deterministic simulation
harness for multi-node tests.

Layer map (mirrors reference SURVEY.md section 1):
  ops/          batched crypto kernels + pure-Python host reference impls
  protocol/     ConsensusProtocol interface; Praos / BFT / PBFT instances
  ledger/       ledger interface, extended ledger state, mock ledger
  block/        block/header model, CBOR codecs, SoA batch staging
  storage/      ImmutableDB / VolatileDB / LedgerDB / ChainDB + ChainSel
  mempool/      transaction pool consistent with the ledger
  miniprotocol/ ChainSync / BlockFetch client+server logic over channels
  node/         node kernel: forging loop, clocks, assembly
  hardfork/     era composition (hard-fork combinator) + time conversions
  parallel/     device mesh sharding, nonce scan, multi-chip fan-out
  utils/        CBOR, tracers, registry, deterministic sim runtime
  tools/        db_synthesizer / db_analyser / db_truncater / immdb_server
"""

__version__ = "0.1.0"
