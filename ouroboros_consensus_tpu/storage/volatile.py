"""VolatileDB: unordered store of recent blocks, GC'd by slot.

Reference: `Ouroboros.Consensus.Storage.VolatileDB` (7 files, ~1.7k LoC) —
blocks append to `blocks-N.dat` files (Impl.hs:83-96) capped at
`maxBlocksPerFile` (Impl.hs:208); all lookup state (block info by hash,
successor map by prev-hash) is IN MEMORY and rebuilt by reparsing the
files on open; garbage collection removes whole files whose blocks are all
older than the GC slot.

On-disk record framing (per block):  u32 length ‖ u32 crc32 ‖ bytes.
A torn/corrupt record truncates its file at that point on open (the
reference's ParseError truncation).
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass
from typing import Callable, Iterable

from ..block.abstract import Point
from ..utils.fs import REAL_FS


@dataclass(frozen=True)
class BlockInfo:
    """What the in-memory index holds per block (VolatileDB API's
    BlockInfo): enough for ChainSel's path finding without reads."""

    hash_: bytes
    prev_hash: bytes | None
    slot: int
    block_no: int
    file_no: int
    offset: int  # of the payload inside the file
    size: int


class VolatileDB:
    def __init__(self, path: str, max_blocks_per_file: int = 1000, fs=None,
                 decode_block=None):
        self.path = path
        self.max_blocks_per_file = max_blocks_per_file
        self.fs = fs if fs is not None else REAL_FS
        # block codec seam (the reference is polymorphic in blk):
        # default = the Praos block, HFC nets pass era-tagged decoders
        if decode_block is None:
            from ..block.praos_block import Block

            decode_block = Block.from_bytes
        self.decode_block = decode_block
        self.fs.makedirs(path)
        self._info: dict[bytes, BlockInfo] = {}
        self._successors: dict[bytes | None, set[bytes]] = {}
        self._file_counts: dict[int, int] = {}
        self._reopen()

    # -- open / reparse ------------------------------------------------------

    def _files(self) -> list[int]:
        ns = []
        for f in self.fs.listdir(self.path):
            if f.startswith("blocks-") and f.endswith(".dat"):
                ns.append(int(f[len("blocks-") : -len(".dat")]))
        return sorted(ns)

    def _reopen(self) -> None:
        for n in self._files():
            p = self._file_path(n)
            data = self.fs.read_bytes(p)
            off = 0
            good_end = 0
            while off + 8 <= len(data):
                size, crc = struct.unpack_from("<II", data, off)
                payload = data[off + 8 : off + 8 + size]
                if len(payload) != size or zlib.crc32(payload) != crc:
                    break
                try:
                    blk = self.decode_block(payload)
                except Exception:
                    break
                self._index(blk, n, off + 8, size)
                off += 8 + size
                good_end = off
            if good_end != len(data):  # truncate torn tail
                self.fs.truncate(p, good_end)
        ns = self._files()
        self._write_file_no = ns[-1] if ns else 0

    def _file_path(self, n: int) -> str:
        return os.path.join(self.path, f"blocks-{n:04d}.dat")

    def _index(self, blk, file_no: int, offset: int, size: int) -> None:
        info = BlockInfo(
            blk.hash_, blk.prev_hash, blk.slot, blk.block_no, file_no, offset, size
        )
        self._info[blk.hash_] = info
        self._successors.setdefault(blk.prev_hash, set()).add(blk.hash_)
        self._file_counts[file_no] = self._file_counts.get(file_no, 0) + 1

    # -- API (Storage/VolatileDB/API.hs) -------------------------------------

    def put_block(self, blk) -> None:
        if blk.hash_ in self._info:
            return  # duplicates are no-ops (putBlock idempotence)
        n = self._write_file_no
        if self._file_counts.get(n, 0) >= self.max_blocks_per_file:
            n = self._write_file_no = n + 1
        raw = blk.bytes_
        p = self._file_path(n)
        offset = (self.fs.getsize(p) if self.fs.exists(p) else 0) + 8
        self.fs.append(p, struct.pack("<II", len(raw), zlib.crc32(raw)) + raw)
        self._index(blk, n, offset, len(raw))

    def get_block_info(self, hash_: bytes) -> BlockInfo | None:
        return self._info.get(hash_)

    def member(self, hash_: bytes) -> bool:
        return hash_ in self._info

    def get_block_bytes(self, hash_: bytes) -> bytes | None:
        info = self._info.get(hash_)
        if info is None:
            return None
        return self.fs.read_at(self._file_path(info.file_no), info.offset, info.size)

    def filter_by_predecessor(self, prev_hash: bytes | None) -> set[bytes]:
        """The successor map ChainSel's path finding walks (Paths.hs)."""
        return set(self._successors.get(prev_hash, ()))

    def garbage_collect(self, slot: int) -> None:
        """Remove whole files whose blocks all have slot < `slot`
        (VolatileDB GC granularity is the file, Impl.hs garbageCollect)."""
        by_file: dict[int, list[BlockInfo]] = {}
        for info in self._info.values():
            by_file.setdefault(info.file_no, []).append(info)
        for n, infos in by_file.items():
            if n == self._write_file_no:
                continue  # never GC the write file
            if all(i.slot < slot for i in infos):
                self.fs.remove(self._file_path(n))
                for i in infos:
                    del self._info[i.hash_]
                    succ = self._successors.get(i.prev_hash)
                    if succ is not None:
                        succ.discard(i.hash_)
                        if not succ:
                            del self._successors[i.prev_hash]
                self._file_counts.pop(n, None)

    def all_hashes(self) -> Iterable[bytes]:
        return self._info.keys()
