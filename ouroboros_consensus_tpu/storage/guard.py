"""The store crash protocol: DB lock, chain-magic marker, clean-
shutdown marker.

Reference: `Node/{DbLock,DbMarker,Recovery}.hs` via `stdWithCheckedDB`
(Node.hs:546) —

  * **DB lock** (DbLock.hs): one process per DB directory. A flock on
    the real filesystem (released by the kernel when the holder dies,
    so a STALE lock file never wedges a restart), the MockFS advisory
    registry in memory (cleared by `MockFS.crash`, same semantics). A
    live second opener refuses LOUDLY with `DbLocked`.
  * **DB marker** (DbMarker.hs): a magic file binding the directory to
    a chain/network id, so a mainnet node (or analyser) can't open a
    testnet DB. Created on first open, verified after; a mismatch
    refuses loudly with `DbMarkerMismatch`.
  * **Clean-shutdown marker** (Recovery.hs:24-59): present only while
    no writer runs. A writer REMOVES it while running and writes it
    back on orderly shutdown; missing at open (after a first run) ⇒
    the last run crashed ⇒ the validation policy escalates to
    all-chunks with on-disk repair — forced revalidation after crash.

These primitives were born in `node/run.py`; they live here so the
tools plane (`db_analyser.revalidate`, `db_synthesizer`, the bench
children) speaks the SAME protocol as node startup — `node/run.py`
re-exports them. `StoreGuard` is the bundled open protocol the tools
use: lock → marker → dirty check → (writer mode) clear marker, with
`close(clean=...)` writing the marker back through the chaos
``marker`` seam (`partial-rename@marker` models a crash between the
tmp write and the rename).
"""

from __future__ import annotations

import os

from ..utils.fs import REAL_FS

DB_LOCK = "lock"
DB_MARKER = "protocolMagicId"
CLEAN_SHUTDOWN = "clean"  # reference: absence of the marker = crashed
DEFAULT_MAGIC = 764824073  # mainnet protocolMagicId (node/run default)


class DbLocked(Exception):
    """Another process holds the DB (DbLock.hs DbLocked)."""


class DbMarkerMismatch(Exception):
    """DB belongs to a different chain/network (DbMarker.hs)."""


class DbLockFile:
    """Single-process guard (DbLock.hs, 2s timeout): flock on the real
    filesystem; on a mock FS, the MockFS advisory-lock registry — which
    MockFS.crash clears, mirroring flock's release-on-process-death."""

    def __init__(self, db_path: str, fs=None):
        self.path = os.path.join(db_path, DB_LOCK)
        self.fs = fs  # None = real FS (flock)
        self._fd: int | None = None
        self._held = False

    def acquire(self) -> None:
        if self.fs is not None:
            if self.path in self.fs.advisory_locks:
                raise DbLocked(self.path)
            self.fs.advisory_locks.add(self.path)
            self._held = True
            return
        import fcntl

        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            raise DbLocked(self.path) from e
        self._fd = fd
        self._held = True

    def release(self) -> None:
        if not self._held:
            return  # never release a lock another instance holds
        self._held = False
        if self.fs is not None:
            self.fs.advisory_locks.discard(self.path)
            return
        if self._fd is not None:
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def check_db_marker(db_path: str, network_magic: int, fs=None) -> None:
    """checkDbMarker (DbMarker.hs): create on first open, verify after."""
    fs = fs if fs is not None else REAL_FS
    p = os.path.join(db_path, DB_MARKER)
    if fs.exists(p):
        found = read_db_marker(db_path, fs=fs)
        if found != network_magic:
            raise DbMarkerMismatch(
                f"DB is for magic {found}, node runs {network_magic}"
            )
    else:
        fs.makedirs(db_path)
        # durable: the marker must survive a crash (write_atomic fsyncs)
        fs.write_atomic(p, str(network_magic).encode())


def read_db_marker(db_path: str, fs=None) -> int | None:
    """The magic the marker binds this DB to; None = no marker yet. A
    marker that EXISTS but does not parse is not 'missing' — treating
    it so would let a writer re-stamp (or a reader silently accept) a
    store whose chain identity is unknown; refuse loudly instead."""
    fs = fs if fs is not None else REAL_FS
    p = os.path.join(db_path, DB_MARKER)
    if not fs.exists(p):
        return None
    raw = fs.read_bytes(p)
    try:
        return int(raw.decode().strip())
    except ValueError:
        raise DbMarkerMismatch(
            f"unparseable DB marker at {p}: {raw[:64]!r}"
        ) from None


def was_clean_shutdown(db_path: str, fs=None) -> bool:
    """Recovery.hs:24: the clean marker is REMOVED while running and
    written back on orderly shutdown; missing at start (after a first
    run) ⇒ crash ⇒ revalidate everything."""
    fs = fs if fs is not None else REAL_FS
    return fs.exists(os.path.join(db_path, CLEAN_SHUTDOWN))


def clear_clean_marker(db_path: str, fs=None) -> None:
    """A writer is running now: a crash must leave no clean marker."""
    fs = fs if fs is not None else REAL_FS
    p = os.path.join(db_path, CLEAN_SHUTDOWN)
    if fs.exists(p):
        fs.remove(p)


def write_clean_marker(db_path: str, fs=None) -> None:
    """Orderly shutdown: write the marker back. The write goes tmp →
    (chaos ``marker`` seam) → atomic rename, so the injected
    ``partial-rename@marker`` fault models the real crash shape: a
    durable tmp file, no final marker — the next open is dirty and a
    stray ``.tmp`` must be tolerated."""
    from ..testing import chaos

    fs = fs if fs is not None else REAL_FS
    p = os.path.join(db_path, CLEAN_SHUTDOWN)
    tmp = p + ".tmp"
    fs.write_bytes(tmp, b"clean\n")
    fs.fsync(tmp)
    chaos.fire("marker", marker=CLEAN_SHUTDOWN)
    fs.replace(tmp, p)


class StoreGuard:
    """The tools-plane open protocol bundled: lock → marker → dirty
    check. ``writer=True`` additionally clears the clean marker for
    the duration (a crash leaves the store dirty) and `close(clean=
    True)` writes it back. ``network_magic=None`` accepts whatever
    marker exists (creating the default on a virgin store) — the
    strict check is for callers that know their chain."""

    def __init__(self, db_path: str, network_magic: int | None = None,
                 fs=None, writer: bool = True):
        self.db_path = db_path
        self.network_magic = network_magic
        self.fs = fs
        self.writer = writer
        self.lock = DbLockFile(db_path, fs=fs)
        self.first_run = False
        self.opened_dirty = False
        self._open = False

    def open(self) -> "StoreGuard":
        vfs = self.fs if self.fs is not None else REAL_FS
        self.lock.acquire()
        try:
            self.first_run = not vfs.exists(
                os.path.join(self.db_path, "immutable")
            )
            self._check_or_create_marker()
            self.opened_dirty = (
                not self.first_run
                and not was_clean_shutdown(self.db_path, fs=self.fs)
            )
            if self.writer:
                clear_clean_marker(self.db_path, fs=self.fs)
            self._open = True
            return self
        except BaseException:
            self.lock.release()
            raise

    def _check_or_create_marker(self) -> None:
        """Verify the chain magic; CREATE a missing marker only in
        writer mode, and only with a magic the caller KNOWS (explicit
        `network_magic`) or on a virgin store this writer is about to
        forge. A magic-agnostic open of an existing marker-less store
        — a read-only analysis, OR a dirty-open escalation promoting
        it to writer mid-open — must never stamp the default: a
        testnet DB analysed once would be branded mainnet forever."""
        found = read_db_marker(self.db_path, fs=self.fs)
        want = self.network_magic
        if found is not None:
            if want is not None and found != want:
                raise DbMarkerMismatch(
                    f"DB is for magic {found}, node runs {want}"
                )
        elif self.writer and (want is not None or self.first_run):
            check_db_marker(
                self.db_path, want if want is not None else DEFAULT_MAGIC,
                fs=self.fs,
            )

    def promote_writer(self) -> None:
        """A reader discovered it must WRITE (dirty-open escalation
        forcing repair write-back; a synthesize that passed its
        refusal checks): adopt the writer half of the protocol
        mid-open — stamp a missing marker, clear the clean marker so
        a crash from here on leaves the store dirty."""
        if not self.writer:
            self.writer = True
            self._check_or_create_marker()
            clear_clean_marker(self.db_path, fs=self.fs)

    def close(self, clean: bool = True) -> None:
        """Release the protocol. ``clean=True`` (the orderly path —
        including a replay that ENDED at a validation error: the store
        itself is consistent) writes the marker back; ``clean=False``
        leaves the store dirty so the next open revalidates."""
        if not self._open:
            return
        self._open = False
        try:
            if self.writer and clean:
                write_clean_marker(self.db_path, fs=self.fs)
        finally:
            self.lock.release()

    def __enter__(self):
        return self.open()

    def __exit__(self, exc_type, exc, tb):
        # an exception unwinding through the guard is the crash shape:
        # writer mode leaves the store DIRTY (no clean marker), exactly
        # what forces the next open to deep-revalidate
        self.close(clean=exc_type is None)
        return False
