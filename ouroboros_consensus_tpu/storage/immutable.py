"""ImmutableDB: append-only chunked store of the immutable chain.

Reference: `Ouroboros.Consensus.Storage.ImmutableDB` (15 files, ~4.9k LoC)
— `NNNNN.chunk` files of concatenated block bytes plus two indices: a
primary index of fixed-width offsets per relative slot
(Impl/Index/Primary.hs:96) and a secondary index of per-block entries with
CRCs (Impl/Index/Secondary.hs). This implementation keeps the same
on-disk shape with one combined index file per chunk:

    NNNNN.chunk      block bytes, concatenated
    NNNNN.index      CBOR [[slot, block_no, hash, offset, size, crc32], …]

Startup validation (Impl/Validation.hs:67) reparses the last chunk (or all
chunks under `validate_all`), checks CRCs and hashes, optionally runs the
`check_integrity` hook (body hash + KES — batched on device by the
caller), and TRUNCATES the corrupted tail rather than failing. Every
on-disk repair the validation takes — truncated tails, rebuilt indices,
dropped chunks, swept orphan indices — QUARANTINES the snipped bytes
under ``quarantine/`` (never deletes) and is banked as a first-class
repair action (storage/repair.py: warmup forensics +
``oct_repair_total{action=}``). ``repair=False`` opens read-only: the
same scan computes every action in memory (``applied=False`` rows, the
db-truncater ``--dry-run`` report) and the disk is never touched.

Iterators stream blocks in slot order across chunk boundaries
(Impl/Iterator.hs). Appends go through an in-memory tail buffer flushed
per block — the OS page cache does the batching; `fsync` on chunk close.
"""

from __future__ import annotations

import os
import zlib
from dataclasses import dataclass
from typing import Callable, Iterator

from ..block.abstract import Point
from ..testing import chaos
from ..utils import cbor
from ..utils.fs import REAL_FS
from . import repair as repair_mod


class ImmutableDBError(Exception):
    pass


class MissingBlock(ImmutableDBError):
    pass


@dataclass(frozen=True)
class IndexEntry:
    slot: int
    block_no: int
    hash_: bytes
    offset: int
    size: int
    crc32: int

    def to_cbor_obj(self):
        return [self.slot, self.block_no, self.hash_, self.offset, self.size, self.crc32]

    @classmethod
    def from_cbor_obj(cls, o):
        return cls(o[0], o[1], bytes(o[2]), o[3], o[4], o[5])


def _chunk_name(n: int) -> str:
    return f"{n:05d}.chunk"


def _index_name(n: int) -> str:
    return f"{n:05d}.index"


def _cols_name(n: int) -> str:
    """Chunk n's columnar sidecar (storage/sidecar.py) — lives beside
    the chunk + index it is derived from."""
    return f"{n:05d}.cols"


class ImmutableDB:
    """Append-only block store; blocks arrive in strictly increasing slot
    order (the chain ≥ k deep is immutable — ChainDB background copy).
    """

    def __init__(
        self,
        path: str,
        chunk_size: int = 21600,  # slots per chunk (reference: epoch-ish)
        check_integrity: Callable[[bytes], bool] | None = None,
        validate_all: bool = False,
        fs=None,  # HasFS seam (utils/fs.py); None = the real filesystem
        decode_block=None,  # block codec for index rebuilds; None = Praos
        check_integrity_batch=None,  # chunk-wide twin of check_integrity:
        # (data, entries) -> count of good leading entries | None
        stream_deep: bool = False,  # validate-all checks owed at READ
        # time: streaming consumers run deep_check_loaded per chunk as
        # they read (single-pass validation; db-analyser "stream" mode)
        repair: bool = True,  # may validation MUTATE the disk? False =
        # read-only scan: truncations computed in memory only, every
        # would-be action recorded with applied=False (--dry-run)
        quarantine_dir: str | None = None,  # where snipped bytes go
        # (default <path>/quarantine); never deleted, always moved
        stream_repair: bool = False,  # stream-mode consumers may call
        # repair_to() to write back the truncation their deep read
        # computed (db_analyser.revalidate --repair)
    ):
        self.path = path
        self.chunk_size = chunk_size
        self.stream_deep = stream_deep
        self.stream_repair = stream_repair
        self._decode_block = decode_block
        self._check_integrity_batch = check_integrity_batch
        self.fs = fs if fs is not None else REAL_FS
        if repair:
            # only a store that may WRITE creates its directory; a
            # read-only scan (--dry-run, stream analysis) of a virgin
            # or typo'd path must leave no side effect — a dir created
            # here would make the NEXT open see a marker-less non-first
            # run and misclassify the untouched store as dirty
            self.fs.makedirs(path)
        self._repair = repair
        self._quarantine = repair_mod.Quarantine(
            path, self.fs, quarantine_dir
        )
        self.repairs: list[dict] = []  # repair rows of THIS open
        self._entries: dict[int, list[IndexEntry]] = {}  # chunk -> entries
        self._chunks: list[int] = []
        self._truncated: dict[int, bool] = {}
        self._validate(check_integrity, validate_all)

    def prepare_write(self) -> None:
        """A read-only probe being adopted as the writer store (the
        synthesizer's fresh-forge path, after its refusal checks
        passed): create the directory the read-only open deliberately
        left uncreated, and allow mutations from here on."""
        self.fs.makedirs(self.path)
        self._repair = True

    # -- startup validation --------------------------------------------------

    def _chunk_numbers(self) -> list[int]:
        ns = []
        if not self.fs.isdir(self.path):  # read-only open, virgin path
            return ns
        for f in self.fs.listdir(self.path):
            if f.endswith(".chunk"):
                ns.append(int(f.split(".")[0]))
        return sorted(ns)

    def _validate(self, check_integrity, validate_all: bool) -> None:
        """Load indices; reparse + CRC-check the last chunk (or all); on
        mismatch truncate the tail from the first bad block onward."""
        chunks = self._chunk_numbers()
        for i, n in enumerate(chunks):
            deep = validate_all or i == len(chunks) - 1
            entries = self._load_chunk(n, deep, check_integrity)
            if entries is None:  # wholly corrupt chunk: drop it and the rest
                for m in chunks[i:]:
                    self._repair_drop_chunk(
                        m,
                        detail=("wholly corrupt chunk" if m == n
                                else "stranded past a dropped chunk"),
                    )
                break
            self._entries[n] = entries
            self._chunks.append(n)
            if self._truncated.get(n):
                # truncated inside this chunk (deep check OR a reparse of
                # a stale/missing index): later chunks would leave a gap
                # in the chain — drop them (truncate-corrupted-tail)
                for m in chunks[i + 1 :]:
                    self._repair_drop_chunk(
                        m, detail="stranded past a truncated chunk"
                    )
                break
        # sweep ORPHANED index files: an index written atomically (hence
        # durable) whose chunk file's creation was never synced survives a
        # crash alone; a later append to that chunk would extend the stale
        # index and duplicate entries (ImmutableModel finding)
        live = set(self._chunks)
        names = self.fs.listdir(self.path) if self.fs.isdir(self.path) else ()
        for f in names:
            if f.endswith(".index") and int(f.split(".")[0]) not in live:
                q = 0
                if self._repair:
                    q = self._quarantine_file(f)  # moved, not copied
                self._note_repair(
                    "sweep-orphan-index", int(f.split(".")[0]), qbytes=q,
                    detail="index file without a chunk",
                )
            elif f.endswith(".cols.tmp") or (
                f.endswith(".cols") and int(f.split(".")[0]) not in live
            ):
                # a sidecar tmp is NEVER live (the rename it awaited
                # died — a crash mid-build); a final-name sidecar is
                # orphaned when its chunk is gone. Both are derived
                # data with no referent — quarantined like any orphan,
                # never trusted, never deleted (storage/sidecar.py
                # trust contract)
                q = 0
                if self._repair:
                    q = self._quarantine_file(f)
                self._note_repair(
                    "sweep-orphan-sidecar", int(f.split(".")[0]), qbytes=q,
                    detail="sidecar without a chunk"
                    if f.endswith(".cols")
                    else "sidecar tmp stranded by a crash mid-build",
                )

    # -- the repair plane ----------------------------------------------------

    def _quarantine_file(self, name: str) -> int:
        """MOVE a live file into quarantine — atomic rename, no bytes
        through memory (a production chunk is hundreds of MB). A move
        that cannot happen refuses (`QuarantineError`) BEFORE anything
        is destroyed: a drop that cannot bank its bytes must not run."""
        return self._quarantine.store_file(
            name, os.path.join(self.path, name)
        )

    def _note_repair(self, action: str, chunk: int, kept: int = 0,
                     dropped: int = 0, qbytes: int = 0,
                     detail: str = "") -> None:
        """Bank one validation repair (storage/repair.note_repair:
        warmup forensics + RepairEvent → oct_repair_total) and keep the
        row on this open's `repairs` report. applied reflects whether
        the disk actually changed (read-only scans compute only)."""
        self.repairs.append(repair_mod.note_repair(
            action, chunk=chunk, kept=kept, dropped=dropped,
            bytes_quarantined=qbytes, applied=self._repair, detail=detail,
        ))

    def _repair_truncate(self, n: int, data: bytes,
                         entries: list[IndexEntry], dropped: int = 0,
                         detail: str = "") -> None:
        """Cut chunk n's corrupted on-disk tail to `entries`:
        quarantine the snipped bytes, rewrite chunk + index — or,
        read-only, record the would-be action."""
        end = entries[-1].offset + entries[-1].size if entries else 0
        snip = max(0, len(data) - end)
        q = snip
        if self._repair:
            q = self._quarantine.store(_chunk_name(n) + ".tail", data[end:])
            self._rewrite_chunk(n, data, entries)
        self._note_repair("truncate-chunk", n, kept=len(entries),
                          dropped=dropped, qbytes=q, detail=detail)

    def _repair_drop_chunk(self, n: int, detail: str = "") -> None:
        """Remove chunk n's files (quarantining both) — a wholly
        corrupt chunk, or one stranded past a truncation gap."""
        dropped = len(self._entries.get(n, ()))
        if n not in self._entries:
            # dropped before its entries were ever loaded (_validate
            # breaks at the first bad chunk): best-effort count from
            # the on-disk index so the repair row reports the real
            # data loss instead of 0 (unreadable index -> 0, honest)
            idx = self._load_index(
                os.path.join(self.path, _index_name(n))
            )
            dropped = len(idx) if idx else 0
        q = 0
        if self._repair:
            for name in (_chunk_name(n), _index_name(n), _cols_name(n)):
                if self.fs.exists(os.path.join(self.path, name)):
                    q += self._quarantine_file(name)  # moved, not copied
        self._note_repair("drop-chunk", n, kept=0, dropped=dropped,
                          qbytes=q, detail=detail)

    def repair_to(self, n: int, good: int,
                  detail: str = "stream deep-validation write-back",
                  data: bytes | None = None) -> None:
        """Stream-mode write-back (db_analyser --repair): truncate
        chunk `n` on disk at entry count `good` — the truncation point
        the deep READ computed — and drop every chunk past it, exactly
        the repair the deep open would have taken. Quarantine + events
        like any other repair; in-memory state mirrors the disk so
        subsequent queries see the repaired store. Pass `data` when the
        chunk bytes are already in hand (the stream reader just loaded
        them) — re-reading a production chunk is hundreds of MB of I/O
        on the exact path where the disk is already suspect."""
        entries = self._entries.get(n, [])
        if data is None:
            try:
                data = self.fs.read_bytes(
                    os.path.join(self.path, _chunk_name(n))
                )
            except OSError:
                data = b""
        kept = entries[:good]
        self._truncated[n] = True
        self._repair_truncate(n, data, kept,
                              dropped=len(entries) - len(kept),
                              detail=detail)
        self._entries[n] = kept
        for m in [m for m in self._chunks if m > n]:
            self._repair_drop_chunk(
                m, detail="stranded past stream truncation"
            )
            self._entries.pop(m, None)
            self._chunks.remove(m)

    def _load_chunk(self, n: int, deep: bool, check_integrity):
        ipath = os.path.join(self.path, _index_name(n))
        cpath = os.path.join(self.path, _chunk_name(n))
        entries = self._load_index(ipath)
        if entries is None:
            # index missing/corrupt (e.g. crash before flush): rebuild it
            # from the chunk data — blocks are self-delimiting CBOR
            entries = self._reparse_chunk(
                n, check_integrity, why="index missing or corrupt"
            )
            return entries
        # deferred index writes mean the on-disk index can LAG the chunk
        # data after a crash: reparse any bytes past the indexed end
        end = entries[-1].offset + entries[-1].size if entries else 0
        try:
            fsize = self.fs.getsize(cpath)
        except OSError:
            return None
        if fsize > end:
            entries = self._reparse_chunk(
                n, check_integrity,
                why=f"index lags chunk data ({fsize} > {end})",
            )
            return entries
        if deep:
            # reparse against the index, truncating at the first corruption
            try:
                data = self.fs.read_bytes(cpath)
            except OSError:
                return None
            n_indexed = len(entries)
            first_bad = self._deep_check_fast(data, entries, check_integrity)
            if first_bad is not None:
                if first_bad < len(entries):
                    self._truncated[n] = True
                entries = entries[:first_bad]
            else:
                # no native library (or a custom per-block hook without a
                # batched twin): the per-blob reference loop
                good = []
                for e in entries:
                    blob = data[e.offset : e.offset + e.size]
                    if len(blob) != e.size or zlib.crc32(blob) != e.crc32:
                        self._truncated[n] = True
                        break
                    if check_integrity is not None and not check_integrity(blob):
                        self._truncated[n] = True
                        break
                    good.append(e)
                entries = good
            if self._truncated.get(n):
                self._repair_truncate(
                    n, data, entries, dropped=n_indexed - len(entries),
                    detail="deep validation (CRC + integrity) found a "
                           "corrupt tail",
                )
        return entries

    def deep_check_loaded(
        self, data, entries, check_integrity=None, check_integrity_batch=None
    ) -> int:
        """validate-all check of one LOADED chunk without disk mutation:
        count of good leading entries (CRC + integrity, per-blob order).
        Streaming consumers (db-analyser single-pass validation) call
        this per chunk as they read, folding the deep-validation walk
        into the replay's own read — same checks as open-time
        validate_all, one disk pass instead of two."""
        fast = self._deep_check_fast(
            data, entries, check_integrity, check_integrity_batch
        )
        if fast is not None:
            return fast
        good = 0
        for e in entries:
            blob = data[e.offset : e.offset + e.size]
            if len(blob) != e.size or zlib.crc32(blob) != e.crc32:
                break
            if check_integrity is not None and not check_integrity(blob):
                break
            good += 1
        return good

    def _deep_check_fast(self, data, entries, check_integrity,
                         batch_hook=None):
        """Vectorized deep validation: ONE native CRC walk over every
        indexed span, then the chunk-wide integrity hook (if any). The
        per-blob Python loop costs ~25 us/block of interpreter overhead
        plus ~80 us/block for the decode-based integrity hook — the
        startup-validation bottleneck on large chains (VERDICT r4 item
        3 profiling). Returns the count of good leading entries, or
        None when the fast path does not apply (caller falls back)."""
        if not entries:
            return None
        if batch_hook is None:
            batch_hook = self._check_integrity_batch
        if check_integrity is not None and batch_hook is None:
            return None  # custom hook, no batched twin
        from .. import native_loader

        rc = native_loader.crc32_first_bad(
            data,
            [e.offset for e in entries],
            [e.size for e in entries],
            [e.crc32 for e in entries],
        )
        if rc is None:
            return None  # no native library
        good = len(entries) if rc < 0 else rc
        if check_integrity is None or good == 0:
            return good
        # the integrity hook must still vet every entry BEFORE the first
        # CRC-bad one: a written-corrupt block (consistent CRC, wrong
        # body hash) earlier in the chunk truncates earlier — order
        # matches the per-blob reference loop
        fb = batch_hook(data, entries[:good])
        if fb is None:
            return None  # hook unavailable -> slow loop
        return min(good, fb)

    def _reparse_chunk(self, n: int, check_integrity, why: str = ""):
        """Walk self-delimiting CBOR blocks in the chunk file, rebuilding
        index entries; truncate at the first unparseable/bad block.

        Uses the native scanner (native/headerscan.cpp) when available,
        no integrity predicate is requested and the block codec is the
        default Praos layout — the pure-Python CBOR walk is the
        startup-validation bottleneck on large DBs."""
        if self._decode_block is None:
            from ..block.praos_block import Block

            decode = Block.from_bytes
        else:
            decode = self._decode_block

        cpath = os.path.join(self.path, _chunk_name(n))
        try:
            data = self.fs.read_bytes(cpath)
        except OSError:
            return None

        if check_integrity is None and self._decode_block is None:
            fast = self._reparse_chunk_native(n, data)
            if fast is not None:
                return self._finish_reparse(n, data, fast, why)

        entries: list[IndexEntry] = []
        off = 0
        while off < len(data):
            try:
                _, end = cbor.decode_prefix(data, off)
                blob = data[off:end]
                blk = decode(blob)
            except Exception:
                self._truncated[n] = True
                break
            if check_integrity is not None and not check_integrity(blob):
                self._truncated[n] = True
                break
            entries.append(
                IndexEntry(
                    blk.slot, blk.block_no, blk.hash_, off, len(blob), zlib.crc32(blob)
                )
            )
            off = end
        return self._finish_reparse(n, data, entries, why)

    def _finish_reparse(self, n: int, data: bytes,
                        entries: list[IndexEntry], why: str):
        """Bank the rebuild and write it back (repair permitting): the
        index is reconstructed from chunk bytes; a torn chunk tail
        found on the way is truncated + quarantined too."""
        self._note_repair("rebuild-index", n, kept=len(entries),
                          detail=why)
        if self._truncated.get(n):
            self._repair_truncate(
                n, data, entries,
                detail=f"unparseable/bad chunk tail ({why})" if why
                       else "unparseable/bad chunk tail",
            )
        elif self._repair:
            self._write_index(n, entries)
        return entries

    def _reparse_chunk_native(self, n: int, data: bytes) -> list[IndexEntry] | None:
        """Native-scanner reparse (no integrity predicate): columnar
        header extraction + hashlib blake2b for the header hashes.
        Returns None when the native library is unavailable or the
        chunk's shape defeats the fast path (falls back to Python)."""
        import hashlib

        from .. import native_loader

        scan = native_loader.scan_items(data)
        if scan is None:
            return None
        offsets, sizes, end = scan
        try:
            cols = (
                native_loader.extract_headers(data, offsets)
                if len(offsets)
                else None
            )
        except ValueError:
            return None  # parseable CBOR but not our block layout
        entries: list[IndexEntry] = []
        for i in range(len(offsets)):
            off, sz = int(offsets[i]), int(sizes[i])
            # header bytes span: after the block's array(2) head (1 byte),
            # through the end of the kes_sig item
            hdr = data[off + 1 : int(cols.header_end[i])]
            h = hashlib.blake2b(hdr, digest_size=32).digest()
            entries.append(
                IndexEntry(
                    int(cols.slot[i]), int(cols.block_no[i]), h, off, sz,
                    zlib.crc32(data[off : off + sz]),
                )
            )
        if end < len(data):
            self._truncated[n] = True  # _finish_reparse writes back
        return entries

    def _rewrite_chunk(self, n: int, data: bytes, entries: list[IndexEntry]):
        # the chunk bytes change, so any sidecar's seal is now a lie:
        # quarantine it BEFORE the rewrite (never trusted past its
        # seal, never deleted) — the next writer replay backfills
        self._invalidate_sidecar(n)
        end = entries[-1].offset + entries[-1].size if entries else 0
        self.fs.write_bytes(os.path.join(self.path, _chunk_name(n)), data[:end])
        self._write_index(n, entries)

    def _invalidate_sidecar(self, n: int) -> int:
        """Move chunk n's sidecar (if any) into quarantine — every
        path that mutates chunk bytes calls this first, so a stale
        seal can never linger beside the rewritten chunk."""
        if self.fs.exists(os.path.join(self.path, _cols_name(n))):
            return self._quarantine_file(_cols_name(n))
        return 0

    def _remove_chunk(self, n: int):
        for name in (_chunk_name(n), _index_name(n), _cols_name(n)):
            self.fs.remove(os.path.join(self.path, name))

    def _load_index(self, ipath: str) -> list[IndexEntry] | None:
        """Index file = concatenated CBOR entry arrays (append-only, like
        the reference's secondary index). A torn final entry (crash
        mid-append) just ends the list — the fsize-lag check reparses."""
        try:
            data = self.fs.read_bytes(ipath)
        except OSError:
            return None
        fast = self._load_index_native(data)
        if fast is not None:
            return fast
        entries: list[IndexEntry] = []
        off = 0
        end = 0
        while off < len(data):
            try:
                obj, off = cbor.decode_prefix(data, off)
                e = IndexEntry.from_cbor_obj(obj)
                # sanity: offsets must tile the chunk contiguously with
                # plausible sizes — a corrupt entry with a huge
                # offset/size must surface as "index corrupt -> reparse"
                # (the reference truncates gracefully), not as an int64
                # overflow crash in the vectorized deep check
                bad = (
                    e.offset != end
                    or e.size <= 0
                    or e.size > (1 << 40)
                    or not isinstance(e.crc32, int)
                )
            except Exception:
                break
            if bad:
                break
            end = e.offset + e.size
            entries.append(e)
        return entries

    def _load_index_native(self, data: bytes) -> list[IndexEntry] | None:
        """Columnar native index parse + vectorized sanity checks (the
        open-time bottleneck at the 1M-header scale: ~9 us/entry of
        Python CBOR decode vs ~20 ns here). None -> Python loop."""
        from .. import native_loader

        cols = native_loader.parse_index(data)
        if cols is None:
            return None
        slots, block_nos, hashes, offsets, sizes, crcs = cols
        n = len(slots)
        if n == 0:
            return []
        import numpy as np

        # same contiguous-tiling sanity as the Python loop: offsets must
        # tile from 0 with plausible sizes; keep the good prefix only
        starts = np.concatenate(([0], (offsets + sizes)[:-1]))
        good = (offsets == starts) & (sizes > 0) & (sizes <= (1 << 40))
        bad = np.flatnonzero(~good)
        if bad.size:
            n = int(bad[0])
        hb = hashes.tobytes()
        return [
            IndexEntry(
                int(slots[i]), int(block_nos[i]), hb[32 * i : 32 * i + 32],
                int(offsets[i]), int(sizes[i]), int(crcs[i]),
            )
            for i in range(n)
        ]

    def _write_index(self, n: int, entries: list[IndexEntry]):
        data = b"".join(cbor.encode(e.to_cbor_obj()) for e in entries)
        self.fs.write_atomic(os.path.join(self.path, _index_name(n)), data)

    # -- queries -------------------------------------------------------------

    @property
    def is_empty(self) -> bool:
        return not any(self._entries.values())

    def tip(self) -> IndexEntry | None:
        for n in reversed(self._chunks):
            if self._entries[n]:
                return self._entries[n][-1]
        return None

    def tip_point(self) -> Point | None:
        t = self.tip()
        return None if t is None else Point(t.slot, t.hash_)

    def n_blocks(self) -> int:
        return sum(len(v) for v in self._entries.values())

    # -- appending -----------------------------------------------------------

    def append_block(self, slot: int, block_no: int, hash_: bytes, raw: bytes) -> None:
        t = self.tip()
        if t is not None and slot <= t.slot:
            raise ImmutableDBError(f"append out of order: {slot} <= {t.slot}")
        n = slot // self.chunk_size
        if n not in self._entries:
            self._entries[n] = []
            self._chunks.append(n)
            self._chunks.sort()
        cpath = os.path.join(self.path, _chunk_name(n))
        offset = self.fs.getsize(cpath) if self.fs.exists(cpath) else 0
        # the write-path chaos seam (testing/chaos.write_fault): the
        # torn-write/bit-rot fault matrix detonates HERE, where the
        # bytes meet the disk — one bool check disarmed
        fault = chaos.write_fault(chunk=n)
        if fault == "torn-write":
            # crash mid-append: a PREFIX of the block lands in the
            # chunk, no index entry, and the writer dies — startup
            # reparse finds the unparseable tail and truncates it
            self.fs.append(cpath, raw[: max(1, len(raw) // 2)])
            raise chaos.TornWriteChaos(
                f"chaos: append torn at chunk {n} slot {slot}"
            )
        data = raw
        if fault == "bitflip":
            # silent bit rot: the write "succeeds" with one byte flipped
            # on disk; the index entry records the TRUE crc, so only a
            # deep (all-chunks / stream) walk can catch it later
            buf = bytearray(raw)
            buf[len(buf) // 2] ^= 0x01
            data = bytes(buf)
        self.fs.append(cpath, data)
        if fault == "sigkill":
            import signal

            # a REAL kill between the chunk append and the index
            # append: the reopened store finds the index lagging
            os.kill(os.getpid(), signal.SIGKILL)
        e = IndexEntry(slot, block_no, hash_, offset, len(raw), zlib.crc32(raw))
        self._entries[n].append(e)
        # O(1) append-only index write (no fsync: startup validation
        # recovers from torn tails); CRC lives in the entry
        enc = cbor.encode(e.to_cbor_obj())
        ipath = os.path.join(self.path, _index_name(n))
        self.fs.append(ipath, enc)
        if fault == "index-truncate":
            # the index file is torn mid-entry and the writer dies —
            # the reopened store sees the index lag the chunk and
            # rebuilds it from chunk bytes
            size = self.fs.getsize(ipath)
            self.fs.truncate(ipath, max(0, size - max(1, len(enc) // 2)))
            raise chaos.IndexTornChaos(
                f"chaos: index torn at chunk {n} slot {slot}"
            )

    def flush(self) -> None:
        """fsync chunk + index data of the newest chunk (clean shutdown)."""
        if not self._chunks:
            return
        n = self._chunks[-1]
        for name in (_chunk_name(n), _index_name(n)):
            p = os.path.join(self.path, name)
            if self.fs.exists(p):
                self.fs.fsync(p)

    # -- reading -------------------------------------------------------------

    def _read(self, n: int, e: IndexEntry) -> bytes:
        return self.fs.read_at(
            os.path.join(self.path, _chunk_name(n)), e.offset, e.size
        )

    def get_block_bytes(self, point: Point) -> bytes:
        n = point.slot // self.chunk_size
        for e in self._entries.get(n, ()):
            if e.slot == point.slot and e.hash_ == point.hash_:
                return self._read(n, e)
        raise MissingBlock(point)

    def iter_entries(self) -> Iterator[IndexEntry]:
        """All index entries in slot order WITHOUT reading bodies (the
        secondary index walk: sizes, CRCs, hashes for stats/plans)."""
        for n in self._chunks:
            yield from self._entries[n]

    def iter_points(self) -> Iterator[Point]:
        """All block points in slot order WITHOUT reading bodies — the
        cheap plan walk ranged ChainDB iterators build on."""
        for e in self.iter_entries():
            yield Point(e.slot, e.hash_)

    def stream_all(self) -> Iterator[tuple[IndexEntry, bytes]]:
        """Stream every block in slot order (db-analyser processAll)."""
        for n in self._chunks:
            entries = self._entries[n]
            if not entries:
                continue
            data = self.fs.read_bytes(os.path.join(self.path, _chunk_name(n)))
            for e in entries:
                yield e, data[e.offset : e.offset + e.size]

    def stream_from(self, after_slot: int) -> Iterator[tuple[IndexEntry, bytes]]:
        """Stream blocks with slot > after_slot, seeking to the first
        relevant chunk instead of scanning from genesis (snapshot-resume
        replay, LedgerDB/Init.hs:116 — must not reread the whole DB)."""
        for n in self._chunks:
            entries = self._entries[n]
            if not entries or entries[-1].slot <= after_slot:
                continue  # chunk entirely at or before the snapshot point
            data = self.fs.read_bytes(os.path.join(self.path, _chunk_name(n)))
            for e in entries:
                if e.slot > after_slot:
                    yield e, data[e.offset : e.offset + e.size]

    def truncate_after(self, point: Point | None) -> None:
        """db-truncater (Tools/DBTruncater/Run.hs): drop everything after
        `point` (None = wipe)."""
        keep_through = -1 if point is None else point.slot
        for n in list(self._chunks):
            entries = [e for e in self._entries[n] if e.slot <= keep_through]
            if len(entries) != len(self._entries[n]):
                if entries:
                    data = self.fs.read_bytes(os.path.join(self.path, _chunk_name(n)))
                    self._entries[n] = entries
                    self._rewrite_chunk(n, data, entries)
                else:
                    self._remove_chunk(n)
                    self._entries.pop(n, None)
                    self._chunks.remove(n)
