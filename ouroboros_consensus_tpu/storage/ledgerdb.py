"""LedgerDB: last-k ledger-state checkpoints + on-disk snapshots.

Reference: `Ouroboros.Consensus.Storage.LedgerDB` (~1.6k LoC) — an
in-memory `AnchoredSeq` of `Checkpoint ExtLedgerState` (LedgerDB.hs:78,102)
anchored at the immutable tip's state, supporting `ledgerDbPush` (:294),
`ledgerDbSwitch` (:315 — rollback + pushMany), pruning to k; plus CBOR
snapshots on disk (Snapshots.hs:108), a keep-2 disk policy
(DiskPolicy.hs:87), and replay-on-init from the newest usable snapshot
with fallback to older/genesis (Init.hs:89-145) using `tickThenReapply`
(NO crypto).

The batched inversion: `push_many` with `apply=True` routes header crypto
through the protocol's device batch (BatchingProtocol.validate_batch)
while ledger-body application stays a cheap host fold — the `Ap` GADT's
Apply/Reapply distinction (Update.hs:89) becomes a flag.
"""

from __future__ import annotations

import os
import re
import zlib
from dataclasses import dataclass
from typing import Callable, Sequence

from ..block.abstract import Point
from ..ledger.extended import ExtLedger, ExtLedgerState
from ..ledger.header_validation import AnnTip, HeaderState, validate_envelope
from ..utils.fs import REAL_FS
from . import serialize


@dataclass
class InvalidBlock(Exception):
    point: Point
    reason: Exception


def encode_snapshot(state: ExtLedgerState) -> bytes:
    """Snapshot file = u32 CRC32 (LE) ‖ CBOR ExtLedgerState. The CRC
    makes ANY on-disk corruption detectable at init — a silently
    bit-flipped nonce would otherwise replay into a divergent chain
    (the reference pairs snapshots with checksum files for the same
    reason)."""
    payload = serialize.encode_ext_state(state)
    return zlib.crc32(payload).to_bytes(4, "little") + payload


def decode_snapshot(data: bytes) -> ExtLedgerState:
    if len(data) < 4:
        raise ValueError("snapshot too short")
    crc, payload = int.from_bytes(data[:4], "little"), data[4:]
    if zlib.crc32(payload) == crc:
        return serialize.decode_ext_state(payload)
    # migration: snapshots written before the CRC framing are raw CBOR —
    # accept them iff the WHOLE byte string decodes (a corrupted CRC
    # snapshot cannot: its leading 4 CRC bytes are not valid CBOR here)
    try:
        return serialize.decode_ext_state(data)
    except Exception:
        raise ValueError("snapshot checksum mismatch") from None


class LedgerDB:
    """AnchoredSeq of (point, state): index 0 is the anchor (immutable
    tip); at most k volatile checkpoints follow."""

    def __init__(self, ext: ExtLedger, k: int, anchor: ExtLedgerState, fs=None):
        self.ext = ext
        self.k = k
        self.fs = fs if fs is not None else REAL_FS
        self._seq: list[tuple[Point | None, ExtLedgerState]] = [
            (ext.tip_point(anchor), anchor)
        ]
        # LgrDB's varPrevApplied (Impl/LgrDB.hs:86): hash -> slot of
        # blocks validated before — a fork switch re-crossing them
        # chooses ReapplyVal (no crypto) instead of ApplyVal
        # (LgrDB.hs:330); GC'd alongside the VolatileDB
        self._prev_applied: dict[bytes, int] = {}
        # typed event tracer: `_push_many_batched` emits one
        # ValidatedBatch (utils.trace) per fused device segment — the
        # NodeKernel wires this to its NodeMetrics/registry fold
        self.tracer = None

    # -- queries -------------------------------------------------------------

    def current(self) -> ExtLedgerState:
        return self._seq[-1][1]

    def anchor(self) -> ExtLedgerState:
        return self._seq[0][1]

    def tip_point(self) -> Point | None:
        return self._seq[-1][0]

    def volatile_length(self) -> int:
        return len(self._seq) - 1

    def past_state(self, point: Point | None) -> ExtLedgerState | None:
        """getPastLedger: state at `point` if within the last k blocks."""
        for p, st in self._seq:
            if p == point:
                return st
        return None

    def header_states(self) -> list[HeaderState]:
        """Header states of every checkpoint, anchor first — the seed
        for the ChainDB's HeaderStateHistory (HeaderStateHistory.hs
        `fromChain` over the in-memory checkpoints)."""
        return [st.header_state for _, st in self._seq]

    def last_header_states(self, n: int) -> list[HeaderState]:
        """Header states of the newest n checkpoints, oldest first."""
        return [st.header_state for _, st in self._seq[len(self._seq) - n :]] if n else []

    # -- updates -------------------------------------------------------------

    def push(self, block, apply: bool = True) -> ExtLedgerState:
        """ledgerDbPush + prune-to-k. `apply` requests full validation,
        downgraded to reapply for previously-applied blocks (the Ap GADT
        choice in LgrDB.validate, Impl/LgrDB.hs:330)."""
        st = self.current()
        requested_apply = apply
        if apply and block.hash_ in self._prev_applied:
            apply = False
        new = (
            self.ext.tick_then_apply(st, block)
            if apply
            else self.ext.tick_then_reapply(st, block)
        )
        if requested_apply:
            # only VALIDATION records prev-applied (LgrDB.hs adds in
            # validate, not during replay) — an immutable-replay push
            # (apply=False) must not grow an O(chain) dict
            self._prev_applied[block.hash_] = block.slot
        self._seq.append((block.point, new))
        if len(self._seq) > self.k + 1:
            self._seq = self._seq[len(self._seq) - (self.k + 1) :]
        return new

    def rollback(self, n: int) -> bool:
        """ledgerDbRollback: drop the last n states; fails beyond k."""
        if n > self.volatile_length():
            return False
        if n:
            self._seq = self._seq[:-n]
        return True

    def push_many(self, blocks: Sequence, apply: bool = True) -> None:
        """ledgerDbPushMany; with `apply` and a batching protocol, header
        crypto runs as fused device batches (epoch-segmented). Runs of
        previously-applied blocks skip the kernels entirely (Reapply)."""
        proto = self.ext.protocol
        if apply and getattr(proto, "use_device_batch", False) and len(blocks) > 1:
            i, n = 0, len(blocks)
            while i < n:
                fresh = blocks[i].hash_ not in self._prev_applied
                j = i
                while j < n and (blocks[j].hash_ not in self._prev_applied) == fresh:
                    j += 1
                run = blocks[i:j]
                if fresh:
                    self._push_many_batched(run)
                else:
                    for b in run:
                        try:
                            self.push(b, False)
                        except Exception as e:
                            raise InvalidBlock(b.point, e) from e
                i = j
        else:
            for b in blocks:
                try:
                    self.push(b, apply)
                except Exception as e:
                    raise InvalidBlock(b.point, e) from e

    def _push_many_batched(self, blocks: Sequence) -> None:
        """Bodies: sequential host fold. Headers: device batch per epoch
        segment (protocol/batch.py), envelope checks on host."""
        proto = self.ext.protocol
        params = proto.params
        i = 0
        n = len(blocks)
        while i < n:
            epoch = params.epoch_of(blocks[i].slot)
            j = i
            while j < n and params.epoch_of(blocks[j].slot) == epoch:
                j += 1
            segment = blocks[i:j]
            st = self.current()
            # envelope + ledger bodies first (reference order applies the
            # ledger before validateHeader, Extended.hs:142-156); a body/
            # envelope failure truncates the segment so header states for
            # the valid prefix are STILL pushed before raising (callers —
            # ChainSel's truncate-rejected loop — rely on that)
            ext_states = []
            tip = st.header_state.tip
            ledger_state = st.ledger_state
            pending: InvalidBlock | None = None
            for b in segment:
                try:
                    validate_envelope(tip, b.header)
                    ledger_state = self.ext.ledger.tick_then_apply(ledger_state, b)
                except Exception as e:
                    pending = InvalidBlock(b.point, e)
                    break
                tip = AnnTip(b.slot, b.block_no, b.hash_)
                ext_states.append(ledger_state)
            segment = segment[: len(ext_states)]
            if segment:
                # ticked ledger view for the segment's epoch from the
                # current state (mock: static; HFC: per-era summary)
                lt = self.ext.ledger.tick(st.ledger_state, segment[0].slot)
                view = self.ext.ledger.protocol_ledger_view(lt)
                ticked = proto.tick(
                    view, segment[0].slot, st.header_state.chain_dep_state
                )
                import time as _time

                t0 = _time.monotonic()
                res = proto.validate_batch(
                    ticked, [b.header.to_view() for b in segment], collect_states=True
                )
                if self.tracer is not None:
                    from ..utils.trace import ValidatedBatch

                    self.tracer(ValidatedBatch(
                        len(segment), res.n_valid,
                        _time.monotonic() - t0,
                    ))
                for idx in range(res.n_valid):
                    b = segment[idx]
                    hs = HeaderState(
                        AnnTip(b.slot, b.block_no, b.hash_), res.states[idx]
                    )
                    self._seq.append((b.point, ExtLedgerState(ext_states[idx], hs)))
                    self._prev_applied[b.hash_] = b.slot
                if len(self._seq) > self.k + 1:
                    self._seq = self._seq[len(self._seq) - (self.k + 1) :]
                if res.error is not None:
                    raise InvalidBlock(segment[res.n_valid].point, res.error)
            if pending is not None:
                raise pending
            i = j

    def gc_prev_applied(self, slot: int) -> None:
        """garbageCollectPrevApplied (Impl/LgrDB.hs): forget hashes with
        slot < `slot` — the VolatileDB no longer holds those blocks, so
        they can never be pushed again."""
        self._prev_applied = {
            h: s for h, s in self._prev_applied.items() if s >= slot
        }

    def switch(self, n_rollback: int, blocks: Sequence, apply: bool = True) -> bool:
        """ledgerDbSwitch (Update.hs:315): rollback then pushMany."""
        if not self.rollback(n_rollback):
            return False
        self.push_many(blocks, apply)
        return True

    # -- snapshots (Snapshots.hs, DiskPolicy.hs) -----------------------------

    SNAP_RE = re.compile(r"^snapshot-(\d+)$")

    def take_snapshot(self, snap_dir: str, keep: int = 2) -> str | None:
        """Write the ANCHOR state (immutable tip, Snapshots.hs:108) named
        by its slot; prune to `keep` newest (DiskPolicy: default 2)."""
        self.fs.makedirs(snap_dir)
        anchor_point, anchor = self._seq[0]
        slot = 0 if anchor_point is None else anchor_point.slot
        name = f"snapshot-{slot}"
        path = os.path.join(snap_dir, name)
        if self.fs.exists(path):
            return None
        self.fs.write_atomic(path, encode_snapshot(anchor))
        snaps = sorted(self.list_snapshots(snap_dir, fs=self.fs))
        for s in snaps[:-keep]:
            self.fs.remove(os.path.join(snap_dir, f"snapshot-{s}"))
        return name

    @classmethod
    def list_snapshots(cls, snap_dir: str, fs=None) -> list[int]:
        fs = fs if fs is not None else REAL_FS
        if not fs.isdir(snap_dir):
            return []
        out = []
        for f in fs.listdir(snap_dir):
            m = cls.SNAP_RE.match(f)
            if m:
                out.append(int(m.group(1)))
        return out

    @classmethod
    def init_from_snapshots(
        cls,
        ext: ExtLedger,
        k: int,
        snap_dir: str,
        genesis: ExtLedgerState,
        immutable_db,
        trace: Callable[[str], None] = lambda s: None,
        fs=None,
        decode_block=None,
    ) -> "LedgerDB":
        """initLedgerDB (Init.hs:89-145): newest snapshot first, fall back
        to older ones then genesis; replay immutable blocks after the
        snapshot with tickThenReapply (no crypto)."""
        if decode_block is None:
            from ..block.praos_block import Block

            decode_block = Block.from_bytes
        fs = fs if fs is not None else REAL_FS
        for slot in sorted(cls.list_snapshots(snap_dir, fs=fs), reverse=True):
            path = os.path.join(snap_dir, f"snapshot-{slot}")
            try:
                state = decode_snapshot(fs.read_bytes(path))
            except Exception:
                trace(f"snapshot-{slot} unreadable; falling back")
                fs.remove(path)
                continue
            db = cls(ext, k, state, fs=fs)
            tip_slot = ext.tip_slot(state)
            start = -1 if tip_slot is None else tip_slot  # None = genesis
            for entry, raw in immutable_db.stream_from(start):
                db.push(decode_block(raw), apply=False)
                db._seq = db._seq[-1:]  # replay keeps only the tip state
            trace(f"replayed from snapshot-{slot}")
            return db
        db = cls(ext, k, genesis, fs=fs)
        n = 0
        for entry, raw in immutable_db.stream_all():
            db.push(decode_block(raw), apply=False)
            db._seq = db._seq[-1:]
            n += 1
        trace(f"replayed {n} blocks from genesis")
        return db
