"""ChainDB: the unified chain store + chain selection.

Reference: `Ouroboros.Consensus.Storage.ChainDB` — the `ChainDB` record
(API.hs:117) facading ImmutableDB + VolatileDB + LedgerDB, and ChainSel
(Impl/ChainSel.hs, 1,305 LoC), the consensus decision engine:

  * `add_block` (addBlockSync, ChainSel.hs:256): store in VolatileDB,
    then `chainSelectionForBlock` (:440) — construct maximal candidate
    fragments through the volatile successor graph (Paths.hs:65
    maximalCandidates / isReachable :372), order them by SelectView
    (chainSelection :874), validate the best (ledgerValidateCandidate
    :1053 → LedgerDB switch), and install the winner.
  * followers (Impl/Follower.hs) — push-style chain-update consumers
    feeding the ChainSync server.
  * background copy: blocks > k deep migrate VolatileDB → ImmutableDB
    with a LedgerDB snapshot (Impl/Background.hs copyAndSnapshotRunner);
    VolatileDB GC after copy.
  * invalid-block set (getIsInvalidBlock, API.hs:331) so peers serving
    known-bad blocks are punished once, not revalidated.

The batched inversion: candidate suffix validation goes through
`LedgerDB.push_many`, which ships the headers' crypto to the device as one
fused batch instead of per-block calls.

Concurrency: the reference serializes chain selection through an STM
queue + single background thread (cdbBlocksToAdd, ChainSel.hs:217-246)
and runs copy/snapshot/GC on background threads (Impl/Background.hs).
Both shapes exist here:

  * synchronous (default): `add_block` IS the serialization point and
    runs the copy/GC step inline — the shape the CLI tools use.
  * decoupled: `add_block_async` enqueues and returns an
    AddBlockPromise; `add_block_runner()` (a sim/asyncio task) pops and
    serializes chain selection, and `background_runner()` performs
    copy-to-immutable, snapshots and DELAYED VolatileDB GC (the
    GcSchedule analog) off the adoption path. Peer tasks never block on
    chain selection, mirroring ChainSel.hs:217-246 + Background.hs:17-38.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Sequence

from ..block.abstract import Point
from ..block.praos_block import Block
from ..ledger.extended import ExtLedger, ExtLedgerState
from ..ledger.header_history import HeaderStateHistory
from ..utils.sim import Event, Fire, Sleep, Wait
from .immutable import ImmutableDB
from .ledgerdb import InvalidBlock, LedgerDB
from .volatile import VolatileDB


class BlockGCed(Exception):
    """Iterator hit a block removed from BOTH stores (Impl/Iterator.hs
    IteratorBlockGCed): the stream fell off the chain's history."""


class MissingBlockError(Exception):
    """Ranged stream bounds not on the current chain (UnknownRange)."""


@dataclass
class AddBlockResult:
    added: bool
    new_tip: Point | None  # tip after (possibly unchanged)
    selected: bool  # did the chain change?


@dataclass
class AddBlockPromise:
    """The caller-visible side of an enqueued block (API.hs:134
    AddBlockPromise): `processed` fires once chain selection ran."""

    block: Block
    processed: Event
    result: AddBlockResult | None = None


class Follower:
    """A push-style consumer of chain updates (Impl/Follower.hs): the
    ChainSync server reads (rollback, new_blocks) instructions.

    `include_tentative` makes this a diffusion-pipelining follower
    (Impl/Follower.hs tentative followers, ChainSel.hs:949-984): headers
    of blocks that extend the current tip are announced BEFORE chain
    selection validates their bodies; if the block is then not adopted,
    a compensating rollback instruction precedes the real update."""

    def __init__(self, db: "ChainDB", include_tentative: bool = False):
        self.db = db
        self.include_tentative = include_tentative
        # ("rollback", Point|None) | ("addblock", Block) | ("tentative", Header)
        self.updates: list = []
        self.event = Event("follower")  # fired on every new instruction
        self._tentative_hash: bytes | None = None
        self._tentative_prev: Point | None = None

    def _notify_tentative(self, header, prev_point: Point | None) -> None:
        if not self.include_tentative or self._tentative_hash is not None:
            return
        self._tentative_hash = header.hash_
        self._tentative_prev = prev_point
        self.updates.append(("tentative", header))
        self._wake()

    def _retract_tentative(self, hash_: bytes) -> None:
        """Chain selection finished WITHOUT adopting the announced block
        (the trap case): retract the tentative header."""
        if self._tentative_hash == hash_:
            self.updates.append(("rollback", self._tentative_prev))
            self._tentative_hash = None
            self._tentative_prev = None
            self._wake()

    def _notify_switch(
        self,
        rolled_back: bool,
        rollback_to: Point | None,
        new_blocks: Sequence[Block],
    ):
        # `rolled_back` distinguishes "no rollback" from "rollback to
        # genesis" — rollback_to is None in BOTH cases
        new_blocks = list(new_blocks)
        if self._tentative_hash is not None:
            if (
                not rolled_back
                and new_blocks
                and new_blocks[0].hash_ == self._tentative_hash
            ):
                # tentative confirmed: the header was already announced
                new_blocks = new_blocks[1:]
            else:
                # tentative lost (trap / different fork): retract it
                # before relaying the real update
                self.updates.append(("rollback", self._tentative_prev))
            self._tentative_hash = None
            self._tentative_prev = None
        if rolled_back:
            self.updates.append(("rollback", rollback_to))
        for b in new_blocks:
            self.updates.append(("addblock", b))
        if self.updates:
            self._wake()

    def _wake(self) -> None:
        if self.db.runtime is not None:
            self.db.runtime.fire(self.event)

    def take_updates(self) -> list:
        out, self.updates = self.updates, []
        return out

    def reset_position(self) -> None:
        """Drop queued instructions AND pending-tentative tracking — the
        server re-anchors on a chain snapshot at find_intersect, so a
        not-yet-resolved tentative must be delivered afresh when (if)
        its block is adopted."""
        self.updates = []
        self._tentative_hash = None
        self._tentative_prev = None

    def close(self) -> None:
        """Unregister (ChainDB followers are owned by their protocol
        server; a killed server must not leak its follower)."""
        self.db.remove_follower(self)


class DiskPolicy:
    """defaultDiskPolicy (Storage/LedgerDB/DiskPolicy.hs:87-108).

    * keep 2 on-disk snapshots (LedgerDB.take_snapshot keep=2);
    * with NO snapshot taken yet this run, snapshot once k blocks have
      been copied/replayed (covers short-lived nodes that would never
      reach the time trigger);
    * otherwise snapshot when the time since the last one reaches the
      requested interval (default k*2 seconds — 72 min at k=2160), or
      when a substantial burst was processed: >= 50k blocks AND >= 6
      minutes since the last snapshot (bulk-sync cadence cap).
    """

    MIN_BLOCKS_BEFORE_SNAPSHOT = 50_000
    MIN_TIME_BEFORE_SNAPSHOT = 6 * 60.0

    def __init__(self, k: int, requested_interval_s: float | None = None):
        self.k = k
        self.interval_s = (
            float(requested_interval_s)
            if requested_interval_s is not None
            else 2.0 * k
        )
        self._last_snapshot_at: float | None = None  # NoSnapshotTakenYet

    def should_take_snapshot(self, blocks_since_last: int, now_s: float) -> bool:
        if self._last_snapshot_at is None:
            return blocks_since_last >= self.k
        since = now_s - self._last_snapshot_at
        if since >= self.interval_s:
            return True
        return (
            blocks_since_last >= self.MIN_BLOCKS_BEFORE_SNAPSHOT
            and since >= self.MIN_TIME_BEFORE_SNAPSHOT
        )

    def snapshot_taken(self, now_s: float) -> None:
        self._last_snapshot_at = now_s


class ChainDB:
    """The facade. `current_chain` is the volatile fragment (≤ k blocks,
    newest last); older blocks live in the ImmutableDB."""

    def __init__(
        self,
        ext: ExtLedger,
        immutable: ImmutableDB,
        volatile: VolatileDB,
        ledgerdb: LedgerDB,
        k: int,
        snap_dir: str | None = None,
        snapshot_interval: int = 100,
        trace: Callable[[str], None] = lambda s: None,
        check_in_future=None,  # block.infuture.CheckInFuture | None
        decode_block=None,  # block codec seam; default = Praos Block
        tracer=None,  # TYPED event tracer (utils.trace ChainDB algebra,
        # ChainDB/Impl.hs:10-28) — `trace` stays the human-string log
    ):
        self.ext = ext
        self.immutable = immutable
        self.volatile = volatile
        self.ledgerdb = ledgerdb
        self.decode_block = (
            decode_block if decode_block is not None else Block.from_bytes
        )
        self.k = k
        self.snap_dir = snap_dir
        # DiskPolicy (DiskPolicy.hs:87-108): block-count trigger kept for
        # sim determinism when `snapshot_interval` is given; the
        # reference's time-based default (k*2 seconds, 50k-block burst
        # rule, snapshot at k blocks on a fresh run) via `disk_policy`
        self.snapshot_interval = snapshot_interval
        self.disk_policy: DiskPolicy | None = None
        self._copied_since_snapshot = 0
        self.trace = trace
        from ..utils import trace as T

        self.tracer = tracer if tracer is not None else T.null_tracer
        self._T = T
        # CheckInFuture (Fragment/InFuture.hs:45): candidates are cut at
        # their first in-future header before selection; None = dontCheck
        self.check_in_future = check_in_future
        self.current_chain: list[Block] = []  # volatile fragment, ≤ k
        self.invalid: dict[bytes, Exception] = {}  # hash -> reason
        self._block_cache: dict[bytes, Block] = {}  # per-selection (BlockCache.hs)
        self.followers: list[Follower] = []
        # decoupled mode state (add_block_runner / background_runner)
        self._blocks_to_add: deque[AddBlockPromise] = deque()
        self._queue_event = Event("blocks-to-add")
        self._chain_event = Event("chain-changed")
        self._background_decoupled = False
        self.runtime = None  # object with .fire(Event), set by the node
        # k-deep header-state history of the CURRENT chain
        # (HeaderStateHistory.hs): answers header_state_at without
        # touching the LedgerDB's full ExtLedgerStates. Maintained
        # incrementally by _install, resynced from the LedgerDB (the
        # authoritative store) when the shapes diverge.
        self.header_history = HeaderStateHistory(k=k)
        self._init_chain_selection()
        self._sync_header_history()

    # -- initial chain selection (ChainSel.hs:96) ----------------------------

    def _init_chain_selection(self) -> None:
        """Find the best chain through the volatile graph extending the
        immutable tip; validates via LedgerDB. The SAME validate-best /
        truncate-rejected loop as chainSelectionForBlock: a candidate
        that truncates to a valid prefix must not end selection — the
        next-best candidate may beat that prefix (initialChainSelection,
        ChainSel.hs:96)."""
        self.current_chain = []
        anchor = self._anchor_point()
        rejected: list[list[bytes]] = []
        while True:
            cand = self._best_candidate_from(anchor, rejected)
            if cand is None:
                return
            cur_view = self._current_select_view()
            if self.check_in_future is not None:
                kept, dropped = self.check_in_future.truncate(cand)
                if dropped:
                    self.trace(
                        f"init: {len(dropped)} in-future block(s) cut "
                        f"from candidate"
                    )
                    kept_view = (
                        self.ext.protocol.select_view(kept[-1].header)
                        if kept else None
                    )
                    if not kept or (
                        cur_view is not None
                        and self.ext.protocol.compare_candidates(
                            cur_view, kept_view
                        ) <= 0
                    ):
                        rejected.append([b.hash_ for b in cand])
                        continue
                    rejected.append([b.hash_ for b in cand])
                    cand = kept
            cand_view = self.ext.protocol.select_view(cand[-1].header)
            if (
                cur_view is not None
                and self.ext.protocol.compare_candidates(cur_view, cand_view) <= 0
            ):
                return
            n_rollback, suffix = self._diff_against_current(cand)
            outcome = self._try_adopt(n_rollback, suffix, full_candidate=cand)
            if outcome == "adopted":
                return
            rejected.append([b.hash_ for b in cand])

    def _anchor_point(self) -> Point | None:
        return self.immutable.tip_point()

    # -- queries (API.hs) ----------------------------------------------------

    def tip_point(self) -> Point | None:
        if self.current_chain:
            return self.current_chain[-1].point
        return self._anchor_point()

    def tip_header(self):
        return self.current_chain[-1].header if self.current_chain else None

    def tip_block_no(self) -> int | None:
        if self.current_chain:
            return self.current_chain[-1].block_no
        t = self.immutable.tip()
        return None if t is None else t.block_no

    def current_ledger(self) -> ExtLedgerState:
        return self.ledgerdb.current()

    def get_past_ledger(self, point: Point | None) -> ExtLedgerState | None:
        return self.ledgerdb.past_state(point)

    def header_state_at(self, point: Point | None):
        """HeaderState at `point` on the current chain, answered from the
        k-deep HeaderStateHistory (HeaderStateHistory.hs) — the cheap
        path for seeding a ChainSync peer candidate at an intersection.
        Falls back to the LedgerDB for the anchor/genesis. None if the
        point is not on the recent chain."""
        if point is not None:
            hs = self.header_history.state_at(point)
            if hs is not None:
                return hs
        ext = self.ledgerdb.past_state(point)
        return None if ext is None else ext.header_state

    def _sync_header_history(self) -> None:
        """Rebuild the header history from the LedgerDB checkpoints.

        The LedgerDB's volatile tail aligns 1:1 with the newest
        current_chain blocks (both are pruned to k); its header states
        ARE the history."""
        states = self.ledgerdb.header_states()
        n = len(states) - 1
        hh = self.header_history
        hh.states = states
        hh.headers = (
            [b.header for b in self.current_chain[len(self.current_chain) - n :]]
            if n > 0
            else []
        )
        hh.trimmed = states[0].tip is not None

    def _update_header_history(self, n_rollback: int, suffix: list[Block]) -> None:
        """Incremental history maintenance after _install: rollback the
        replaced suffix, append the new states the LedgerDB just pushed.
        extend() trims to k as the chain grows."""
        hh = self.header_history
        if n_rollback <= len(hh.headers) and len(suffix) <= self.ledgerdb.volatile_length():
            hh.rollback_n(n_rollback)
            for b, hs in zip(suffix, self.ledgerdb.last_header_states(len(suffix))):
                hh.extend(b.header, hs)
        else:
            self._sync_header_history()

    def get_is_invalid_block(self, hash_: bytes) -> Exception | None:
        return self.invalid.get(hash_)

    def get_block(self, point: Point) -> Block | None:
        raw = self.volatile.get_block_bytes(point.hash_)
        if raw is None:
            try:
                raw = self.immutable.get_block_bytes(point)
            except Exception:
                return None
        return self.decode_block(raw)

    def new_follower(self, include_tentative: bool = False) -> Follower:
        f = Follower(self, include_tentative=include_tentative)
        self.followers.append(f)
        self.tracer(self._T.NewFollowerEvent(include_tentative))
        return f

    def remove_follower(self, f: Follower) -> None:
        if f in self.followers:
            self.followers.remove(f)

    def stream_all(self) -> Iterable[Block]:
        """Iterator over the whole current chain, immutable part first."""
        for entry, raw in self.immutable.stream_all():
            yield self.decode_block(raw)
        yield from self.current_chain

    def stream(
        self, from_exclusive: Point | None = None, to_inclusive: Point | None = None
    ) -> Iterable[Block]:
        """GC-safe ranged iterator (ChainDB.stream, API.hs:274 +
        Impl/Iterator.hs): stream the current chain after
        `from_exclusive` up to `to_inclusive` (None = tip at creation).

        The PLAN (the point sequence) is pinned at creation; each body
        is resolved lazily at yield time — first from the VolatileDB,
        then from the ImmutableDB. A block that background copy+GC moved
        between the stores mid-iteration is therefore still found (the
        reference's Volatile→Immutable iterator switching); a block
        found in NEITHER store raises BlockGCed."""
        plan: list[Point] = []
        started = from_exclusive is None
        done = False

        def visit(p: Point) -> None:
            nonlocal started, done
            if not started:
                if p == from_exclusive:
                    started = True
                    if to_inclusive == from_exclusive:
                        done = True  # valid empty range
                return
            plan.append(p)
            if to_inclusive is not None and p == to_inclusive:
                done = True

        for p in self.immutable.iter_points():
            visit(p)
            if done:
                break
        if not done:
            for b in self.current_chain:
                visit(b.point)
                if done:
                    break
        if not started:
            raise MissingBlockError(from_exclusive)
        if to_inclusive is not None and not done:
            raise MissingBlockError(to_inclusive)

        def resolve():
            for p in plan:
                raw = self.volatile.get_block_bytes(p.hash_)
                if raw is None:
                    try:
                        raw = self.immutable.get_block_bytes(p)
                    except Exception:
                        raise BlockGCed(p) from None
                yield self.decode_block(raw)

        return resolve()

    # -- candidates (Impl/Paths.hs) ------------------------------------------

    def _candidates_through(
        self, anchor: Point | None, via: bytes | None = None
    ) -> list[list[bytes]]:
        """maximalCandidates (Paths.hs:65): maximal hash-paths in the
        volatile successor graph rooted at `anchor`. With `via`, only the
        paths passing through that block (isReachable, Paths.hs:372):
        walk prev-hashes backwards from `via` to the anchor, then extend
        forward — O(depth + subtree) instead of the whole graph.

        Iterative DFS: volatile paths reach k blocks (2160 mainnet),
        beyond Python's recursion limit.
        """
        root = None if anchor is None else anchor.hash_

        if via is not None:
            back: list[bytes] = []
            h = via
            while True:
                info = self.volatile.get_block_info(h)
                if info is None or h in self.invalid:
                    return []  # not connected (yet) or known bad
                back.append(h)
                if info.prev_hash == root:
                    break
                h = info.prev_hash
                if h is None:
                    return []  # hit genesis without meeting the anchor
            prefix = list(reversed(back))
            return [prefix[:-1] + tail for tail in self._forward_paths(via)]

        out: list[list[bytes]] = []
        # stack of (hash, path-so-far); paths share list copies only on fork
        stack: list[tuple[bytes | None, list[bytes]]] = [(root, [])]
        while stack:
            h, acc = stack.pop()
            succs = [
                s
                for s in self.volatile.filter_by_predecessor(h)
                if s not in self.invalid
            ]
            if not succs:
                if acc:
                    out.append(acc)
                continue
            for s in succs:
                stack.append((s, acc + [s]))
        return out

    def _forward_paths(self, start: bytes) -> list[list[bytes]]:
        """All maximal paths beginning AT `start` (inclusive)."""
        out: list[list[bytes]] = []
        stack: list[tuple[bytes, list[bytes]]] = [(start, [start])]
        while stack:
            h, acc = stack.pop()
            succs = [
                s
                for s in self.volatile.filter_by_predecessor(h)
                if s not in self.invalid
            ]
            if not succs:
                out.append(acc)
                continue
            for s in succs:
                stack.append((s, acc + [s]))
        return out

    def _load_fragment(self, hashes: list[bytes]) -> list[Block] | None:
        blocks = []
        for h in hashes:
            cached = self._block_cache.get(h)
            if cached is not None:
                blocks.append(cached)
                continue
            raw = self.volatile.get_block_bytes(h)
            if raw is None:
                return None
            blocks.append(self.decode_block(raw))
        return blocks

    def _best_candidate_from(
        self,
        anchor: Point | None,
        exclude: Sequence[Sequence[bytes]],
        via: bytes | None = None,
    ) -> list[Block] | None:
        """Best UNVALIDATED candidate by SelectView ordering; `exclude`
        lists hash-fragments already rejected this round."""
        cands = [
            c for c in self._candidates_through(anchor, via)
            if not any(list(c) == list(e) for e in exclude)
        ]
        if not cands:
            return None
        proto = self.ext.protocol

        # compare by TIP select-view only (sortCandidates, ChainSel.hs:874
        # orders on the tip's SelectView) — parsing whole fragments here
        # would cost O(k) block reads per incoming block on the hot path
        def tip_view(c):
            cached = self._block_cache.get(c[-1])
            if cached is not None:
                return proto.select_view(cached.header)
            raw = self.volatile.get_block_bytes(c[-1])
            if raw is None:
                return None
            return proto.select_view(self.decode_block(raw).header)

        ranked = [(c, v) for c in cands if (v := tip_view(c)) is not None]
        # best-first: load the full fragment only for the winner; fall
        # back to the next candidate if a body went missing (GC race)
        while ranked:
            best_i = 0
            for i in range(1, len(ranked)):
                if proto.compare_candidates(ranked[best_i][1], ranked[i][1]) > 0:
                    best_i = i
            c, _ = ranked.pop(best_i)
            blocks = self._load_fragment(c)
            if blocks is not None:
                return blocks
        return None

    # -- chain selection for a new block (ChainSel.hs:440) -------------------

    def add_block(self, block: Block) -> AddBlockResult:
        """addBlockSync: store, then run chain selection."""
        if block.hash_ in self.invalid:
            self.tracer(self._T.IgnoreInvalidBlock(block.slot, block.hash_))
            return AddBlockResult(False, self.tip_point(), False)
        # olderThanK (ChainSel.hs:359): blocks at or before the immutable
        # tip slot can never be adopted
        imm = self.immutable.tip()
        if imm is not None and block.slot <= imm.slot:
            self.tracer(
                self._T.IgnoreBlockOlderThanK(block.slot, block.hash_)
            )
            return AddBlockResult(False, self.tip_point(), False)
        self.volatile.put_block(block)
        self.tracer(self._T.AddedBlockToVolatileDB(block.slot, block.hash_))
        # BlockCache (Impl/BlockCache.hs): the block in hand need not be
        # reread/reparsed from the VolatileDB during this selection
        self._block_cache[block.hash_] = block
        try:
            selected = self._chain_selection_for_block(block)
        finally:
            self._block_cache.clear()
        if not selected:
            self.tracer(self._T.StoreButDontChange(block.slot, block.hash_))
        return AddBlockResult(True, self.tip_point(), selected)

    def _current_select_view(self):
        proto = self.ext.protocol
        if self.current_chain:
            return proto.select_view(self.current_chain[-1].header)
        return None

    def _chain_selection_for_block(self, block: Block) -> bool:
        """chainSelectionForBlock: consider candidates containing `block`;
        loop validate-best / truncate-rejected (chainSelection :874).
        Adopting a TRUNCATED prefix of a candidate continues the loop —
        the remaining candidates are compared against the new (prefix)
        chain, so a longer fully-valid fork is never shadowed by a
        better-ranked candidate that failed validation."""
        proto = self.ext.protocol
        anchor = self._anchor_point()
        rejected: list[list[bytes]] = []
        changed = False
        while True:
            cur_view = self._current_select_view()
            cand = self._best_candidate_from(anchor, rejected, via=block.hash_)
            if cand is None:
                return changed
            # rejection must always record the FULL candidate's hashes:
            # _best_candidate_from excludes by exact hash-list equality
            # against the maximal fragments it regenerates, so rejecting
            # only a truncated prefix would re-select the same candidate
            # forever when _try_adopt fails without changing any state
            # (e.g. rollback beyond the LedgerDB window)
            full_hashes = [b.hash_ for b in cand]
            if self.check_in_future is not None:
                kept, dropped = self.check_in_future.truncate(cand)
                if dropped:
                    self.trace(
                        f"{len(dropped)} in-future block(s) cut from "
                        f"candidate (first at slot {dropped[0].slot})"
                    )
                    # candidates were RANKED by untruncated tip, so a
                    # truncated loser must not end the loop — reject it
                    # and let the next-best (possibly all-present-slot)
                    # candidate have its turn
                    kept_view = (
                        proto.select_view(kept[-1].header) if kept else None
                    )
                    if not kept or proto.compare_candidates(
                        cur_view, kept_view
                    ) <= 0:
                        rejected.append(full_hashes)
                        continue
                    cand = kept
            cand_view = proto.select_view(cand[-1].header)
            # preferCandidate: only strictly better chains are adopted
            if proto.compare_candidates(cur_view, cand_view) <= 0:
                return changed
            n_rollback, suffix = self._diff_against_current(cand)
            outcome = self._try_adopt(n_rollback, suffix, full_candidate=cand)
            if outcome == "adopted":
                return True
            if outcome == "prefix":
                changed = True
            rejected.append(full_hashes)

    def _diff_against_current(self, cand: list[Block]):
        """ChainDiff (Fragment/Diff.hs): longest common prefix with the
        current chain -> (rollback count, new suffix)."""
        i = 0
        while (
            i < len(cand)
            and i < len(self.current_chain)
            and cand[i].hash_ == self.current_chain[i].hash_
        ):
            i += 1
        return len(self.current_chain) - i, cand[i:]

    def _try_adopt(
        self, n_rollback: int, suffix: list[Block], full_candidate: list[Block] | None = None
    ) -> str:
        """ledgerValidateCandidate (:1053): LedgerDB switch validates the
        suffix (batched header crypto). On invalid blocks, mark + truncate
        and adopt the valid prefix if it still beats the current chain
        (the truncate-rejected loop).

        Returns "adopted" (full candidate installed), "prefix" (an
        invalid block truncated it; the VALID PREFIX was installed), or
        "failed" (nothing changed)."""
        if not suffix and n_rollback == 0:
            return "failed"
        n_before = self.ledgerdb.volatile_length()
        state_before = self.ledgerdb.current()
        try:
            if not self.ledgerdb.switch(n_rollback, suffix):
                # rollback deeper than the LedgerDB holds (> k): the
                # candidate forks before our immutability window — reject
                self.trace(f"rollback {n_rollback} beyond LedgerDB window")
                return "failed"
        except InvalidBlock as e:
            self.invalid[e.point.hash_] = e.reason
            self.trace(f"invalid block at {e.point}: {type(e.reason).__name__}")
            self.tracer(self._T.InvalidBlockEvent(
                e.point.slot, e.point.hash_, type(e.reason).__name__
            ))
            # LedgerDB has adopted the valid prefix's states already;
            # roll its extra states back to match a prefix decision
            n_valid = next(
                (i for i, b in enumerate(suffix) if b.point == e.point),
                len(suffix),
            )
            prefix = suffix[:n_valid]
            if prefix:
                proto = self.ext.protocol
                cur_view = self._current_select_view()
                pref_view = proto.select_view(prefix[-1].header)
                if proto.compare_candidates(cur_view, pref_view) > 0:
                    self._install(n_rollback, prefix)
                    return "prefix"
            # restore: rollback the states LedgerDB pushed for the prefix
            pushed = self.ledgerdb.volatile_length() - (n_before - n_rollback)
            if pushed > 0:
                self.ledgerdb.rollback(pushed)
            # and re-push the states for the blocks we rolled back earlier
            if n_rollback > 0:
                restore = self.current_chain[len(self.current_chain) - n_rollback :]
                self.ledgerdb.push_many(restore, apply=False)
            return "failed"
        if suffix:
            self.tracer(
                self._T.ValidCandidate(len(suffix), suffix[-1].slot)
            )
        self._install(n_rollback, suffix)
        # InspectLedger (Ledger/Inspect.hs): trace ledger events of the
        # adoption — era transitions, protocol-update warnings
        from ..ledger.inspect import inspect_ledger

        for ev in inspect_ledger(
            self.ext.ledger,
            state_before.ledger_state,
            self.ledgerdb.current().ledger_state,
        ):
            self.trace(f"ledger event: {ev}")
        return "adopted"

    def _install(self, n_rollback: int, suffix: list[Block]) -> None:
        """switchTo (ChainSel.hs:703): swap the fragment, notify
        followers, run the copy/GC/snapshot background step."""
        if n_rollback:
            rollback_point = (
                self.current_chain[len(self.current_chain) - n_rollback - 1].point
                if n_rollback < len(self.current_chain)
                else self._anchor_point()
            )
            self.current_chain = self.current_chain[: len(self.current_chain) - n_rollback]
        else:
            rollback_point = None
        self.current_chain.extend(suffix)
        self._update_header_history(n_rollback, suffix)
        tip_slot = self.current_chain[-1].slot if self.current_chain else -1
        if n_rollback:
            self.tracer(
                self._T.SwitchedToAFork(n_rollback, len(suffix), tip_slot)
            )
        else:
            self.tracer(self._T.AddedToCurrentChain(len(suffix), tip_slot))
        for f in self.followers:
            f._notify_switch(n_rollback > 0, rollback_point, suffix)
        if self._background_decoupled:
            if self.runtime is not None:
                self.runtime.fire(self._chain_event)
        else:
            self._copy_and_gc()

    def close(self) -> None:
        """Clean shutdown: final ledger snapshot + index flush, so the
        next open resumes from the tip without a long replay."""
        if self.snap_dir is not None:
            self.ledgerdb.take_snapshot(self.snap_dir)
        self.immutable.flush()

    # -- background (Impl/Background.hs) -------------------------------------

    def _copy_step(self) -> int | None:
        """copyAndSnapshotRunner body: move blocks > k deep to the
        ImmutableDB, snapshot the ledger anchor on the DiskPolicy
        cadence. Returns the GC slot bound, or None if nothing moved."""
        excess = len(self.current_chain) - self.k
        if excess <= 0:
            return None
        to_copy, self.current_chain = (
            self.current_chain[:excess],
            self.current_chain[excess:],
        )
        for b in to_copy:
            self.immutable.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
        self.tracer(
            self._T.CopiedToImmutableDB(len(to_copy), to_copy[-1].slot)
        )
        self._copied_since_snapshot += len(to_copy)
        if self.snap_dir is not None and self._should_snapshot():
            self.ledgerdb.take_snapshot(self.snap_dir)
            self.tracer(self._T.TookSnapshot(self._copied_since_snapshot))
            self._copied_since_snapshot = 0
            if self.disk_policy is not None:
                self.disk_policy.snapshot_taken(self._policy_now())
        return to_copy[-1].slot + 1

    def _policy_now(self) -> float:
        """Clock source for the DiskPolicy: virtual sim time when the
        node runtime is attached, wallclock otherwise."""
        if self.runtime is not None and hasattr(self.runtime, "now"):
            return float(self.runtime.now)
        import time as _time

        return _time.monotonic()

    def _should_snapshot(self) -> bool:
        if self.disk_policy is not None:
            return self.disk_policy.should_take_snapshot(
                self._copied_since_snapshot, self._policy_now()
            )
        return self._copied_since_snapshot >= self.snapshot_interval

    def _copy_and_gc(self) -> None:
        """Synchronous-mode step: copy + immediate GC."""
        gc_slot = self._copy_step()
        if gc_slot is not None:
            self.volatile.garbage_collect(gc_slot)
            self.ledgerdb.gc_prev_applied(gc_slot)
            self.tracer(self._T.PerformedGC(gc_slot))

    # -- decoupled mode (ChainSel.hs:217-246 + Background.hs:17-38) ----------

    def start_decoupled(self, runtime) -> list:
        """Switch to decoupled mode on `runtime` (a Sim or an adapter
        with .fire(Event)); returns the runner generators for the caller
        to spawn. Must be called before any add_block_async."""
        self.runtime = runtime
        self._background_decoupled = True
        return [self.add_block_runner(), self.background_runner()]

    def add_block_async(self, block: Block) -> AddBlockPromise:
        """addBlockAsync (API.hs:134): enqueue for the add-block runner
        and return a promise. Works in BOTH modes so call sites never
        branch: synchronous mode runs chain selection inline and returns
        an already-completed promise. Callers needing the verdict do
        `if p.result is None: yield Wait(p.processed)`."""
        p = AddBlockPromise(block, Event(f"processed-{block.slot}"))
        if not self._background_decoupled:
            p.result = self.add_block(block)
            return p
        # diffusion pipelining (ChainSel.hs:949-984): a block extending
        # the current tip is announced to tentative followers as a
        # header BEFORE its (possibly slow, batched) validation
        tip = self.tip_point()
        if block.prev_hash == (tip.hash_ if tip else None):
            if any(f.include_tentative for f in self.followers):
                self.tracer(
                    self._T.SetTentativeHeader(block.slot, block.hash_)
                )
            for f in self.followers:
                f._notify_tentative(block.header, tip)
        self._blocks_to_add.append(p)
        self.tracer(self._T.AddedBlockToQueue(
            block.slot, block.hash_, len(self._blocks_to_add)
        ))
        if self.runtime is not None:
            self.runtime.fire(self._queue_event)
        return p

    def add_block_runner(self):
        """Sim task (Background.hs addBlockRunner): the single consumer
        of the add-block queue — chain selection is serialized here no
        matter how many peer tasks feed the queue."""
        while True:
            while not self._blocks_to_add:
                yield Wait(self._queue_event)
            p = self._blocks_to_add.popleft()
            self.tracer(
                self._T.PoppedBlockFromQueue(p.block.slot, p.block.hash_)
            )
            p.result = self.add_block(p.block)
            if not p.result.selected:
                if any(f._tentative_hash == p.block.hash_
                       for f in self.followers):
                    self.tracer(self._T.TrapTentativeHeader(
                        p.block.slot, p.block.hash_
                    ))
                for f in self.followers:
                    f._retract_tentative(p.block.hash_)
            yield Fire(p.processed)

    def background_runner(self, gc_delay: float = 1.0):
        """Sim task (copyAndSnapshotRunner + GcSchedule): on every chain
        change, copy mature blocks to the ImmutableDB + snapshot; GC the
        VolatileDB only `gc_delay` later, so concurrent readers of the
        copied blocks (iterators, servers) drain first — the reference's
        scheduled-GC batching (Background.hs GcSchedule)."""
        while True:
            yield Wait(self._chain_event)
            # chain changes fired while we were sleeping below are not in
            # the waiter list — re-run the copy step until it finds
            # nothing, so no adoption's excess blocks are stranded
            while True:
                gc_slot = self._copy_step()
                if gc_slot is None:
                    break
                self.tracer(self._T.ScheduledGC(gc_slot))
                yield Sleep(gc_delay)
                self.volatile.garbage_collect(gc_slot)
                self.ledgerdb.gc_prev_applied(gc_slot)
                self.tracer(self._T.PerformedGC(gc_slot))
