"""Write-once columnar sidecar plane (``NNNNN.cols``) for the ImmutableDB.

SURVEY.md §7.3 (hard part 5) predicted host decode becomes the wall at
≥10x, and the PR-12/15 rounds proved it: with the device point-ops cut
13.6x, the hot replay ceiling (~177k headers/s) is dominated by the
per-header chunk parse (headerscan offsets → ``HeaderColumns`` → span
materialization) and the per-blob integrity walk. This module kills the
parse: each chunk gets a write-once, CRC-sealed ``NNNNN.cols`` sidecar
holding the chunk's header columns ALREADY in ``protocol/views
.ViewColumns`` shape, so a warm replay builds device-ready windows
straight off disk (mmap on the real filesystem) with zero per-header
work.

Format v1 (all little-endian):

    header   magic ``OCTCOLS1`` + version + flags + n + kes_w + sgn_w
             + chunk_len + chunk_crc32 + payload_crc32 + layout digest
             (blake2b-256 of the column plan below — a layout change
             bumps the digest, so old sidecars read as stale, never as
             garbage columns)
    payload  fixed-width column blobs, one after another, in the plan's
             order: slot/prev_hash/…/ocert_sigma (the ViewColumns
             fields), header_end + body_hash (the integrity columns —
             the hot path's body-hash compare without a parse), and the
             int32 sig/kes/sgn offset+len span arrays (the variable-
             width fallback). When every row shares one KES-signature
             and signed-body width (flag ``UNIFORM`` — the common case
             on real chains between CBOR integer-width steps) the
             ``kes_sig`` and ``signed_bytes`` matrices are appended
             too and the loader never touches the chunk bytes for
             column data.

Trust contract — **never trusted past the seal**: the freshness probe
re-derives the live chunk's length + CRC32 and the payload's CRC32 on
every open and rejects on any mismatch (``stale``), on any structural
truncation (``torn``), and on a layout/version/entry-count change. A
rejected or missing sidecar costs exactly one parse: the caller falls
back to ``native_loader.extract_headers`` and — writer opens only —
rebuilds the sidecar through the PR 13 tmp+rename durability protocol
(``fs.write_atomic``). Read-only opens NEVER write a sidecar.

Chaos seams (testing/chaos.py): ``sidecar-torn@build:N`` makes the
writer bypass the atomic protocol and land a torn prefix at the final
name (the crash-consistency hole under test); ``sigkill@build:N`` kills
the process between the tmp write and the rename; ``sidecar-stale@
open:N`` forces the Nth freshness probe to report stale. All three must
never change a replay verdict — the matrix cells in tests/test_repair.py
prove fallback → rebuild → hit.

Every probe/build outcome is one ``SidecarEvent`` through the batch
tracer (``oct_sidecar_total{outcome=hit|miss|stale|rebuilt|torn}`` when
the flight recorder is installed) plus a module-level counter snapshot
(``counters()``) that profile_replay/bench bank into the round JSON.

``OCT_SIDECAR=0`` is the kill-switch: probes and writes both disabled,
the replay is byte-identical to the parse path. Read per call (like
``OCT_COLUMNAR``) so the differential tests can A/B in one process.
"""

from __future__ import annotations

import hashlib
import os
import struct
import zlib
from dataclasses import dataclass, field

import numpy as np

from .immutable import _cols_name

_ENV = "OCT_SIDECAR"

MAGIC = b"OCTCOLS1"
VERSION = 1
FLAG_UNIFORM = 1
# The builder covered a full integrity walk of the chunk it sealed
# (forge-time construction, a stream-deep replay that walked every
# blob, truncater regeneration after truncate-to-last-valid). A HIT on
# a WALKED seal lets the hot path skip the per-blob CRC sweep: the
# probe's whole-chunk CRC already proved the live bytes are the
# build-time bytes, and the build-time walk proved those bytes pass.
# Unwalked seals (a shallow replay's backfill) keep the full sweep —
# rot that predates the build would otherwise change the verdict.
FLAG_WALKED = 2

# magic, version, flags, n, kes_w, sgn_w, chunk_len, chunk_crc,
# payload_crc, layout digest
_HEADER = struct.Struct("<8sIIIIIQII32s")
HEADER_SIZE = _HEADER.size

SIDECAR_OUTCOMES = ("hit", "miss", "stale", "rebuilt", "torn")

# the column plan: name, numpy dtype, row width (elements). Payload =
# these blobs concatenated in order, then (UNIFORM only) the kes_sig
# [n, kes_w] and signed_bytes [n, sgn_w] matrices. The layout digest
# seals this plan into every sidecar header.
_FIXED_COLS = (
    ("slot", "<i8", 1),
    ("prev_hash", "u1", 32),
    ("has_prev", "u1", 1),
    ("vk_cold", "u1", 32),
    ("vrf_vk", "u1", 32),
    ("vrf_output", "u1", 64),
    ("vrf_proof", "u1", 128),
    ("vrf_proof_len", "<i8", 1),
    ("ocert_vk_hot", "u1", 32),
    ("ocert_counter", "<i8", 1),
    ("ocert_kes_period", "<i8", 1),
    ("ocert_sigma", "u1", 64),
    ("header_end", "<i8", 1),
    ("body_hash", "u1", 32),
    ("sig_off", "<i4", 1),
    ("sig_len", "<i4", 1),
    ("kes_off", "<i4", 1),
    ("kes_len", "<i4", 1),
    ("sgn_off", "<i4", 1),
    ("sgn_len", "<i4", 1),
)

_LAYOUT = "v1;" + ",".join(
    f"{name}:{dt}x{w}" for name, dt, w in _FIXED_COLS
) + ";uniform:kes_sig,signed_bytes"
LAYOUT_DIGEST = hashlib.blake2b(
    _LAYOUT.encode(), digest_size=32
).digest()

_ROW_BYTES = sum(np.dtype(dt).itemsize * w for _, dt, w in _FIXED_COLS)


def enabled() -> bool:
    """``OCT_SIDECAR`` (default 1): probe + build the columnar sidecar
    plane. =0 is the kill-switch — the replay runs the parse path
    byte-identically; read per call so tests A/B in one process."""
    return os.environ.get(_ENV, "1") != "0"


def _crc32(data) -> int:
    """CRC32 of `data` — the native PCLMULQDQ fold when the host-crypto
    library is loadable (the probe's seal check is on the replay hot
    path), ``zlib.crc32`` otherwise. Both are the same polynomial and
    bit-identical; seals written by either verify under the other."""
    from .. import native_loader

    crc = native_loader.native_crc32(data)
    if crc is None:
        crc = zlib.crc32(data) & 0xFFFFFFFF
    return crc


def sidecar_path(db_dir: str, chunk: int) -> str:
    """The one path rule for chunk `chunk`'s sidecar (octsync SYNC207
    durability root: every write to this path goes through the
    tmp+rename protocol)."""
    return os.path.join(db_dir, _cols_name(chunk))


# ---------------------------------------------------------------------------
# counters + events
# ---------------------------------------------------------------------------

_COUNTS = {k: 0 for k in SIDECAR_OUTCOMES}


def record(outcome: str, chunk: int = -1) -> None:
    """Bank one probe/build outcome: the module counter snapshot
    (profile_replay/bench round JSON) and a `SidecarEvent` through the
    batch tracer (→ ``oct_sidecar_total{outcome=}`` when the flight
    recorder is installed). Fail-soft: telemetry may never break a
    replay."""
    if outcome in _COUNTS:
        _COUNTS[outcome] += 1
    try:
        from ..protocol import batch as pbatch
        from ..utils.trace import SidecarEvent

        if pbatch.BATCH_TRACER is not None:
            pbatch.BATCH_TRACER(SidecarEvent(outcome=outcome, chunk=chunk))
    except Exception:  # noqa: BLE001 # octflow: disable=FLOW303 — the
        # outcome counter already ticked; only the tracer mirror is
        # best-effort, and sidecar verdicts never depend on telemetry
        pass


def counters() -> dict:
    """Snapshot of the per-process outcome counts."""
    return dict(_COUNTS)


def reset_counters() -> None:
    for k in _COUNTS:
        _COUNTS[k] = 0


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------


def build_bytes(hc, chunk_bytes, walked: bool = False) -> bytes | None:
    """Serialize one chunk's ``native_loader.HeaderColumns`` into a
    sealed sidecar blob, or None when the chunk cannot columnarize
    (zero entries, a non-64-byte OCert sigma, offsets past int32 —
    the parse path owns such chunks; an absent sidecar is always
    correct)."""
    n = int(hc.n)
    if n == 0:
        return None
    sig_len = np.asarray(hc.sig_len)
    if not bool((sig_len == 64).all()):
        return None  # ViewColumns requires a rectangular 64-byte sigma
    if int(hc.sgn_off.max()) + int(hc.sgn_len.max()) >= 2**31:
        return None  # span arrays are int32 by format
    from ..native_loader import _span_matrix

    buf = hc._buf_u8
    sigma = np.ascontiguousarray(_span_matrix(buf, hc.sig_off, hc.sig_len))
    uniform = (
        np.unique(np.asarray(hc.kes_len)).size == 1
        and np.unique(np.asarray(hc.sgn_len)).size == 1
    )
    kes_w = int(hc.kes_len[0]) if uniform else 0
    sgn_w = int(hc.sgn_len[0]) if uniform else 0
    cols = {
        "slot": hc.slot,
        "prev_hash": hc.prev_hash,
        "has_prev": hc.has_prev,
        "vk_cold": hc.issuer_vk,
        "vrf_vk": hc.vrf_vk,
        "vrf_output": hc.vrf_output,
        "vrf_proof": hc.vrf_proof,
        "vrf_proof_len": hc.vrf_proof_len,
        "ocert_vk_hot": hc.ocert_vk,
        "ocert_counter": hc.ocert_counter,
        "ocert_kes_period": hc.ocert_kes_period,
        "ocert_sigma": sigma,
        "header_end": hc.header_end,
        "body_hash": hc.body_hash,
        "sig_off": hc.sig_off,
        "sig_len": hc.sig_len,
        "kes_off": hc.kes_off,
        "kes_len": hc.kes_len,
        "sgn_off": hc.sgn_off,
        "sgn_len": hc.sgn_len,
    }
    parts = []
    for name, dt, w in _FIXED_COLS:
        a = np.ascontiguousarray(cols[name], dtype=np.dtype(dt))
        if a.shape != ((n,) if w == 1 else (n, w)):
            return None  # shape drift: refuse, never seal a lie
        parts.append(a.tobytes())
    flags = FLAG_WALKED if walked else 0
    if uniform:
        kes = _span_matrix(buf, hc.kes_off, hc.kes_len)
        sgn = _span_matrix(buf, hc.sgn_off, hc.sgn_len)
        if kes is None or sgn is None:
            uniform, kes_w, sgn_w = False, 0, 0
        else:
            flags |= FLAG_UNIFORM
            parts.append(np.ascontiguousarray(kes, np.uint8).tobytes())
            parts.append(np.ascontiguousarray(sgn, np.uint8).tobytes())
    payload = b"".join(parts)
    header = _HEADER.pack(
        MAGIC, VERSION, flags, n, kes_w, sgn_w,
        len(chunk_bytes), _crc32(chunk_bytes),
        _crc32(payload), LAYOUT_DIGEST,
    )
    return header + payload


def write_sidecar(fs, db_dir: str, chunk: int, blob: bytes) -> bool:
    """Land one sealed sidecar blob on disk through the PR 13
    tmp+rename durability protocol (``fs.write_atomic``). The chaos
    seam detonates HERE, where the bytes meet the disk: ``sidecar-torn``
    bypasses the protocol and leaves a torn prefix at the final name
    (the probe must reject it by seal); ``sigkill`` dies between the
    tmp write and the rename (only the durable tmp survives)."""
    from ..testing import chaos

    path = sidecar_path(db_dir, chunk)
    kind = chaos.sidecar_fault("sidecar-build", chunk=chunk)
    if kind == "sidecar-torn":
        cut = min(len(blob) - 1, max(HEADER_SIZE + 7, len(blob) // 3))
        fs.write_bytes(path, blob[:cut])
        return False
    if kind == "sigkill":
        import signal

        fs.write_bytes(path + ".tmp", blob)
        os.kill(os.getpid(), signal.SIGKILL)
    fs.write_atomic(path, blob)
    return True


def backfill(fs, db_dir: str, chunk: int, hc, chunk_bytes,
             walked: bool = False) -> bool:
    """Build + write chunk `chunk`'s sidecar from an in-hand parse
    (the first replay of an un-sidecared chunk, forge time, truncater
    regeneration). `walked` stamps FLAG_WALKED — pass True only when
    a full integrity walk of these exact bytes backs the seal. True
    when a sealed sidecar landed."""
    blob = build_bytes(hc, chunk_bytes, walked=walked)
    if blob is None:
        return False
    try:
        return write_sidecar(fs, db_dir, chunk, blob)
    except OSError:
        return False  # an unwritable sidecar is a missed optimization,
        # never an error: the parse path stays correct


def backfill_store(imm, walked: bool = False) -> int:
    """Regenerate every missing/stale sidecar of an open (writer)
    ImmutableDB — db_synthesizer forge time, db_truncater
    --to-last-valid. Chunks already carrying a fresh seal are skipped
    (write-once); chunks the native scanner cannot parse are skipped
    (the parse path owns them). `walked` stamps FLAG_WALKED on every
    seal written — the forge (bytes it just wrote) and the truncater
    (everything ≤ the validated truncation point) qualify; a bare
    writer open does not. Returns the number of sidecars written."""
    from .. import native_loader
    from .immutable import _chunk_name

    if not enabled() or native_loader.load() is None:
        return 0
    wrote = 0
    for n in imm._chunks:
        entries = imm._entries.get(n, ())
        if not entries:
            continue
        try:
            data = imm.fs.read_bytes(os.path.join(imm.path, _chunk_name(n)))
        except OSError:
            continue
        sc, outcome = load_sidecar(imm.fs, imm.path, n, data, len(entries))
        if sc is not None:
            continue  # fresh seal: write-once
        offsets = np.asarray([e.offset for e in entries], np.int64)
        try:
            hc = native_loader.extract_headers(data, offsets)
        except native_loader.MalformedBlock:
            continue
        if backfill(imm.fs, imm.path, n, hc, data, walked=walked):
            record("rebuilt", n)
            wrote += 1
    return wrote


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------


def _payload_size(n: int, kes_w: int, sgn_w: int, flags: int) -> int:
    size = n * _ROW_BYTES
    if flags & FLAG_UNIFORM:
        size += n * (kes_w + sgn_w)
    return size


def _map_bytes(fs, path: str):
    """The sidecar bytes as a buffer + keep-alive handles: mmap'd on
    the real filesystem (columns page in lazily; no copy), a plain
    read through the fs seam otherwise (MockFS tests)."""
    from ..utils.fs import RealFS

    if isinstance(fs, RealFS):
        import mmap

        try:
            with open(fs._p(path), "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError):  # vanished / zero-length file
            return b"", ()
        return memoryview(mm), (mm,)
    try:
        return fs.read_bytes(path), ()
    except OSError:
        return b"", ()


@dataclass
class SidecarColumns:
    """One loaded, seal-verified sidecar: the fixed columns by name
    (zero-copy views over the mapped file) plus — UNIFORM chunks —
    the kes_sig/signed_bytes matrices."""

    n: int
    uniform: bool
    arrays: dict
    kes_sig: np.ndarray | None = None
    signed_bytes: np.ndarray | None = None
    walked: bool = False
    _keepalive: tuple = field(default=(), repr=False)

    def pieces(self, data) -> list | None:
        """The chunk as rectangular `ViewColumns` pieces — the same
        split-at-width-steps contract as
        ``ViewColumns.pieces_from_header_columns``, but from the
        sidecar's columns instead of a parse. UNIFORM chunks are one
        piece straight off the mapped matrices; non-uniform chunks
        gather the ragged kes/sgn spans from the in-hand chunk bytes
        (the span-gather fallback — still zero parse)."""
        from ..protocol.views import ViewColumns

        a = self.arrays

        def piece(lo, hi, kes, sgn):
            return ViewColumns(
                slot=a["slot"][lo:hi],
                prev_hash=a["prev_hash"][lo:hi],
                has_prev=a["has_prev"][lo:hi],
                vk_cold=a["vk_cold"][lo:hi],
                vrf_vk=a["vrf_vk"][lo:hi],
                vrf_output=a["vrf_output"][lo:hi],
                vrf_proof=a["vrf_proof"][lo:hi],
                vrf_proof_len=a["vrf_proof_len"][lo:hi],
                ocert_vk_hot=a["ocert_vk_hot"][lo:hi],
                ocert_counter=a["ocert_counter"][lo:hi],
                ocert_kes_period=a["ocert_kes_period"][lo:hi],
                ocert_sigma=a["ocert_sigma"][lo:hi],
                kes_sig=kes,
                signed_bytes=sgn,
            )

        if self.uniform:
            return [piece(0, self.n, self.kes_sig, self.signed_bytes)]
        from ..native_loader import _span_matrix

        buf = np.frombuffer(data, np.uint8)
        kes_len = a["kes_len"].astype(np.int64)
        sgn_len = a["sgn_len"].astype(np.int64)
        kes_off = a["kes_off"].astype(np.int64)
        sgn_off = a["sgn_off"].astype(np.int64)
        widths = np.stack([kes_len, sgn_len], axis=1)
        chg = np.flatnonzero((widths[1:] != widths[:-1]).any(axis=1)) + 1
        bounds = [0, *chg.tolist(), self.n]
        out = []
        for k in range(len(bounds) - 1):
            lo, hi = bounds[k], bounds[k + 1]
            kes = _span_matrix(buf, kes_off[lo:hi], kes_len[lo:hi])
            sgn = _span_matrix(buf, sgn_off[lo:hi], sgn_len[lo:hi])
            if kes is None or sgn is None:
                return None  # cannot happen within one width run;
                # refuse rather than mis-shape
            out.append(piece(lo, hi, kes, sgn))
        return out


def load_sidecar(fs, db_dir: str, chunk: int, chunk_bytes,
                 n_entries: int) -> tuple[SidecarColumns | None, str]:
    """Probe + map chunk `chunk`'s sidecar against the LIVE chunk
    bytes. Returns ``(columns, "hit")`` only when every seal matches —
    structural truncation is ``torn``, any seal/layout/count mismatch
    is ``stale``, no file is ``miss``. The chaos seam
    (``sidecar-stale@open:N``) forces a stale verdict to prove the
    fallback path never changes a verdict."""
    from ..testing import chaos

    path = sidecar_path(db_dir, chunk)
    if chaos.sidecar_fault("sidecar-open", chunk=chunk) == "sidecar-stale":
        return None, "stale"
    if not fs.exists(path):
        return None, "miss"
    buf, keep = _map_bytes(fs, path)
    if len(buf) < HEADER_SIZE:
        return None, "torn"
    (magic, version, flags, n, kes_w, sgn_w, chunk_len, chunk_crc,
     payload_crc, digest) = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC or version != VERSION:
        return None, "torn"
    end = HEADER_SIZE + _payload_size(n, kes_w, sgn_w, flags)
    if len(buf) < end:
        return None, "torn"
    if digest != LAYOUT_DIGEST or n != n_entries:
        return None, "stale"
    if chunk_len != len(chunk_bytes) or chunk_crc != _crc32(chunk_bytes):
        return None, "stale"
    payload = buf[HEADER_SIZE:end]
    if payload_crc != _crc32(payload):
        return None, "stale"
    arrays: dict = {}
    off = HEADER_SIZE
    for name, dt, w in _FIXED_COLS:
        dtype = np.dtype(dt)
        count = n * w
        a = np.frombuffer(buf, dtype=dtype, count=count, offset=off)
        arrays[name] = a if w == 1 else a.reshape(n, w)
        off += count * dtype.itemsize
    kes = sgn = None
    if flags & FLAG_UNIFORM:
        kes = np.frombuffer(
            buf, np.uint8, count=n * kes_w, offset=off
        ).reshape(n, kes_w)
        off += n * kes_w
        sgn = np.frombuffer(
            buf, np.uint8, count=n * sgn_w, offset=off
        ).reshape(n, sgn_w)
    sc = SidecarColumns(
        n=n, uniform=bool(flags & FLAG_UNIFORM), arrays=arrays,
        kes_sig=kes, signed_bytes=sgn,
        walked=bool(flags & FLAG_WALKED), _keepalive=keep,
    )
    return sc, "hit"


# ---------------------------------------------------------------------------
# hot-path integrity (tentpole piece 3)
# ---------------------------------------------------------------------------


def integrity_batch_hook(sc: SidecarColumns):
    """``default_check_integrity_batch`` WITHOUT the parse: the
    per-header body-hash compare from the sidecar's
    ``header_end``/``body_hash`` columns via ``ops/blake2b.hash_spans``
    (one native batch call; device batch behind
    ``OCT_SIDECAR_DEVICE_HASH``). Unwalked seals run under
    ``_deep_check_fast``, which adds the native ``crc32_first_bad``
    sweep over the raw chunk bytes; WALKED seals call the hook directly
    — the probe's whole-chunk CRC stands in for the per-blob sweep the
    builder already walked. Same contract and
    same non-canonical-block arbitration as the parse-path hook, so a
    mismatch truncates at the identical point; any truncation sends the
    caller to the exact host walk (``deep_check_loaded``) anyway — the
    anomaly path stays the parse."""

    def hook(data, entries):
        from ..ops.blake2b import hash_spans
        from .open import default_check_integrity

        m = len(entries)
        starts = np.asarray(sc.arrays["header_end"][:m], np.int64)
        ends = np.asarray(
            [e.offset + e.size for e in entries], np.int64
        )
        digests = hash_spans(data, starts, ends)
        bad = (digests != sc.arrays["body_hash"][:m]).any(axis=1)
        for i in np.flatnonzero(bad):
            e = entries[int(i)]
            if not default_check_integrity(
                data[e.offset : e.offset + e.size]
            ):
                return int(i)
        return m

    return hook
