"""Disk codecs for ledger/protocol state — the snapshot payloads.

Reference: `Storage/Serialisation.hs` + the EncodeDisk/DecodeDisk
instances for `ExtLedgerState` (Ledger/Extended.hs:178-199): snapshots
serialize (ledger state, header state) where the header state embeds the
protocol's ChainDepState — the chain itself is the checkpoint for
consensus state (SURVEY.md §5.4).
"""

from __future__ import annotations

from fractions import Fraction

from ..ledger.extended import ExtLedgerState
from ..ledger.header_validation import AnnTip, HeaderState
from ..ledger.mock import MockState
from ..protocol.praos import PraosState
from ..utils import cbor


def encode_praos_state(st: PraosState):
    return [
        st.last_slot,
        sorted((k, v) for k, v in st.ocert_counters.items()),
        st.evolving_nonce,
        st.candidate_nonce,
        st.epoch_nonce,
        st.lab_nonce,
        st.last_epoch_block_nonce,
    ]


def decode_praos_state(o) -> PraosState:
    def nb(x):
        return bytes(x) if x is not None else None

    return PraosState(
        last_slot=o[0],
        ocert_counters={bytes(k): v for k, v in o[1]},
        evolving_nonce=nb(o[2]),
        candidate_nonce=nb(o[3]),
        epoch_nonce=nb(o[4]),
        lab_nonce=nb(o[5]),
        last_epoch_block_nonce=nb(o[6]),
    )


def encode_header_state(hs: HeaderState):
    tip = None if hs.tip is None else [hs.tip.slot, hs.tip.block_no, hs.tip.hash_]
    return [tip, encode_praos_state(hs.chain_dep_state)]


def decode_header_state(o) -> HeaderState:
    tip = None if o[0] is None else AnnTip(o[0][0], o[0][1], bytes(o[0][2]))
    return HeaderState(tip, decode_praos_state(o[1]))


def encode_mock_state(st: MockState):
    utxo = sorted(
        ([txid, ix, addr, amt] for (txid, ix), (addr, amt) in st.utxo.items()),
        key=lambda e: (e[0], e[1]),
    )
    return [utxo, st.tip_slot_]


def decode_mock_state(o) -> MockState:
    utxo = {(bytes(e[0]), e[1]): (bytes(e[2]), e[3]) for e in o[0]}
    return MockState(utxo, o[1])


def encode_ext_state(st: ExtLedgerState) -> bytes:
    return cbor.encode(
        [encode_mock_state(st.ledger_state), encode_header_state(st.header_state)]
    )


def decode_ext_state(data: bytes) -> ExtLedgerState:
    o = cbor.decode(data)
    return ExtLedgerState(decode_mock_state(o[0]), decode_header_state(o[1]))
