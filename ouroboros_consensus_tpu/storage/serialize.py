"""Disk codecs for ledger/protocol state — the snapshot payloads.

Reference: `Storage/Serialisation.hs` + the EncodeDisk/DecodeDisk
instances for `ExtLedgerState` (Ledger/Extended.hs:178-199): snapshots
serialize (ledger state, header state) where the header state embeds the
protocol's ChainDepState — the chain itself is the checkpoint for
consensus state (SURVEY.md §5.4).
"""

from __future__ import annotations

from fractions import Fraction

from ..ledger.extended import ExtLedgerState
from ..ledger.header_validation import AnnTip, HeaderState
from ..ledger.mock import MockState
from ..protocol.praos import PraosState
from ..utils import cbor


def encode_praos_state(st: PraosState):
    return [
        st.last_slot,
        sorted((k, v) for k, v in st.ocert_counters.items()),
        st.evolving_nonce,
        st.candidate_nonce,
        st.epoch_nonce,
        st.lab_nonce,
        st.last_epoch_block_nonce,
    ]


def decode_praos_state(o) -> PraosState:
    def nb(x):
        return bytes(x) if x is not None else None

    return PraosState(
        last_slot=o[0],
        ocert_counters={bytes(k): v for k, v in o[1]},
        evolving_nonce=nb(o[2]),
        candidate_nonce=nb(o[3]),
        epoch_nonce=nb(o[4]),
        lab_nonce=nb(o[5]),
        last_epoch_block_nonce=nb(o[6]),
    )


def encode_header_state(hs: HeaderState):
    tip = None if hs.tip is None else [hs.tip.slot, hs.tip.block_no, hs.tip.hash_]
    return [tip, encode_praos_state(hs.chain_dep_state)]


def decode_header_state(o) -> HeaderState:
    tip = None if o[0] is None else AnnTip(o[0][0], o[0][1], bytes(o[0][2]))
    return HeaderState(tip, decode_praos_state(o[1]))


def encode_mock_state(st: MockState):
    utxo = sorted(
        ([txid, ix, addr, amt] for (txid, ix), (addr, amt) in st.utxo.items()),
        key=lambda e: (e[0], e[1]),
    )
    return [utxo, st.tip_slot_]


def decode_mock_state(o) -> MockState:
    utxo = {(bytes(e[0]), e[1]): (bytes(e[2]), e[3]) for e in o[0]}
    return MockState(utxo, o[1])


# -- Shelley / HFC state codecs (tagged, format v2) --------------------------
#
# The original snapshot format is the UNTAGGED 2-list
# [mock_state, header_state(praos)] — kept verbatim (golden-pinned,
# tests/golden/ext_ledger_state.hex). Any other (ledger, chain-dep)
# combination writes the 3-list ["v2", tagged_ledger, tagged_header];
# decode dispatches on the shape. This mirrors the reference's
# per-block-type EncodeDisk instances selected by the codec config
# (Storage/Serialisation.hs), collapsed to runtime type dispatch.


def _enc_fraction(f: Fraction):
    return [f.numerator, f.denominator]


def _dec_fraction(o) -> Fraction:
    return Fraction(int(o[0]), int(o[1]))


def _enc_shelley_snapshot(snap):
    return [
        sorted([c, v] for c, v in snap.stake.items()),
        sorted([c, p] for c, p in snap.delegations.items()),
        sorted(_enc_pool(p) for p in snap.pools.values()),
    ]


def _dec_shelley_snapshot(o):
    from ..ledger import shelley as sh

    return sh.Snapshot(
        stake={bytes(c): int(v) for c, v in o[0]},
        delegations={bytes(c): bytes(p) for c, p in o[1]},
        pools={p.pool_id: p for p in (_dec_pool(e) for e in o[2])},
    )


def _enc_pool(p):
    # owners keep their wire order (certificates store them as-is):
    # sorting here would break the decode(encode(st)) == st identity
    return [
        p.pool_id, p.vrf_hash, p.pledge, p.cost, _enc_fraction(p.margin),
        p.reward_cred, list(p.owners),
    ]


def _dec_pool(o):
    from ..ledger import shelley as sh

    return sh.PoolParams(
        pool_id=bytes(o[0]), vrf_hash=bytes(o[1]), pledge=int(o[2]),
        cost=int(o[3]), margin=_dec_fraction(o[4]), reward_cred=bytes(o[5]),
        owners=tuple(bytes(w) for w in o[6]),
    )


def _enc_pparams(pp):
    # field list = PParams.UPDATABLE (single source of truth: a new
    # updatable parameter extends the snapshot format automatically)
    out = []
    for f in type(pp).UPDATABLE:
        v = getattr(pp, f)
        out.append(_enc_fraction(v) if isinstance(v, Fraction) else v)
    return out


def _dec_pparams(o):
    from ..ledger import shelley as sh

    fields = sh.PParams.UPDATABLE
    if len(o) != len(fields):
        raise ValueError(
            f"pparams snapshot has {len(o)} fields, expected {len(fields)}"
        )
    kw = {}
    for f, v in zip(fields, o):
        kw[f] = _dec_fraction(v) if isinstance(v, (list, tuple)) else int(v)
    return sh.PParams(**kw)


def _enc_value(v):
    """UTxO value column: plain coin stays a bare int (golden-stable);
    a Mary multi-asset value becomes [coin, MaryValue.to_triples()] —
    the canonical asset flattening lives on MaryValue itself."""
    if not getattr(v, "assets", ()):
        return int(v)
    return [int(v), v.to_triples()]


def _dec_value(o):
    if isinstance(o, int):
        return o
    from ..ledger.mary import MaryValue

    coin, triples = o
    return MaryValue.from_triples(coin, triples)


def encode_shelley_state(st) -> list:
    utxo = sorted(
        [txid, ix, a[0], a[1], _enc_value(c)]
        for (txid, ix), (a, c) in st.utxo.items()
    )
    return [
        utxo, st.fees, st.deposits, st.treasury, st.reserves,
        sorted([c, d] for c, d in st.stake_creds.items()),
        sorted([c, v] for c, v in st.rewards.items()),
        sorted([c, p] for c, p in st.delegations.items()),
        sorted(_enc_pool(p) for p in st.pools.values()),
        sorted([p, d] for p, d in st.pool_deposits.items()),
        sorted([p, e] for p, e in st.retiring.items()),
        _enc_shelley_snapshot(st.mark),
        _enc_shelley_snapshot(st.set_),
        _enc_shelley_snapshot(st.go),
        sorted([p, n] for p, n in st.blocks_current.items()),
        sorted([p, n] for p, n in st.blocks_prev.items()),
        st.prev_fees,
        _enc_pparams(st.pparams),
        sorted(
            [p, [[k, list(v) if isinstance(v, (list, tuple)) else v]
                 for k, v in upd]]
            for p, upd in st.proposals.items()
        ),
        st.epoch,
        st.tip_slot_,
        sorted([p, c, a] for (p, c), a in st.pending_mir.items()),
    ]


def decode_shelley_state(o):
    from ..ledger import shelley as sh

    return sh.ShelleyState(
        utxo={
            (bytes(e[0]), int(e[1])): (
                (bytes(e[2]), None if e[3] is None else bytes(e[3])),
                _dec_value(e[4]),
            )
            for e in o[0]
        },
        fees=int(o[1]), deposits=int(o[2]), treasury=int(o[3]),
        reserves=int(o[4]),
        stake_creds={bytes(c): int(d) for c, d in o[5]},
        rewards={bytes(c): int(v) for c, v in o[6]},
        delegations={bytes(c): bytes(p) for c, p in o[7]},
        pools={p.pool_id: p for p in (_dec_pool(e) for e in o[8])},
        pool_deposits={bytes(p): int(d) for p, d in o[9]},
        retiring={bytes(p): int(e) for p, e in o[10]},
        mark=_dec_shelley_snapshot(o[11]),
        set_=_dec_shelley_snapshot(o[12]),
        go=_dec_shelley_snapshot(o[13]),
        blocks_current={bytes(p): int(n) for p, n in o[14]},
        blocks_prev={bytes(p): int(n) for p, n in o[15]},
        prev_fees=int(o[16]),
        pparams=_dec_pparams(o[17]),
        proposals={
            bytes(p): tuple(
                (k.decode() if isinstance(k, bytes) else k,
                 tuple(v) if isinstance(v, list) else v)
                for k, v in upd
            )
            for p, upd in o[18]
        },
        epoch=int(o[19]),
        tip_slot_=o[20],
        # round-3 snapshots predate MIR: tolerate the shorter list
        pending_mir=(
            {(int(p), bytes(c)): int(a) for p, c, a in o[21]}
            if len(o) > 21 else {}
        ),
    )


def encode_byron_state(st) -> list:
    return [
        sorted([t, ix, a, c] for (t, ix), (a, c) in st.utxo.items()),
        sorted([g, d] for g, d in st.delegation.items()),
        st.fees,
        st.tip_slot_,
    ]


def decode_byron_state(o):
    from ..ledger.byron import ByronState

    return ByronState(
        utxo={(bytes(e[0]), int(e[1])): (bytes(e[2]), int(e[3]))
              for e in o[0]},
        delegation={bytes(g): bytes(d) for g, d in o[1]},
        fees=int(o[2]),
        tip_slot_=o[3],
    )


def encode_ledger_state_tagged(st) -> list:
    """Type-dispatched ledger-state codec (v2 snapshot payloads)."""
    from ..hardfork.combinator import HFState
    from ..ledger import byron as byron_led
    from ..ledger import shelley as sh
    from ..ledger.byron_spec import DualByronState
    from ..ledger.dual import DualState

    if isinstance(st, MockState):
        return ["mock", encode_mock_state(st)]
    if isinstance(st, sh.ShelleyState):
        # Mary-era states reuse this codec: the value column widens
        # per-entry (see _enc_value), ada-only entries stay golden-stable
        return ["shelley", encode_shelley_state(st)]
    if isinstance(st, byron_led.ByronState):
        return ["byron", encode_byron_state(st)]
    if isinstance(st, DualByronState):
        spec = st.spec
        return ["dual_byron", encode_byron_state(st.impl), [
            sorted([t, ix, a, v] for (t, ix), (a, v) in spec.utxo.items()),
            sorted([g, d] for g, d in spec.delegation.items()),
            spec.fees,
        ]]
    if isinstance(st, HFState):
        return ["hf", st.era, encode_ledger_state_tagged(st.inner)]
    if isinstance(st, DualState):
        spec = st.spec
        utxo = sorted(
            [t, ix, a, v] for (t, ix), (a, v) in spec.utxo.items()
        )
        return ["dual", encode_mock_state(st.impl), [utxo, spec.tip_slot_]]
    raise TypeError(f"no snapshot codec for ledger state {type(st).__name__}")


def decode_ledger_state_tagged(o):
    from ..hardfork.combinator import HFState

    tag = o[0].decode() if isinstance(o[0], bytes) else o[0]
    if tag == "mock":
        return decode_mock_state(o[1])
    if tag == "shelley":
        return decode_shelley_state(o[1])
    if tag == "byron":
        return decode_byron_state(o[1])
    if tag == "dual_byron":
        from ..ledger.byron_spec import ByronSpecState, DualByronState

        return DualByronState(
            decode_byron_state(o[1]),
            ByronSpecState(
                utxo={(bytes(e[0]), int(e[1])): (bytes(e[2]), int(e[3]))
                      for e in o[2][0]},
                delegation={bytes(g): bytes(d) for g, d in o[2][1]},
                fees=int(o[2][2]),
            ),
        )
    if tag == "hf":
        return HFState(int(o[1]), decode_ledger_state_tagged(o[2]))
    if tag == "dual":
        from ..ledger.dual import DualState, SpecState

        spec_utxo = {
            (bytes(e[0]), int(e[1])): (bytes(e[2]), int(e[3]))
            for e in o[2][0]
        }
        return DualState(
            decode_mock_state(o[1]), SpecState(spec_utxo, o[2][1])
        )
    raise ValueError(f"unknown ledger-state tag {tag!r}")


def encode_chain_dep_tagged(st) -> list:
    from ..hardfork.combinator import HFState
    from ..protocol.instances import PBftState
    from ..protocol.tpraos import TPraosState

    if isinstance(st, TPraosState):  # subclass of PraosState: check first
        return ["tpraos", encode_praos_state(st)]
    if isinstance(st, PraosState):
        return ["praos", encode_praos_state(st)]
    if isinstance(st, PBftState):
        return ["pbft", [list(s) for s in st.signers]]
    if isinstance(st, HFState):
        return ["hf", st.era, encode_chain_dep_tagged(st.inner)]
    raise TypeError(f"no snapshot codec for chain-dep state {type(st).__name__}")


def decode_chain_dep_tagged(o):
    from ..hardfork.combinator import HFState
    from ..protocol.instances import PBftState
    from ..protocol.tpraos import TPraosState

    tag = o[0].decode() if isinstance(o[0], bytes) else o[0]
    if tag == "praos":
        return decode_praos_state(o[1])
    if tag == "tpraos":
        import dataclasses

        return TPraosState(**dataclasses.asdict(decode_praos_state(o[1])))
    if tag == "pbft":
        return PBftState(tuple((int(s), int(g)) for s, g in o[1]))
    if tag == "hf":
        return HFState(int(o[1]), decode_chain_dep_tagged(o[2]))
    raise ValueError(f"unknown chain-dep tag {tag!r}")


def _encode_header_state_tagged(hs: HeaderState):
    tip = None if hs.tip is None else [hs.tip.slot, hs.tip.block_no, hs.tip.hash_]
    return [tip, encode_chain_dep_tagged(hs.chain_dep_state)]


def _decode_header_state_tagged(o) -> HeaderState:
    tip = None if o[0] is None else AnnTip(o[0][0], o[0][1], bytes(o[0][2]))
    return HeaderState(tip, decode_chain_dep_tagged(o[1]))


def encode_ext_state(st: ExtLedgerState) -> bytes:
    if isinstance(st.ledger_state, MockState) and type(
        st.header_state.chain_dep_state
    ) is PraosState:
        # the original (golden-pinned) untagged format
        return cbor.encode(
            [encode_mock_state(st.ledger_state),
             encode_header_state(st.header_state)]
        )
    return cbor.encode([
        "v2",
        encode_ledger_state_tagged(st.ledger_state),
        _encode_header_state_tagged(st.header_state),
    ])


def decode_ext_state(data: bytes) -> ExtLedgerState:
    o = cbor.decode(data)
    tag = o[0].decode() if isinstance(o[0], bytes) else o[0]
    if tag == "v2":
        return ExtLedgerState(
            decode_ledger_state_tagged(o[1]), _decode_header_state_tagged(o[2])
        )
    return ExtLedgerState(decode_mock_state(o[0]), decode_header_state(o[1]))
