"""Storage engine: ImmutableDB / VolatileDB / LedgerDB / ChainDB + ChainSel."""

from .chaindb import AddBlockResult, ChainDB, Follower
from .immutable import ImmutableDB
from .ledgerdb import InvalidBlock, LedgerDB
from .volatile import VolatileDB
