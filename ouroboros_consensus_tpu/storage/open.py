"""ChainDB assembly: the openDB path of node startup.

Reference: `ChainDB.openDB` via `openChainDB` (diffusion Node.hs:568-580)
— open ImmutableDB (with validation policy), VolatileDB (reparse),
initialize LedgerDB from newest snapshot + replay, then initial chain
selection. The `validate_all` flag is the clean-shutdown-marker policy
(Node/Recovery.hs:24-59): absent marker ⇒ last run crashed ⇒ full
revalidation of all chunks.
"""

from __future__ import annotations

import os
from typing import Callable

from ..block.praos_block import Block
from ..ledger.extended import ExtLedger, ExtLedgerState
from .chaindb import ChainDB
from .immutable import ImmutableDB
from .ledgerdb import LedgerDB
from .volatile import VolatileDB


def default_check_integrity(raw: bytes) -> bool:
    """nodeCheckIntegrity (Node/InitStorage.hs:25 → shelley
    Ledger/Integrity.hs): parseable + body hash matches. (The KES check
    runs batched when the analyser revalidates headers.)"""
    try:
        return Block.from_bytes(raw).check_integrity()
    except Exception:
        return False


def open_chaindb(
    path: str,
    ext: ExtLedger,
    genesis: ExtLedgerState,
    k: int,
    validate_all: bool = False,
    chunk_size: int = 21600,
    trace: Callable[[str], None] = lambda s: None,
    fs=None,  # HasFS seam — a MockFS here runs the whole ChainDB in memory
    check_in_future=None,  # block.infuture.CheckInFuture | None
    decode_block=None,  # block codec seam; default = Praos Block
    check_integrity=None,  # per-block-type integrity hook
    tracer=None,  # typed ChainDB event tracer (utils.trace algebra)
) -> ChainDB:
    if check_integrity is None and validate_all:
        check_integrity = default_check_integrity
    imm = ImmutableDB(
        os.path.join(path, "immutable"),
        chunk_size=chunk_size,
        check_integrity=check_integrity if validate_all else None,
        validate_all=validate_all,
        fs=fs,
        decode_block=decode_block,
    )
    vol = VolatileDB(
        os.path.join(path, "volatile"), fs=fs, decode_block=decode_block
    )
    snap_dir = os.path.join(path, "ledger")
    ldb = LedgerDB.init_from_snapshots(
        ext, k, snap_dir, genesis, imm, trace, fs=fs, decode_block=decode_block
    )
    return ChainDB(
        ext, imm, vol, ldb, k, snap_dir=snap_dir, trace=trace,
        check_in_future=check_in_future, decode_block=decode_block,
        tracer=tracer,
    )
