"""ChainDB assembly: the openDB path of node startup.

Reference: `ChainDB.openDB` via `openChainDB` (diffusion Node.hs:568-580)
— open ImmutableDB (with validation policy), VolatileDB (reparse),
initialize LedgerDB from newest snapshot + replay, then initial chain
selection. The `validate_all` flag is the clean-shutdown-marker policy
(Node/Recovery.hs:24-59): absent marker ⇒ last run crashed ⇒ full
revalidation of all chunks.
"""

from __future__ import annotations

import os
from typing import Callable

from ..block.praos_block import Block
from ..ledger.extended import ExtLedger, ExtLedgerState
from .chaindb import ChainDB
from .immutable import ImmutableDB
from .ledgerdb import LedgerDB
from .volatile import VolatileDB

# Validation policies (Run.hs:133-143): `--only-validation` forces
# ValidateAllChunks; normal startup validates the most recent chunk and
# trusts the clean-shutdown marker for the rest. The policy threads
# through this codebase as the `validate_all` flag — these names exist
# so the protocol layer (storage/guard.py, db_analyser, db_truncater)
# can speak the reference vocabulary. db_analyser adds a third value,
# "stream": the SAME all-chunks checks folded into the replay's own
# chunk reads (one disk pass, identical truncation points).
ValidateAllChunks = True
ValidateMostRecentChunk = False


def escalate_policy(policy, opened_dirty: bool):
    """Node/Recovery.hs:24-59 — forced revalidation after a crash: a
    store that cannot prove a clean shutdown revalidates EVERYTHING.
    `ValidateMostRecentChunk` escalates to `ValidateAllChunks`;
    "stream" already runs the all-chunks checks (at read time) and
    stays stream; an explicit all-chunks policy is unchanged."""
    if opened_dirty and not policy:
        return ValidateAllChunks
    return policy


def open_repair_store(path: str, chunk_size: int = 21600, fs=None,
                      quarantine_dir: str | None = None,
                      repair: bool = True) -> ImmutableDB:
    """The deep-open recipe in ONE place: full `ValidateAllChunks` walk
    (CRC + body-hash integrity, chunk-batched fast path) with on-disk
    repair — the bundle every dirty-store escalation opens
    (db_synthesizer resume, db_truncater slot-rewind and --to-last-valid).
    ``repair=False`` is the read-only twin (--dry-run): identical scan,
    actions computed in memory only."""
    return ImmutableDB(
        os.path.join(path, "immutable"),
        chunk_size=chunk_size,
        check_integrity=default_check_integrity,
        validate_all=True,
        check_integrity_batch=default_check_integrity_batch,
        repair=repair,
        quarantine_dir=quarantine_dir,
        fs=fs,
    )


def default_check_integrity(raw: bytes) -> bool:
    """nodeCheckIntegrity (Node/InitStorage.hs:25 → shelley
    Ledger/Integrity.hs): parseable + body hash matches. (The KES check
    runs batched when the analyser revalidates headers.)"""
    try:
        return Block.from_bytes(raw).check_integrity()
    except Exception:  # octflow: disable=FLOW303 — fail-closed IS the
        # verdict here: nodeCheckIntegrity treats any parse/hash failure
        # as not-intact; the open-with-repair scan owns what follows
        return False


def default_check_integrity_batch(data, entries):
    """Chunk-wide twin of default_check_integrity: native columnar
    header parse + blake2b over each block's WIRE txs span (the codec
    writes canonical CBOR, so the span IS cbor.encode(txs); a mismatch
    is arbitrated by the per-block Python check so a non-canonical but
    internally consistent block is not wrongly truncated). Returns the
    index of the first bad block, len(entries) if all pass, or None
    when the native scanner is unavailable (caller falls back to the
    per-block loop). The per-block Python hook costs ~80 us/block of
    decode; this path is ~2 us/block."""
    import hashlib

    import numpy as np

    from .. import native_loader

    if native_loader.load() is None:
        return None
    offsets = np.asarray([e.offset for e in entries], np.int64)
    limit = len(entries)
    try:
        cols = native_loader.extract_headers(data, offsets)
    except native_loader.MalformedBlock as exc:
        # blocks before the malformed one parsed clean, but they must
        # STILL pass the body-hash check — a written-corrupt block
        # earlier in the chunk truncates earlier (per-blob loop order)
        limit = exc.index
        if limit == 0:
            return 0
        cols = native_loader.extract_headers(data, offsets[:limit])
    for i in range(limit):
        e = entries[i]
        span = data[int(cols.header_end[i]) : e.offset + e.size]
        if (
            hashlib.blake2b(span, digest_size=32).digest()
            != cols.body_hash[i].tobytes()
        ):
            if not default_check_integrity(data[e.offset : e.offset + e.size]):
                return i
    return limit


def open_chaindb(
    path: str,
    ext: ExtLedger,
    genesis: ExtLedgerState,
    k: int,
    validate_all: bool = False,
    chunk_size: int = 21600,
    trace: Callable[[str], None] = lambda s: None,
    fs=None,  # HasFS seam — a MockFS here runs the whole ChainDB in memory
    check_in_future=None,  # block.infuture.CheckInFuture | None
    decode_block=None,  # block codec seam; default = Praos Block
    check_integrity=None,  # per-block-type integrity hook
    tracer=None,  # typed ChainDB event tracer (utils.trace algebra)
) -> ChainDB:
    check_integrity_batch = None
    if check_integrity is None and validate_all:
        check_integrity = default_check_integrity
        if decode_block is None:
            # the batched twin only parses the default Praos layout
            check_integrity_batch = default_check_integrity_batch
    imm = ImmutableDB(
        os.path.join(path, "immutable"),
        chunk_size=chunk_size,
        check_integrity=check_integrity if validate_all else None,
        validate_all=validate_all,
        fs=fs,
        decode_block=decode_block,
        check_integrity_batch=check_integrity_batch if validate_all else None,
    )
    vol = VolatileDB(
        os.path.join(path, "volatile"), fs=fs, decode_block=decode_block
    )
    snap_dir = os.path.join(path, "ledger")
    ldb = LedgerDB.init_from_snapshots(
        ext, k, snap_dir, genesis, imm, trace, fs=fs, decode_block=decode_block
    )
    return ChainDB(
        ext, imm, vol, ldb, k, snap_dir=snap_dir, trace=trace,
        check_in_future=check_in_future, decode_block=decode_block,
        tracer=tracer,
    )
