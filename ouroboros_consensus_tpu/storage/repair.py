"""The durable-store repair plane: quarantine + repair forensics.

Reference: ImmutableDB/VolatileDB startup validation *truncates
corrupted tails on disk* (ImmutableDB/Impl/Validation.hs:67) — repair
is a first-class subsystem, not a side effect. This module gives every
on-disk repair the ImmutableDB takes (or, read-only, WOULD take) a
durable story:

  * **Quarantine, never delete** — snipped chunk tails, dropped chunk
    files and swept orphan indices are MOVED into
    ``<immutable>/quarantine/`` before the live file mutates. A repair
    that turns out to be wrong (a bad integrity hook, a bug in the
    scanner) loses nothing; an operator can inspect or restore the
    bytes.
  * **Every action a first-class event** — `note_repair` fans one
    repair action into the warmup forensics (`WARMUP.note_repair` →
    round JSON + run ledger) and a `RepairEvent` through the batch
    tracer (→ ``oct_repair_total{action=}`` when the flight recorder
    is installed). Dry-run scans emit ``applied=False`` rows and are
    never counted into the metric.

Action vocabulary (the ``oct_repair_total{action=}`` labels):

    truncate-chunk        a chunk's corrupted tail was cut on disk
                          (CRC / body-hash / reparse first-bad point)
    rebuild-index         a secondary index was reconstructed from
                          chunk bytes (missing / corrupt / lagging)
    drop-chunk            a wholly corrupt chunk (or a chunk stranded
                          past a truncation gap) was removed
    sweep-orphan-index    an index file without a chunk was removed
    sweep-orphan-sidecar  a columnar sidecar (or a sidecar tmp
                          stranded by a crash mid-build) without a
                          live chunk was removed (storage/sidecar.py)
    dirty-open-escalated  a missing clean-shutdown marker escalated
                          the validation policy to all-chunks
                          (storage/guard.py; the open itself)
"""

from __future__ import annotations

import os

REPAIR_ACTIONS = (
    "truncate-chunk",
    "rebuild-index",
    "drop-chunk",
    "sweep-orphan-index",
    "sweep-orphan-sidecar",
    "dirty-open-escalated",
)

QUARANTINE_DIR = "quarantine"


class QuarantineError(Exception):
    """The quarantine copy could not be made durable (ENOSPC, an
    unwritable quarantine dir). The repair REFUSES rather than
    proceed: destroying bytes it promised to keep would break the
    quarantine-never-delete guarantee exactly when disk pressure —
    the condition under which stores corrupt — makes restores likely.
    Classified REFUSE by `node/exit.triage`, never absorbed by the
    recovery ladder."""


def note_repair(action: str, chunk: int = -1, kept: int = 0,
                dropped: int = 0, bytes_quarantined: int = 0,
                applied: bool = True, detail: str = "") -> dict:
    """Bank one repair action everywhere at once: the warmup report
    (always-on forensics — round JSON + run ledger) and the batch
    tracer (`RepairEvent` → ``oct_repair_total{action=}`` when the
    flight recorder is installed). Returns the row for callers that
    accumulate a per-open repair report. Fail-soft: forensics may
    never break a store open."""
    row = {
        "action": action,
        "chunk": chunk,
        "kept": kept,
        "dropped": dropped,
        "bytes_quarantined": bytes_quarantined,
        "applied": applied,
        "detail": detail[:200],
    }
    try:
        from ..obs.warmup import WARMUP

        WARMUP.note_repair(action=action, chunk=chunk, kept=kept,
                           dropped=dropped,
                           bytes_quarantined=bytes_quarantined,
                           applied=applied, detail=detail)
    except Exception:  # noqa: BLE001 # octflow: disable=FLOW303 — the
        # repair row is already built; dropping the best-effort warmup
        # mirror fabricates no verdict
        pass
    try:
        from ..protocol import batch as pbatch
        from ..utils.trace import RepairEvent

        if pbatch.BATCH_TRACER is not None:
            pbatch.BATCH_TRACER(RepairEvent(
                action=action, chunk=chunk, blocks_kept=kept,
                blocks_dropped=dropped,
                bytes_quarantined=bytes_quarantined,
                applied=applied, detail=detail[:200],
            ))
    except Exception:  # noqa: BLE001 # octflow: disable=FLOW303 — the
        # tracer mirror of the same row: best-effort telemetry, no
        # verdict depends on it
        pass
    return row


def count_actions(rows, applied_only: bool = True) -> dict:
    """``{action: count}`` over repair rows — the one aggregation
    behind db_analyser's applied-repair counts and db_truncater's
    report (``applied_only=False``: a dry-run report counts its
    would-repair rows too). scripts/perf_report.py carries a local
    twin (it is deliberately stdlib-only); keep the filter rules in
    sync."""
    counts: dict = {}
    for row in rows or ():
        if not isinstance(row, dict):
            continue
        if applied_only and not row.get("applied", True):
            continue
        a = row.get("action", "?")
        counts[a] = counts.get(a, 0) + 1
    return counts


class Quarantine:
    """Holds snipped bytes under ``<store>/quarantine/`` instead of
    deleting them. Names collide across repeated repairs of the same
    chunk, so a numeric suffix keeps every generation."""

    def __init__(self, store_path: str, fs, directory: str | None = None):
        self.fs = fs
        self.path = (directory if directory is not None
                     else os.path.join(store_path, QUARANTINE_DIR))
        self._made = False

    def _fresh_target(self, name: str) -> str:
        """Lazy-mkdir the quarantine dir and pick a collision-free
        target path (numeric suffix keeps every generation)."""
        if not self._made:
            self.fs.makedirs(self.path)
            self._made = True
        target = os.path.join(self.path, name)
        suffix = 0
        while self.fs.exists(target):
            suffix += 1
            target = os.path.join(self.path, f"{name}.{suffix}")
        return target

    def store(self, name: str, data: bytes) -> int:
        """Write `data` under a fresh quarantine name; returns the byte
        count banked (0 on empty data). A write failure raises
        `QuarantineError` — callers MUST quarantine before they mutate,
        so the failed copy aborts the repair instead of turning it into
        the deletion this module exists to prevent."""
        if not data:
            return 0
        try:
            self.fs.write_bytes(self._fresh_target(name), data)
            return len(data)
        except OSError as exc:
            raise QuarantineError(
                f"cannot quarantine {name!r} under {self.path}: {exc}"
            ) from exc

    def store_file(self, name: str, src_path: str) -> int:
        """MOVE a whole live file into quarantine (atomic rename —
        O(1), no bytes through memory; the drop/sweep path, where the
        original leaves the store anyway). Same collision-suffix and
        refusal semantics as `store`."""
        try:
            size = self.fs.getsize(src_path)
            self.fs.replace(src_path, self._fresh_target(name))
            return size
        except OSError as exc:
            raise QuarantineError(
                f"cannot quarantine {name!r} under {self.path}: {exc}"
            ) from exc
