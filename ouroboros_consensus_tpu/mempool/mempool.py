"""Mempool: a transaction pool kept consistent with the ledger.

Reference: `Ouroboros.Consensus.Mempool` (Mempool/API.hs:102 — `addTx`,
`removeTxs`, `syncWithLedger`, `getSnapshot`, `getSnapshotFor`;
capacity = 2 × max block size by default; FIFO order with monotonically
increasing ticket numbers, TxSeq.hs:83).

Design notes vs the reference:
  * The reference's `TxSeq` is a strict FingerTree for O(log n) splits
    at a ticket number; here a plain list + dict index gives the same
    API (snapshot_after) with O(n) worst case — fine for the pool sizes
    the capacity bound admits. The batch path that matters for TPU is
    `get_snapshot_for`, which revalidates the whole pool against a new
    ledger state in one pass.
  * Validation state is maintained incrementally: the mempool caches
    the UTxO view after applying the pool's txs, so `add_tx` validates
    against the cached view in O(tx) (Mempool/Impl/Common.hs
    `InternalState` analog: `isLedgerState` + `isTxs`).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Sequence

from ..ledger.mock import InvalidTx, LedgerError


@dataclass(frozen=True)
class TxTicket:
    """TxSeq.hs:55 — a tx with its ticket number and byte size."""

    tx: bytes
    number: int
    size: int


@dataclass(frozen=True)
class MempoolSnapshot:
    """MempoolSnapshot (Mempool/API.hs:338): an immutable view."""

    txs: tuple[TxTicket, ...]
    ledger_slot: int | None
    last_ticket: int

    def tx_bytes(self) -> tuple[bytes, ...]:
        return tuple(t.tx for t in self.txs)

    def after(self, ticket: int) -> tuple[TxTicket, ...]:
        """snapshotTxsAfter: txs with ticket number > `ticket` (the
        TxSubmission server's incremental read)."""
        return tuple(t for t in self.txs if t.number > ticket)


class MempoolFull(Exception):
    """Mempool capacity (in bytes) would be exceeded (TxLimits)."""


class Mempool:
    """The pool. Thread-safe; all mutation under one lock (the
    reference gets atomicity from STM — Mempool/Impl/Common.hs)."""

    def __init__(
        self,
        ledger,
        get_ledger_state: Callable[[], object],
        capacity_bytes: int | None = None,
        max_block_bytes: int = 65536,
        trace: Callable[[str], None] = lambda s: None,
    ):
        """`get_ledger_state` returns the current (state, slot) anchor —
        the ChainDB's current ledger in the full node assembly."""
        self._ledger = ledger
        self._get_ledger_state = get_ledger_state
        self.capacity = (
            capacity_bytes if capacity_bytes is not None else 2 * max_block_bytes
        )
        self._trace = trace
        self._lock = threading.Lock()
        self._txs: list[TxTicket] = []
        self._size_bytes = 0
        self._next_ticket = 1
        self._anchor_state = None
        self._anchor_slot: int | None = None
        self._cached_utxo: dict | None = None
        self._sync_locked()

    # -- internal ----------------------------------------------------------

    def _validation_view(self, state, slot):
        """The scratch state the per-tx rules fold over: the ledger's
        `mempool_view` when it has one (the Shelley TxView — full
        UTXOW/DELEGS/POOL scratch), else a plain UTxO dict (mock
        ledgers). Both are consumed solely through `apply_tx`."""
        mk = getattr(self._ledger, "mempool_view", None)
        if mk is not None:
            return mk(state, slot if slot is not None else 0)
        return dict(state.utxo)

    def _sync_locked(self) -> list[TxTicket]:
        """Revalidate the pool against the current ledger anchor
        (syncWithLedger, Mempool/API.hs:191). Returns dropped tickets."""
        state, slot = self._get_ledger_state()
        self._anchor_state = state
        self._anchor_slot = slot
        utxo = self._validation_view(state, slot)
        kept: list[TxTicket] = []
        dropped: list[TxTicket] = []
        for t in self._txs:
            try:
                utxo = self._ledger.apply_tx(utxo, t.tx)
                kept.append(t)
            except LedgerError:
                dropped.append(t)
        self._txs = kept
        self._size_bytes = sum(t.size for t in kept)
        self._cached_utxo = utxo
        if dropped:
            self._trace(f"mempool: dropped {len(dropped)} txs on sync")
        return dropped

    def _size_locked(self) -> int:
        return self._size_bytes

    # -- API (Mempool/API.hs:102) -----------------------------------------

    def add_tx(self, tx: bytes) -> TxTicket:
        """addTx: validate against the pool-extended ledger view, FIFO.

        Raises InvalidTx (ledger rejection) or MempoolFull (capacity).
        """
        with self._lock:
            if self._size_locked() + len(tx) > self.capacity:
                raise MempoolFull(len(tx), self.capacity)
            # validates and, on success, extends the cached view
            # in place — apply_tx is atomic-on-failure, so no defensive
            # copy (the reference folds the same way; a per-tx copy of
            # the whole UTxO made bulk adds O(n^2))
            self._cached_utxo = self._ledger.apply_tx(self._cached_utxo, tx)
            t = TxTicket(tx, self._next_ticket, len(tx))
            self._next_ticket += 1
            self._txs.append(t)
            self._size_bytes += t.size
            return t

    def try_add_txs(self, txs: Sequence[bytes]) -> tuple[list[TxTicket], list[bytes]]:
        """Bulk add; returns (accepted, rejected)."""
        ok, bad = [], []
        for tx in txs:
            try:
                ok.append(self.add_tx(tx))
            except (InvalidTx, MempoolFull):
                bad.append(tx)
        return ok, bad

    def remove_txs(self, tx_ids: Sequence[bytes]) -> None:
        """removeTxs (Mempool/API.hs:174): drop by tx id, then
        revalidate the remainder (a removed tx may have fed a later one)."""
        from ..ledger.mock import tx_id as mk_id

        ids = set(tx_ids)
        with self._lock:
            self._txs = [t for t in self._txs if mk_id(t.tx) not in ids]
            self._sync_locked()  # recomputes _size_bytes from the kept set

    def sync_with_ledger(self) -> list[TxTicket]:
        """syncWithLedger: called by the node when the chain advances."""
        with self._lock:
            return self._sync_locked()

    def get_snapshot(self) -> MempoolSnapshot:
        """getSnapshot: view at the current anchor."""
        with self._lock:
            return MempoolSnapshot(
                tuple(self._txs), self._anchor_slot, self._next_ticket - 1
            )

    def get_snapshot_for(self, state, slot: int, max_bytes: int | None = None) -> MempoolSnapshot:
        """getSnapshotFor (Mempool/API.hs:203): revalidate against a
        GIVEN ticked ledger state (the forge path: NodeKernel.hs:348-375)
        without mutating the pool; optionally cap to a block's budget."""
        with self._lock:
            txs = list(self._txs)
        utxo = self._validation_view(state, slot)
        kept: list[TxTicket] = []
        used = 0
        for t in txs:
            if max_bytes is not None and used + t.size > max_bytes:
                break
            try:
                utxo = self._ledger.apply_tx(utxo, t.tx)
            except LedgerError:
                continue
            kept.append(t)
            used += t.size
        return MempoolSnapshot(tuple(kept), slot, self._next_ticket - 1)
