"""Transaction pool (reference: Ouroboros.Consensus.Mempool)."""

from .mempool import Mempool, MempoolFull, MempoolSnapshot, TxTicket

__all__ = ["Mempool", "MempoolFull", "MempoolSnapshot", "TxTicket"]
