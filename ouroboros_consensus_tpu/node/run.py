"""Node start-up assembly: locks, markers, crash recovery, DB open.

Reference: `ouroboros-consensus-diffusion` `Node.hs:272-580` (`run` /
`runWith` / `stdWithCheckedDB` / `openChainDB`) and the failure-handling
modules `Node/{DbLock,DbMarker,Recovery,Exit}.hs`:

  * DB lock — one process per DB directory (DbLock.hs).
  * DB marker — a magic file binding the directory to a network id so a
    mainnet node can't open a testnet DB (DbMarker.hs).
  * clean-shutdown marker — present while a node runs; found on start ⇒
    the previous run crashed ⇒ open with full validation
    (Recovery.hs:24-59).
  * exit triage — map exceptions to exit reasons (Exit.hs:63).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from enum import Enum
from typing import Callable

from ..ledger.extended import ExtLedger, ExtLedgerState
from ..storage.open import open_chaindb
from ..utils.fs import REAL_FS
from .kernel import NodeKernel, SlotClock

DB_LOCK = "lock"
DB_MARKER = "protocolMagicId"
CLEAN_SHUTDOWN = "clean"  # reference: absence of the marker = crashed


class DbLocked(Exception):
    """Another process holds the DB (DbLock.hs DbLocked)."""


class DbMarkerMismatch(Exception):
    """DB belongs to a different network (DbMarker.hs)."""


class ExitReason(Enum):
    """Node/Exit.hs:63 ExitReason — process exit triage."""

    SUCCESS = 0
    GENERIC = 1
    CONFIG_ERROR = 2
    DB_CORRUPTION = 3
    NETWORK_ERROR = 4


def to_exit_reason(exc: BaseException) -> ExitReason:
    """toExitReason (Node/Exit.hs:100)."""
    from ..storage.immutable import ImmutableDBError

    if isinstance(exc, (DbLocked, DbMarkerMismatch)):
        return ExitReason.CONFIG_ERROR
    if isinstance(exc, ImmutableDBError):
        return ExitReason.DB_CORRUPTION
    if isinstance(exc, (ConnectionError, OSError)):
        return ExitReason.NETWORK_ERROR
    return ExitReason.GENERIC


class DbLockFile:
    """Single-process guard (DbLock.hs, 2s timeout): flock on the real
    filesystem; on a mock FS, the MockFS advisory-lock registry — which
    MockFS.crash clears, mirroring flock's release-on-process-death."""

    def __init__(self, db_path: str, fs=None):
        self.path = os.path.join(db_path, DB_LOCK)
        self.fs = fs  # None = real FS (flock)
        self._fd: int | None = None
        self._held = False

    def acquire(self) -> None:
        if self.fs is not None:
            if self.path in self.fs.advisory_locks:
                raise DbLocked(self.path)
            self.fs.advisory_locks.add(self.path)
            self._held = True
            return
        import fcntl

        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            raise DbLocked(self.path) from e
        self._fd = fd
        self._held = True

    def release(self) -> None:
        if not self._held:
            return  # never release a lock another instance holds
        self._held = False
        if self.fs is not None:
            self.fs.advisory_locks.discard(self.path)
            return
        if self._fd is not None:
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def check_db_marker(db_path: str, network_magic: int, fs=None) -> None:
    """checkDbMarker (DbMarker.hs): create on first open, verify after."""
    fs = fs if fs is not None else REAL_FS
    p = os.path.join(db_path, DB_MARKER)
    if fs.exists(p):
        found = int(fs.read_bytes(p).decode().strip())
        if found != network_magic:
            raise DbMarkerMismatch(f"DB is for magic {found}, node runs {network_magic}")
    else:
        fs.makedirs(db_path)
        # durable: the marker must survive a crash (write_atomic fsyncs)
        fs.write_atomic(p, str(network_magic).encode())


def was_clean_shutdown(db_path: str, fs=None) -> bool:
    """Recovery.hs:24: the clean marker is REMOVED while running and
    written back on orderly shutdown; missing at start (after a first
    run) ⇒ crash ⇒ revalidate everything."""
    fs = fs if fs is not None else REAL_FS
    return fs.exists(os.path.join(db_path, CLEAN_SHUTDOWN))


@dataclass
class RunningNode:
    kernel: NodeKernel
    db_path: str
    lock: DbLockFile
    crashed_last_run: bool
    fs: object = None

    def shutdown(self) -> None:
        """Orderly stop: final snapshot, clean marker, release lock."""
        fs = self.fs if self.fs is not None else REAL_FS
        self.kernel.chain_db.close()
        fs.write_atomic(
            os.path.join(self.db_path, CLEAN_SHUTDOWN), b"clean\n"
        )
        self.lock.release()


def start_node(
    name: str,
    db_path: str,
    ext: ExtLedger,
    genesis: ExtLedgerState,
    k: int,
    *,
    network_magic: int = 764824073,
    pool=None,
    clock: SlotClock | None = None,
    chunk_size: int = 21600,
    trace: Callable[[str], None] = lambda s: None,
    fs=None,  # HasFS seam: a MockFS runs the WHOLE node in memory
) -> RunningNode:
    """run/runWith condensed (Node.hs:272): lock → marker → recovery
    check → ChainDB open (validation policy per recovery) → NodeKernel.

    The caller wires mini-protocol tasks and the forging loop into a
    sim/asyncio runtime (testing/threadnet.py is the reference user).
    """
    vfs = fs if fs is not None else REAL_FS
    lock = DbLockFile(db_path, fs=fs)
    lock.acquire()
    try:
        check_db_marker(db_path, network_magic, fs=fs)
        first_run = not vfs.exists(os.path.join(db_path, "immutable"))
        crashed = not first_run and not was_clean_shutdown(db_path, fs=fs)
        clean_marker = os.path.join(db_path, CLEAN_SHUTDOWN)
        if vfs.exists(clean_marker):
            vfs.remove(clean_marker)  # running now: a crash leaves no marker
        if crashed:
            trace(f"{name}: unclean shutdown detected -> full revalidation")
        db = open_chaindb(
            db_path, ext, genesis, k,
            validate_all=crashed,
            chunk_size=chunk_size,
            trace=trace,
            fs=fs,
        )
        kernel = NodeKernel(
            name, db, ext.protocol, ext.ledger, pool=pool, clock=clock, trace=trace
        )
        return RunningNode(kernel, db_path, lock, crashed, fs=fs)
    except BaseException:
        lock.release()
        raise
