"""Node start-up assembly: locks, markers, crash recovery, DB open.

Reference: `ouroboros-consensus-diffusion` `Node.hs:272-580` (`run` /
`runWith` / `stdWithCheckedDB` / `openChainDB`) and the failure-handling
modules `Node/{DbLock,DbMarker,Recovery,Exit}.hs`:

  * DB lock — one process per DB directory (DbLock.hs).
  * DB marker — a magic file binding the directory to a network id so a
    mainnet node can't open a testnet DB (DbMarker.hs).
  * clean-shutdown marker — present while a node runs; found on start ⇒
    the previous run crashed ⇒ open with full validation
    (Recovery.hs:24-59).
  * exit triage — map exceptions to exit reasons (Exit.hs:63).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from enum import Enum
from typing import Callable

from ..ledger.extended import ExtLedger, ExtLedgerState
from ..storage.open import open_chaindb
from .kernel import NodeKernel, SlotClock

DB_LOCK = "lock"
DB_MARKER = "protocolMagicId"
CLEAN_SHUTDOWN = "clean"  # reference: absence of the marker = crashed


class DbLocked(Exception):
    """Another process holds the DB (DbLock.hs DbLocked)."""


class DbMarkerMismatch(Exception):
    """DB belongs to a different network (DbMarker.hs)."""


class ExitReason(Enum):
    """Node/Exit.hs:63 ExitReason — process exit triage."""

    SUCCESS = 0
    GENERIC = 1
    CONFIG_ERROR = 2
    DB_CORRUPTION = 3
    NETWORK_ERROR = 4


def to_exit_reason(exc: BaseException) -> ExitReason:
    """toExitReason (Node/Exit.hs:100)."""
    from ..storage.immutable import ImmutableDBError

    if isinstance(exc, (DbLocked, DbMarkerMismatch)):
        return ExitReason.CONFIG_ERROR
    if isinstance(exc, ImmutableDBError):
        return ExitReason.DB_CORRUPTION
    if isinstance(exc, (ConnectionError, OSError)):
        return ExitReason.NETWORK_ERROR
    return ExitReason.GENERIC


class DbLockFile:
    """flock-based single-process guard (DbLock.hs, 2s timeout)."""

    def __init__(self, db_path: str):
        self.path = os.path.join(db_path, DB_LOCK)
        self._fd: int | None = None

    def acquire(self) -> None:
        import fcntl

        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            raise DbLocked(self.path) from e
        self._fd = fd

    def release(self) -> None:
        if self._fd is not None:
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def check_db_marker(db_path: str, network_magic: int) -> None:
    """checkDbMarker (DbMarker.hs): create on first open, verify after."""
    p = os.path.join(db_path, DB_MARKER)
    if os.path.exists(p):
        with open(p) as f:
            found = int(f.read().strip())
        if found != network_magic:
            raise DbMarkerMismatch(f"DB is for magic {found}, node runs {network_magic}")
    else:
        os.makedirs(db_path, exist_ok=True)
        with open(p, "w") as f:
            f.write(str(network_magic))


def was_clean_shutdown(db_path: str) -> bool:
    """Recovery.hs:24: the clean marker is REMOVED while running and
    written back on orderly shutdown; missing at start (after a first
    run) ⇒ crash ⇒ revalidate everything."""
    return os.path.exists(os.path.join(db_path, CLEAN_SHUTDOWN))


def _has_db(db_path: str) -> bool:
    return os.path.exists(os.path.join(db_path, DB_MARKER))


@dataclass
class RunningNode:
    kernel: NodeKernel
    db_path: str
    lock: DbLockFile
    crashed_last_run: bool

    def shutdown(self) -> None:
        """Orderly stop: final snapshot, clean marker, release lock."""
        self.kernel.chain_db.close()
        with open(os.path.join(self.db_path, CLEAN_SHUTDOWN), "w") as f:
            f.write("clean\n")
        self.lock.release()


def start_node(
    name: str,
    db_path: str,
    ext: ExtLedger,
    genesis: ExtLedgerState,
    k: int,
    *,
    network_magic: int = 764824073,
    pool=None,
    clock: SlotClock | None = None,
    chunk_size: int = 21600,
    trace: Callable[[str], None] = lambda s: None,
) -> RunningNode:
    """run/runWith condensed (Node.hs:272): lock → marker → recovery
    check → ChainDB open (validation policy per recovery) → NodeKernel.

    The caller wires mini-protocol tasks and the forging loop into a
    sim/asyncio runtime (testing/threadnet.py is the reference user).
    """
    lock = DbLockFile(db_path)
    lock.acquire()
    try:
        check_db_marker(db_path, network_magic)
        first_run = not os.path.exists(os.path.join(db_path, "immutable"))
        crashed = not first_run and not was_clean_shutdown(db_path)
        clean_marker = os.path.join(db_path, CLEAN_SHUTDOWN)
        if os.path.exists(clean_marker):
            os.remove(clean_marker)  # running now: a crash leaves no marker
        if crashed:
            trace(f"{name}: unclean shutdown detected -> full revalidation")
        db = open_chaindb(
            db_path, ext, genesis, k,
            validate_all=crashed,
            chunk_size=chunk_size,
            trace=trace,
        )
        kernel = NodeKernel(
            name, db, ext.protocol, ext.ledger, pool=pool, clock=clock, trace=trace
        )
        return RunningNode(kernel, db_path, lock, crashed)
    except BaseException:
        lock.release()
        raise
