"""Node start-up assembly: locks, markers, crash recovery, DB open.

Reference: `ouroboros-consensus-diffusion` `Node.hs:272-580` (`run` /
`runWith` / `stdWithCheckedDB` / `openChainDB`) and the failure-handling
modules `Node/{DbLock,DbMarker,Recovery,Exit}.hs`:

  * DB lock — one process per DB directory (DbLock.hs).
  * DB marker — a magic file binding the directory to a network id so a
    mainnet node can't open a testnet DB (DbMarker.hs).
  * clean-shutdown marker — present while a node runs; found on start ⇒
    the previous run crashed ⇒ open with full validation
    (Recovery.hs:24-59).
  * exit triage — map exceptions to exit reasons (Exit.hs:63).

The lock/marker/clean-shutdown primitives live in `storage/guard.py`
(re-exported here) so the tools plane — `db_analyser.revalidate`,
`db_synthesizer`, the bench children — speaks the SAME crash protocol
as node startup; the exit triage (and the repair-vs-refuse-vs-recover
disposition map the RecoverySupervisor consults) lives in
`node/exit.py`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from ..ledger.extended import ExtLedger, ExtLedgerState
from ..storage.guard import (  # noqa: F401 — the node-facing re-exports
    CLEAN_SHUTDOWN, DB_LOCK, DB_MARKER, DEFAULT_MAGIC, DbLocked,
    DbLockFile, DbMarkerMismatch, StoreGuard, check_db_marker,
    was_clean_shutdown, write_clean_marker,
)
from ..storage.open import open_chaindb
from .exit import ExitReason, to_exit_reason  # noqa: F401 — re-export
from .kernel import NodeKernel, SlotClock


@dataclass
class RunningNode:
    kernel: NodeKernel
    db_path: str
    guard: StoreGuard
    crashed_last_run: bool
    fs: object = None

    @property
    def lock(self) -> DbLockFile:
        return self.guard.lock

    def shutdown(self) -> None:
        """Orderly stop: final snapshot, then the guard's close
        protocol — clean marker (through the chaos ``marker`` seam; a
        partial-rename fault leaves the store dirty, exactly the crash
        shape), lock released even if the marker write dies, a second
        shutdown a no-op. ONE implementation (StoreGuard.close) shared
        with the tools plane."""
        self.kernel.chain_db.close()
        self.guard.close(clean=True)


def start_node(
    name: str,
    db_path: str,
    ext: ExtLedger,
    genesis: ExtLedgerState,
    k: int,
    *,
    network_magic: int = DEFAULT_MAGIC,
    pool=None,
    clock: SlotClock | None = None,
    chunk_size: int = 21600,
    trace: Callable[[str], None] = lambda s: None,
    fs=None,  # HasFS seam: a MockFS runs the WHOLE node in memory
) -> RunningNode:
    """run/runWith condensed (Node.hs:272): lock → marker → recovery
    check → ChainDB open (validation policy per recovery) → NodeKernel.

    The caller wires mini-protocol tasks and the forging loop into a
    sim/asyncio runtime (testing/threadnet.py is the reference user).
    """
    # the bundled protocol (storage/guard.py): lock → marker → dirty
    # check → clear clean marker (writer mode) — ONE implementation
    # shared with the tools plane, so a protocol fix lands everywhere
    guard = StoreGuard(db_path, network_magic=network_magic, fs=fs,
                       writer=True)
    guard.open()
    try:
        crashed = guard.opened_dirty
        if crashed:
            trace(f"{name}: unclean shutdown detected -> full revalidation")
        db = open_chaindb(
            db_path, ext, genesis, k,
            validate_all=crashed,
            chunk_size=chunk_size,
            trace=trace,
            fs=fs,
        )
        kernel = NodeKernel(
            name, db, ext.protocol, ext.ledger, pool=pool, clock=clock, trace=trace
        )
        return RunningNode(kernel, db_path, guard, crashed, fs=fs)
    except BaseException:
        guard.close(clean=False)  # crash shape: store stays dirty
        raise
